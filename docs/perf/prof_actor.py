import jax; jax.config.update("jax_platforms", "cpu")
import sys, cProfile, pstats, io
sys.path.insert(0, "/root/repo")
import numpy as np
from r2d2_tpu.actor import VectorActor, make_act_fn
from r2d2_tpu.config import pong_config
from r2d2_tpu.envs.fake import FakeAtariEnv
from r2d2_tpu.models.network import create_network, init_params
from r2d2_tpu.utils.math import epsilon_ladder
from r2d2_tpu.utils.store import ParamStore

cfg = pong_config(game_name="Fake", num_actors=64)
net = create_network(cfg, 4)
params = init_params(cfg, net, jax.random.PRNGKey(0))
store = ParamStore(params)
act_fn = make_act_fn(cfg, net)
envs = [FakeAtariEnv(obs_shape=cfg.stored_obs_shape, action_dim=4, seed=i, episode_len=500) for i in range(64)]
eps = [epsilon_ladder(i, 64) for i in range(64)]
actor = VectorActor(cfg, envs, eps, act_fn, store, sink=lambda b,p,r: None, rng=np.random.default_rng(1))
actor.run(max_steps=20)  # warmup

pr = cProfile.Profile()
pr.enable()
actor.run(max_steps=200)
pr.disable()
s = io.StringIO()
pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(22)
print("\n".join(s.getvalue().splitlines()[:40]))
