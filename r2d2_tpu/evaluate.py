"""Offline evaluation: checkpoint sweep → learning curve.

Capability-parity with the reference evaluator (test.py:14-88): walk
checkpoints in save order, run ``eval_episodes`` rollouts at
``test_epsilon`` per checkpoint, report env frames (env_steps ×
frameskip — test.py:36), wall-clock time, and mean reward; optionally plot
the reward-vs-frames / reward-vs-time curves.

TPU-first redesign: the reference forks an ``mp.Pool`` of 5 CPU rollout
workers (test.py:18,33); here the episodes run **in lockstep as one
batched jitted act** (the same inference-server pattern as the actor
fleet), so evaluation uses one device efficiently instead of 5 forked
torch processes.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from r2d2_tpu.actor import make_act_fn
from r2d2_tpu.checkpoint import Checkpointer
from r2d2_tpu.config import Config
from r2d2_tpu.models.network import R2D2Network, create_network


def run_episodes(cfg: Config, net: R2D2Network, params: Any,
                 envs: List[Any], epsilon: Optional[float] = None,
                 rng: Optional[np.random.Generator] = None,
                 act_fn=None) -> List[float]:
    """One episode per env, stepped in lockstep with batched inference
    (the batched analogue of test.py:60-81).  Returns per-env returns."""
    epsilon = cfg.test_epsilon if epsilon is None else epsilon
    rng = rng or np.random.default_rng(cfg.seed)
    act_fn = act_fn or make_act_fn(cfg, net)
    N = len(envs)
    action_dim = envs[0].action_space.n

    obs = np.zeros((N, *cfg.stored_obs_shape), np.uint8)
    last_action = np.zeros((N, action_dim), np.float32)
    last_reward = np.zeros(N, np.float32)
    hidden = np.zeros((N, 2, cfg.lstm_layers, cfg.hidden_dim), np.float32)
    for i, env in enumerate(envs):
        o, _ = env.reset()
        obs[i] = np.asarray(o, np.uint8)

    returns = np.zeros(N, np.float64)
    done = np.zeros(N, bool)
    steps = 0
    while not done.all() and steps < cfg.max_episode_steps:
        q, new_hidden = act_fn(params, obs, last_action, last_reward, hidden)
        q = np.asarray(q)
        new_hidden = np.asarray(new_hidden)
        explore = rng.random(N) < epsilon
        actions = np.where(explore, rng.integers(action_dim, size=N),
                           q.argmax(axis=1))
        for i, env in enumerate(envs):
            if done[i]:
                continue
            a = int(actions[i])
            o, r, terminated, truncated, _ = env.step(a)
            obs[i] = np.asarray(o, np.uint8)
            last_action[i] = 0.0
            last_action[i, a] = 1.0
            last_reward[i] = r
            hidden[i] = new_hidden[i]
            returns[i] += r
            done[i] = bool(terminated or truncated)
        steps += 1
    return [float(x) for x in returns]


def evaluate_params(cfg: Config, net: R2D2Network, params: Any,
                    env_factory: Callable[[Config, int], Any],
                    episodes: Optional[int] = None,
                    epsilon: Optional[float] = None,
                    seed: int = 0, act_fn=None) -> float:
    """Mean return over ``episodes`` rollouts (test.py:33,38 semantics)."""
    episodes = episodes or cfg.eval_episodes
    envs = [env_factory(cfg, seed + i) for i in range(episodes)]
    returns = run_episodes(cfg, net, params, envs, epsilon=epsilon,
                           rng=np.random.default_rng(seed), act_fn=act_fn)
    return float(np.mean(returns))


def evaluate_sweep(cfg: Config,
                   checkpoint_dir: str,
                   env_factory: Callable[[Config, int], Any],
                   episodes: Optional[int] = None,
                   out_json: Optional[str] = None,
                   out_plot: Optional[str] = None,
                   action_dim: Optional[int] = None,
                   follow: bool = False,
                   follow_timeout: Any = "default",
                   poll_interval: float = 2.0,
                   stop: Optional[Callable[[], bool]] = None
                   ) -> List[Dict[str, float]]:
    """Walk every checkpoint in save order (test.py:26-40) and produce the
    learning curve: one record per checkpoint with training step, env
    frames (env_steps × frameskip), wall-clock minutes, mean reward.

    With ``follow=True`` the sweep trails a concurrent training run the way
    the reference evaluator does (test.py:26-27's poll-the-next-file walk):
    after draining the checkpoints already on disk it keeps polling for new
    ones, evaluating each as it appears, and exits when ``stop()`` reports
    training finished (with one final drain) or when no new checkpoint has
    appeared for ``follow_timeout`` seconds.  The timeout defaults to 600
    when no ``stop`` callback is given (a bare follow call must not poll
    forever) and to ``None`` — poll until ``stop()`` — when one is: a
    live training run with a slow checkpoint cadence must not be cut
    short.  ``out_json`` is rewritten
    after every record in follow mode so the curve file trails the run too.
    A step is only picked up once its metadata sidecar exists — process 0
    writes that after the orbax save, so its presence marks a finished save.
    """
    if follow_timeout == "default":
        follow_timeout = 600.0 if stop is None else None
    ckpt = Checkpointer(checkpoint_dir)
    if action_dim is None:
        action_dim = env_factory(cfg, 0).action_space.n
    net = create_network(cfg, action_dim)
    act_fn = make_act_fn(cfg, net)

    def _eval_step(step: int) -> Dict[str, float]:
        from r2d2_tpu.checkpoint import check_arch_compat

        check_arch_compat(cfg, ckpt.peek_meta(step))
        raw, meta = ckpt.restore(None, step=step)
        params = raw["params"]  # the flax variables dict of the online net
        mean_reward = evaluate_params(cfg, net, params, env_factory,
                                      episodes=episodes, seed=cfg.seed,
                                      act_fn=act_fn)
        return dict(
            step=step,
            env_frames=int(meta.get("env_steps", 0)) * cfg.frameskip,
            minutes=float(meta.get("minutes", 0.0)),
            mean_reward=mean_reward,
        )

    def _write(curve: List[Dict[str, float]]) -> None:
        if out_json:
            # atomic replace: follow mode invites concurrent readers, who
            # must never observe a truncated file mid-rewrite
            tmp = f"{out_json}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(curve, f, indent=2)
            os.replace(tmp, out_json)

    curve: List[Dict[str, float]] = []
    seen: set = set()
    last_new = time.monotonic()
    while True:
        stopping = stop() if (follow and stop is not None) else False
        fresh = [s for s in ckpt.steps() if s not in seen]
        if follow:
            # gate on the sidecar: a step dir may be visible mid-save
            fresh = [s for s in fresh if ckpt.has_meta(s)]
        for step in fresh:
            seen.add(step)
            curve.append(_eval_step(step))
            if follow:
                _write(curve)
        if fresh:
            last_new = time.monotonic()
        if not follow:
            break
        if stopping and not fresh:
            break  # training done and the final drain found nothing new
        if (follow_timeout is not None and not fresh
                and time.monotonic() - last_new > follow_timeout):
            break
        if not fresh:
            time.sleep(poll_interval)

    _write(curve)
    if out_plot:
        _plot_curve(cfg, curve, out_plot)
    return curve


def _plot_curve(cfg: Config, curve: List[Dict[str, float]],
                path: str) -> None:
    """Reward-vs-frames and reward-vs-hours dual plot (test.py:42-58).
    Matplotlib is optional in this image; silently skips if missing."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return
    frames = [c["env_frames"] for c in curve]
    hours = [c["minutes"] / 60.0 for c in curve]
    rewards = [c["mean_reward"] for c in curve]
    fig, axes = plt.subplots(1, 2, figsize=(12, 6))
    fig.suptitle(cfg.game_name)
    axes[0].plot(frames, rewards)
    axes[0].set_xlabel("environment frames")
    axes[0].set_ylabel("average reward")
    axes[1].plot(hours, rewards)
    axes[1].set_xlabel("wall-clock hours")
    axes[1].set_ylabel("average reward")
    fig.savefig(path)
    plt.close(fig)
