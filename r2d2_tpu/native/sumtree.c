/* Native hot loops for the prioritised-replay sum tree.
 *
 * The host side of the framework (SURVEY §2.1 #8: the reference's
 * PriorityTree, priority_tree.py:4-45) is pointer-chasing over a flat
 * binary-heap array — the wrong shape for the TPU *and* an awkward shape
 * for numpy: the vectorised Python implementation (replay/sum_tree.py)
 * spends its time in per-level `np.unique` + fancy-indexing dispatch
 * overhead rather than arithmetic.  On the training host this code shares
 * one core with actor inference and the interconnect relay, so shaving
 * the tree ops to microseconds (and releasing the GIL while they run —
 * ctypes does that for free) buys real fabric throughput.
 *
 * Layout contract (must match replay/sum_tree.py): `nodes` is the flat
 * heap, node 0 the root, children of i at 2i+1 / 2i+2, leaves start at
 * `leaf_offset = 2**(levels-1) - 1`.  All functions are exact ports of
 * the numpy arithmetic — same operation order, bit-identical results —
 * so the Python oracle tests validate both paths.
 *
 * Build: compiled on demand by r2d2_tpu/native/__init__.py (cc -O2
 * -shared -fPIC); loaded via ctypes.  No Python.h dependency.
 */

#include <stdint.h>

/* Set leaves[idxes[i]] = prios[i] (already exponentiated by the caller)
 * and repair all ancestor sums level by level.  Duplicate parents are
 * recomputed idempotently — cheaper than dedup at batch sizes ~64. */
void st_update(double *nodes, int64_t num_levels, int64_t leaf_offset,
               const int64_t *idxes, const double *prios, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        nodes[leaf_offset + idxes[i]] = prios[i];
    }
    /* walk each touched path upward; level-synchronous so a parent's
     * children are final before the parent is recomputed */
    /* small scratch on stack for typical n; fall back to in-place walking
     * of the caller's idx array is avoided to keep the API const */
    int64_t scratch[1024];
    int64_t *cur = scratch;
    if (n > 1024) {
        /* degenerate: walk one path at a time (still exact) */
        for (int64_t i = 0; i < n; ++i) {
            int64_t node = leaf_offset + idxes[i];
            while (node > 0) {
                node = (node - 1) / 2;
                nodes[node] = nodes[2 * node + 1] + nodes[2 * node + 2];
            }
        }
        return;
    }
    for (int64_t i = 0; i < n; ++i) cur[i] = leaf_offset + idxes[i];
    for (int64_t lvl = 0; lvl < num_levels - 1; ++lvl) {
        for (int64_t i = 0; i < n; ++i) {
            int64_t p = (cur[i] - 1) / 2;
            nodes[p] = nodes[2 * p + 1] + nodes[2 * p + 2];
            cur[i] = p;
        }
    }
}

/* Vectorised lock-step top-down descent: prefix-sum targets -> leaf NODE
 * ids (same arithmetic as SumTree._descend: compare against the left
 * child's mass, subtract when going right). */
void st_descend(const double *nodes, int64_t num_levels,
                const double *targets_in, int64_t n, int64_t *out_nodes) {
    for (int64_t i = 0; i < n; ++i) {
        double t = targets_in[i];
        int64_t node = 0;
        for (int64_t lvl = 0; lvl < num_levels - 1; ++lvl) {
            int64_t left = 2 * node + 1;
            double lm = nodes[left];
            if (t >= lm) {
                node = left + 1;
                t -= lm;
            } else {
                node = left;
            }
        }
        out_nodes[i] = node;
    }
}

/* Total mass of leaves strictly before leaf_idx (root-walk, exact port of
 * SumTree.prefix_mass). */
double st_prefix_mass(const double *nodes, int64_t leaf_offset,
                      int64_t leaf_idx) {
    int64_t node = leaf_idx + leaf_offset;
    double mass = 0.0;
    while (node > 0) {
        int64_t parent = (node - 1) / 2;
        if (node == 2 * parent + 2) {
            mass += nodes[2 * parent + 1];
        }
        node = parent;
    }
    return mass;
}
