"""On-demand-built native (C) fast paths for host-side hot loops.

The TPU compute path is JAX/XLA/Pallas; this package is the native side of
the *runtime* — currently the prioritised-replay sum tree's update/descent
loops (replay/sum_tree.py), which run under the replay-buffer lock on a
host core shared with actor inference.  The C implementations are exact
ports (bit-identical arithmetic, see native/sumtree.c) and release the
GIL for the duration of the call.

Build model: ``cc -O2 -shared -fPIC`` at first use into a cache directory
(``$R2D2_NATIVE_CACHE`` or ``~/.cache/r2d2_tpu``), keyed by a content
hash of the source; loaded via ctypes (no Python.h / pybind dependency).  Anything failing —
no compiler, read-only cache, load error — degrades silently to the numpy
implementations (``R2D2_NO_NATIVE=1`` forces that).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "sumtree.c")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> str:
    return (os.environ.get("R2D2_NATIVE_CACHE")
            or os.path.join(os.path.expanduser("~"), ".cache", "r2d2_tpu"))


def _build() -> Optional[str]:
    try:
        with open(_SRC, "rb") as f:
            import hashlib

            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    # content-keyed cache: mtimes collide across wheel builds
    # (SOURCE_DATE_EPOCH) and same-second edits, silently loading stale code
    # uid-scoped filename: users sharing a cache dir never collide, and a
    # pre-planted file under our exact name still fails the ownership
    # check below and is rebuilt over (never silently loaded)
    out = os.path.join(_cache_dir(), f"sumtree_{digest}_u{os.getuid()}.so")
    if os.path.exists(out):
        # only trust a cached .so we own: a writable shared cache path must
        # not let a pre-planted file be ctypes-loaded into the process
        try:
            if os.stat(out).st_uid == os.getuid():
                return out
        except OSError:
            return None
        # foreign-owned file under our name: fall through and rebuild over
        # it (os.replace) instead of permanently disabling the fast path
    cc = os.environ.get("CC", "cc")
    try:
        os.makedirs(_cache_dir(), mode=0o700, exist_ok=True)
        tmp = out + f".tmp{os.getpid()}"
        subprocess.run([cc, "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
                       check=True, capture_output=True, timeout=60)
        os.replace(tmp, out)  # atomic: concurrent builders race benignly
        return out
    except Exception:
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("R2D2_NO_NATIVE"):
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        i64, f64p, i64p = (ctypes.c_int64, ctypes.POINTER(ctypes.c_double),
                           ctypes.POINTER(ctypes.c_int64))
        lib.st_update.argtypes = [f64p, i64, i64, i64p, f64p, i64]
        lib.st_update.restype = None
        lib.st_descend.argtypes = [f64p, i64, f64p, i64, i64p]
        lib.st_descend.restype = None
        lib.st_prefix_mass.argtypes = [f64p, i64, i64]
        lib.st_prefix_mass.restype = ctypes.c_double
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def _ptr_f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _ptr_i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def st_update(nodes: np.ndarray, num_levels: int, leaf_offset: int,
              idxes: np.ndarray, prios: np.ndarray) -> bool:
    """Native leaf-set + ancestor repair.  Returns False when the native
    library is unavailable (caller falls back to numpy).  ``idxes`` must
    be int64 and ``prios`` float64, both contiguous."""
    lib = _load()
    if lib is None:
        return False
    idxes = np.ascontiguousarray(idxes, dtype=np.int64)
    prios = np.ascontiguousarray(prios, dtype=np.float64)
    leaf_count = nodes.size - leaf_offset
    if idxes.size and (int(idxes.min()) < 0 or int(idxes.max()) >= leaf_count):
        # match the numpy path's IndexError instead of letting the C loop
        # write outside the nodes heap
        raise IndexError(
            f"sum-tree leaf index out of range [0, {leaf_count}): "
            f"[{int(idxes.min())}, {int(idxes.max())}]")
    lib.st_update(_ptr_f64(nodes), num_levels, leaf_offset,
                  _ptr_i64(idxes), _ptr_f64(prios), idxes.size)
    return True


def st_descend(nodes: np.ndarray, num_levels: int,
               targets: np.ndarray) -> Optional[np.ndarray]:
    """Native top-down descent; returns leaf node ids, or None when the
    native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    targets = np.ascontiguousarray(targets, dtype=np.float64)
    out = np.empty(targets.size, dtype=np.int64)
    lib.st_descend(_ptr_f64(nodes), num_levels, _ptr_f64(targets),
                   targets.size, _ptr_i64(out))
    return out


def st_prefix_mass(nodes: np.ndarray, leaf_offset: int,
                   leaf_idx: int) -> Optional[float]:
    lib = _load()
    if lib is None:
        return None
    if not 0 <= leaf_idx <= nodes.size - leaf_offset:
        raise IndexError(f"prefix_mass leaf index {leaf_idx} out of range "
                         f"[0, {nodes.size - leaf_offset}]")
    return float(lib.st_prefix_mass(_ptr_f64(nodes), leaf_offset, leaf_idx))
