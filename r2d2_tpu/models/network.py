"""Dueling CNN+LSTM Q-network, TPU-native.

Capability-parity with the reference's ``Network`` (model.py:27-150): Nature
conv torso → LSTM over [latent ⊕ one-hot last action ⊕ last reward] → dueling
heads, with a single-step acting path and full-sequence training paths.

TPU-first redesign:
- NHWC layout (XLA's native conv layout) instead of torch NCHW.
- The LSTM is a fused cell under ``jax.lax.scan`` with the input projection
  hoisted out of the scan into one large ``(B*T, F) @ (F, 4H)`` MXU matmul;
  only the small recurrent matmul stays sequential.
- No ``pack_padded_sequence`` emulation: the unroll is static-shape over the
  full padded T; per-sample window extraction is a masked gather done by the
  learner (r2d2_tpu/learner/step.py), replacing the reference's per-sample
  Python loops (model.py:95-111,143).
- One ``unroll`` serves all three reference forward variants (model.py:65,
  81, 122): acting is a T=1 unroll; online/target training Q are gathers at
  different time indices of the same unrolled Q sequence.
- ``impala`` torso (deep residual CNN) and stacked LSTM layers cover the
  scaled-model benchmark config; ``mlp`` torso supports fast tests.
- Optional rematerialisation of the scan body for long unrolls.

Recurrent state wire format everywhere: ``(B, 2, layers, H)`` float32 where
axis 1 is (h, c).
"""
from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from r2d2_tpu.config import Config


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


class NatureTorso(nn.Module):
    """Nature-DQN conv stack (reference geometry: model.py:39-49), NHWC.

    With ``s2d_input`` the input arrives space-to-depth folded from the
    host pipeline ((21, 21, 16) for an 84×84 frame — cfg.stored_obs_shape)
    and conv1 is the equivalent 2×2 stride-1 conv: the same linear map as
    8×8 stride-4 on raw pixels (every 8×8/4 window is a 2×2 window of 4×4
    blocks; kernel entries permuted — see
    tests/test_network.py::test_space_to_depth_equals_direct_conv1), but
    with a 16-deep MXU-shaped contraction instead of the pathological
    1-channel one, and no device-side relayout (a device transform of the
    (B·T, 84, 84, 1) batch costs more than conv1 itself).
    """
    out_dim: int
    compute_dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    s2d_input: bool = False

    @nn.compact
    def __call__(self, x):  # x: (B, H, W, C) in [0, 1]
        kw = dict(padding="VALID", dtype=self.compute_dtype,
                  param_dtype=self.param_dtype)
        if self.s2d_input:
            x = nn.relu(nn.Conv(32, (2, 2), strides=(1, 1), **kw)(x))
        else:
            x = nn.relu(nn.Conv(32, (8, 8), strides=(4, 4), **kw)(x))
        x = nn.relu(nn.Conv(64, (4, 4), strides=(2, 2), **kw)(x))
        x = nn.relu(nn.Conv(64, (3, 3), strides=(1, 1), **kw)(x))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.out_dim, dtype=self.compute_dtype,
                             param_dtype=self.param_dtype)(x))
        return x


class ImpalaTorso(nn.Module):
    """IMPALA deep residual CNN (BASELINE configs[4] scaled-model stress)."""
    out_dim: int
    compute_dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    channels: Tuple[int, ...] = (16, 32, 32)
    blocks_per_stage: int = 2

    @nn.compact
    def __call__(self, x):
        kw = dict(padding="SAME", dtype=self.compute_dtype,
                  param_dtype=self.param_dtype)
        for ch in self.channels:
            x = nn.Conv(ch, (3, 3), **kw)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            for _ in range(self.blocks_per_stage):
                skip = x
                x = nn.Conv(ch, (3, 3), **kw)(nn.relu(x))
                x = nn.Conv(ch, (3, 3), **kw)(nn.relu(x))
                x = x + skip
        x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.out_dim, dtype=self.compute_dtype,
                             param_dtype=self.param_dtype)(x))
        return x


class MlpTorso(nn.Module):
    """Small flatten+dense torso for tests and non-image observations."""
    out_dim: int
    compute_dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.out_dim, dtype=self.compute_dtype,
                             param_dtype=self.param_dtype)(x))
        return x


class LSTMLayer(nn.Module):
    """Fused LSTM layer unrolled over time.

    The input projection for all T steps is one large matmul (MXU-friendly);
    only the (B, H) @ (H, 4H) recurrent matmul is sequential.  Gate
    nonlinearities and cell state stay float32 for stability; matmuls run in
    ``compute_dtype``.  Gate order (i, f, g, o); forget-gate bias init 1.

    Two recurrence implementations behind the same parameters:
    - ``impl="scan"``: ``jax.lax.scan`` — portable, works on CPU and under
      GSPMD meshes, differentiable.  The ONLY training recurrence.
    - ``impl="pallas"``: the fused inference kernel (ops/lstm.py) — the
      whole unroll is one TPU program with the recurrent weights and h/c
      held in VMEM across steps.  No-grad paths only (acting/eval):
      the backward kernel was retired in r5 after the round-4 v5e
      measurement (B=64 T=85 H=512 bf16) put fused fwd+bwd at 0.96x
      scan; the inference edge (1.07x, residual-free) is what remains.
      Differentiating this branch raises at trace time — the learner
      builds its loss networks with ``lstm_impl="scan"``
      (learner/step.py:make_train_step).
    """
    hidden_dim: int
    compute_dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    impl: str = "scan"
    interpret: bool = False

    @nn.compact
    def __call__(self, xs, h0, c0):
        # xs: (B, T, F); h0, c0: (B, H)
        B, T, F = xs.shape
        H = self.hidden_dim
        cd = self.compute_dtype

        wi = self.param("wi", nn.initializers.xavier_uniform(), (F, 4 * H),
                        self.param_dtype)
        wh = self.param("wh", nn.initializers.orthogonal(), (H, 4 * H),
                        self.param_dtype)

        def bias_init(key, shape, dtype):
            b = jnp.zeros(shape, dtype)
            return b.at[H:2 * H].set(1.0)  # forget-gate bias

        b = self.param("b", bias_init, (4 * H,), self.param_dtype)

        x_proj = (xs.astype(cd) @ wi.astype(cd)).astype(jnp.float32) + b

        def run_pallas(xp, wh, h0, c0):
            from r2d2_tpu.ops.lstm import lstm_unroll_pallas

            hs_tm, h, c = lstm_unroll_pallas(
                xp.swapaxes(0, 1), wh, h0, c0,
                compute_dtype=cd, interpret=self.interpret)
            return hs_tm.swapaxes(0, 1), h, c

        def run_scan(xp, wh, h0, c0):
            def step(carry, x_t):
                h, c = carry
                gates = x_t + (h.astype(cd) @ wh.astype(cd)).astype(
                    jnp.float32)
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c_new = (jax.nn.sigmoid(f) * c
                         + jax.nn.sigmoid(i) * jnp.tanh(g))
                h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
                return (h_new, c_new), h_new

            if self.remat:
                step = jax.checkpoint(step)
            (h, c), hs = jax.lax.scan(step, (h0, c0), xp.swapaxes(0, 1))
            return hs.swapaxes(0, 1), h, c

        h0f, c0f = h0.astype(jnp.float32), c0.astype(jnp.float32)
        # The pallas branch only lowers on TPU (interpret=True is the CPU
        # test mode).  Callers that jit the network onto a non-TPU device —
        # actor/eval inference on the host CPU backend — must request a
        # scan-impl network instead (actor.make_act_fn builds that twin;
        # the two impls declare identical parameters).
        if self.impl == "pallas":
            hs, h, c = run_pallas(x_proj, wh, h0f, c0f)
        else:
            hs, h, c = run_scan(x_proj, wh, h0f, c0f)
        return hs, (h, c)


class DuelingHead(nn.Module):
    """q = V + A - mean(A) (reference: model.py:53-63, 75-77)."""
    hidden_dim: int
    action_dim: int
    compute_dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kw = dict(dtype=self.compute_dtype, param_dtype=self.param_dtype)
        adv = nn.Dense(self.action_dim, name="adv_out", **kw)(
            nn.relu(nn.Dense(self.hidden_dim, name="adv_hidden", **kw)(x)))
        val = nn.Dense(1, name="val_out", **kw)(
            nn.relu(nn.Dense(self.hidden_dim, name="val_hidden", **kw)(x)))
        q = val + adv - adv.mean(axis=-1, keepdims=True)
        return q.astype(jnp.float32)


class R2D2Network(nn.Module):
    """The full Q-network.  Two entry points:

    - ``unroll``: (obs (B,T,*obs) uint8, last_action (B,T,A), last_reward
      (B,T), hidden (B,2,layers,H)) → (q (B,T,A) f32, new hidden).
    - ``act``: single-step batched inference for actors/eval.
    """
    action_dim: int
    cfg: Config

    def setup(self):
        cfg = self.cfg
        cd, pd = _dtype(cfg.compute_dtype), _dtype(cfg.param_dtype)
        torso_cls = {"nature": NatureTorso, "impala": ImpalaTorso,
                     "mlp": MlpTorso}[cfg.torso]
        torso_kw = dict(out_dim=cfg.hidden_dim, compute_dtype=cd,
                        param_dtype=pd)
        if cfg.torso == "nature":
            torso_kw["s2d_input"] = cfg.obs_space_to_depth
        self.torso = torso_cls(**torso_kw)
        impl = resolve_lstm_impl(cfg)
        self.lstm_layers_ = [
            LSTMLayer(hidden_dim=cfg.hidden_dim, compute_dtype=cd,
                      param_dtype=pd, remat=cfg.remat, impl=impl,
                      interpret=cfg.pallas_interpret,
                      name=f"lstm_{i}")
            for i in range(cfg.lstm_layers)
        ]
        self.head = DuelingHead(hidden_dim=cfg.hidden_dim,
                                action_dim=self.action_dim,
                                compute_dtype=cd, param_dtype=pd)

    def _lstm_stack(self, xs, hidden):
        # xs: (B, T, F); hidden: (B, 2, layers, H)
        new_h, new_c = [], []
        for i, layer in enumerate(self.lstm_layers_):
            xs, (h, c) = layer(xs, hidden[:, 0, i], hidden[:, 1, i])
            new_h.append(h)
            new_c.append(c)
        new_hidden = jnp.stack([jnp.stack(new_h, 1), jnp.stack(new_c, 1)], 1)
        return xs, new_hidden

    def _features(self, obs, last_action, last_reward):
        # obs: (B, T, *obs_shape) uint8 → latent (B, T, hidden)
        B, T = obs.shape[:2]
        cd = _dtype(self.cfg.compute_dtype)
        x = obs.reshape(B * T, *obs.shape[2:]).astype(cd) / 255.0
        latent = self.torso(x).reshape(B, T, -1)
        return jnp.concatenate(
            [latent.astype(jnp.float32), last_action.astype(jnp.float32),
             last_reward[..., None].astype(jnp.float32)], axis=-1)

    def unroll(self, obs, last_action, last_reward, hidden):
        feats = self._features(obs, last_action, last_reward)
        outs, new_hidden = self._lstm_stack(feats, hidden)
        B, T = outs.shape[:2]
        q = self.head(outs.reshape(B * T, -1)).reshape(B, T, -1)
        return q, new_hidden

    def act(self, obs, last_action, last_reward, hidden):
        # obs: (B, *obs_shape) uint8 — a T=1 unroll (reference model.py:65-79)
        q, new_hidden = self.unroll(obs[:, None], last_action[:, None],
                                    last_reward[:, None], hidden)
        return q[:, 0], new_hidden


def resolve_lstm_impl(cfg: Config) -> str:
    """``auto`` → the fused Pallas inference kernel on TPU, ``scan``
    elsewhere.  The resolved impl governs NO-GRAD unrolls only — any grad
    path must use a ``lstm_impl="scan"`` network (the learner builds its
    loss networks that way, learner/step.py:make_train_step; the Pallas
    kernel has no backward since r5 and raises under differentiation).

    All implementations declare identical parameters, so checkpoints and
    param pytrees are interchangeable between them (e.g. act with pallas
    on TPU, evaluate with scan on CPU).
    """
    if cfg.lstm_impl != "auto":
        return cfg.lstm_impl
    return "pallas" if jax.default_backend() == "tpu" else "scan"


def create_network(cfg: Config, action_dim: int) -> R2D2Network:
    return R2D2Network(action_dim=action_dim, cfg=cfg)


def init_params(cfg: Config, net: R2D2Network, key: jax.Array):
    B, T = 1, 2
    obs = jnp.zeros((B, T, *cfg.stored_obs_shape), jnp.uint8)
    la = jnp.zeros((B, T, net.action_dim), jnp.float32)
    lr = jnp.zeros((B, T), jnp.float32)
    hidden = jnp.zeros((B, 2, cfg.lstm_layers, cfg.hidden_dim), jnp.float32)
    return net.init(key, obs, la, lr, hidden, method=R2D2Network.unroll)


def zero_hidden(cfg: Config, batch: int) -> jnp.ndarray:
    return jnp.zeros((batch, 2, cfg.lstm_layers, cfg.hidden_dim), jnp.float32)
