from r2d2_tpu.models.network import (
    R2D2Network,
    NatureTorso,
    ImpalaTorso,
    MlpTorso,
    LSTMLayer,
    DuelingHead,
    create_network,
    init_params,
    zero_hidden,
)
