"""Persistent XLA compilation cache.

The flagship train step / super-step are multi-second XLA compiles (first
compile ~20-40 s through a tunneled chip); every bench run, battery run,
and restarted trainer pays them again.  JAX ships a persistent on-disk
compilation cache — this module turns it on with sane defaults, keyed off
``R2D2_COMPILE_CACHE`` (path; ``0`` disables).  The reference has no
analogue (torch eager); for a jitted framework it is the difference
between a ~40 s and a ~1 s warm start on repeat runs.

Call :func:`enable` before the first jit compilation (cli/train/bench
entry points do).  Safe to call multiple times; silently no-ops when the
config knob is absent (very old jax) or the dir cannot be created.
"""
from __future__ import annotations

import os

_DEFAULT = os.path.join(os.path.expanduser("~"), ".cache", "r2d2_tpu",
                        "xla_cache")


def _configured_platform() -> str:
    """The platform this process is configured for, WITHOUT initialising
    the backend (jax.devices() on a tunneled accelerator can hang)."""
    try:
        import jax

        plat = getattr(jax.config, "jax_platforms", None)
        if plat:
            return plat.split(",")[0]
    except Exception:
        pass
    env = os.environ.get("JAX_PLATFORMS", "")
    return env.split(",")[0] if env else ""


def enable(path: str | None = None, force: bool = False) -> str | None:
    """Enable the persistent compilation cache; returns the dir or None.

    **Not by default on explicitly CPU-pinned processes**: measured on
    this image, XLA:CPU persists AOT results keyed loosely enough that a
    cached executable can reload under *mismatched host machine
    features* ("could lead to execution errors such as SIGILL") and run
    pathologically slowly — a cached actor act-fn degraded ~30x and
    starved the actor plane.  CPU compiles are cheap anyway; the cache's
    purpose is the multi-second TPU train-step/super-step compiles.  An
    unset platform (JAX auto-detection — typical real TPU hosts) keeps
    the cache; an explicit ``path`` arg, a non-off ``R2D2_COMPILE_CACHE``
    value, or ``force=True`` opts in even on CPU.

    Precedence: explicit ``path`` arg > ``R2D2_COMPILE_CACHE`` env (``0``/
    ``off`` disables) > default under ``~/.cache/r2d2_tpu``.  Entries
    below 1 s compile time are not persisted (cache stays small).
    """
    env = os.environ.get("R2D2_COMPILE_CACHE", "")
    env_is_path = bool(env) and env.lower() not in ("0", "off", "false")
    # Gate applies only to *explicitly* CPU-configured processes (tests,
    # the CPU tools — all of which pin jax_platforms="cpu" before calling
    # this) with no explicit opt-in.  An unset platform means JAX
    # auto-detection, typical on real TPU hosts — those must keep the
    # cache.  A caller-provided path or a non-off R2D2_COMPILE_CACHE
    # value is an explicit opt-in and bypasses the gate.
    if (not force and path is None and not env_is_path
            and _configured_platform() == "cpu"):
        return None
    if path is None and env.lower() in ("0", "off", "false"):
        return None  # env off-switch governs only when no explicit path
    cache_dir = path or env or _DEFAULT
    try:
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception:
        return None  # old jax / read-only home: run uncached
