"""Persistent XLA compilation cache.

The flagship train step / super-step are multi-second XLA compiles (first
compile ~20-40 s through a tunneled chip); every bench run, battery run,
and restarted trainer pays them again.  JAX ships a persistent on-disk
compilation cache — this module turns it on with sane defaults, keyed off
``R2D2_COMPILE_CACHE`` (path; ``0`` disables).  The reference has no
analogue (torch eager); for a jitted framework it is the difference
between a ~40 s and a ~1 s warm start on repeat runs.

Call :func:`enable` before the first jit compilation (cli/train/bench
entry points do).  Safe to call multiple times; silently no-ops when the
config knob is absent (very old jax) or the dir cannot be created.
"""
from __future__ import annotations

import os

_DEFAULT = os.path.join(os.path.expanduser("~"), ".cache", "r2d2_tpu",
                        "xla_cache")


def enable(path: str | None = None) -> str | None:
    """Enable the persistent compilation cache; returns the dir or None.

    Precedence: explicit ``path`` arg > ``R2D2_COMPILE_CACHE`` env (``0``/
    ``off`` disables) > default under ``~/.cache/r2d2_tpu``.  Entries
    below 1 s compile time are not persisted (cache stays small; only the
    multi-second train-step/super-step graphs matter).
    """
    env = os.environ.get("R2D2_COMPILE_CACHE", "")
    if path is None and env.lower() in ("0", "off", "false"):
        return None  # env off-switch governs only when no explicit path
    cache_dir = path or env or _DEFAULT
    try:
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception:
        return None  # old jax / read-only home: run uncached
