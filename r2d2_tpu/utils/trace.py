"""Tracing / profiling instrumentation (SURVEY.md §5.1).

The reference has no tracing at all — only the buffer process's 10-second
stdout stats (worker.py:89-106).  This module supplies the TPU-native hooks
the survey calls for:

- :class:`Tracer` — in-process stage timers and gauges.  Spans record
  wall-time per pipeline stage (actor inference, batch assembly, H2D
  staging, learner step, priority feedback) as exponential moving averages
  with counts AND a fixed log-bucket histogram per span (p50/p95/p99
  surfaced in ``snapshot()``, hence /statusz and the console line).  A
  ``snapshot()`` is a plain dict, cheap enough to attach to every log
  line.  Each span call site also doubles as a structured trace event
  whenever a capture window is armed (telemetry/tracing.py — the
  cross-process Perfetto timeline).
- :func:`device_profile` — a context manager around ``jax.profiler`` trace
  capture, producing a TensorBoard-loadable trace of the XLA device
  timeline for any region of the training loop.
- :class:`RetraceGuard` — compile-boundary discipline made checkable
  (Podracer, PAPERS.md): every jitted entry point wraps its Python
  function in :data:`RETRACES`.wrap(name, fn, budget), so each XLA trace
  (the Python body runs exactly once per compilation) increments a
  per-instance counter.  A function that silently retraces per step —
  shape drift, weak-type flapping, a host value captured as a tracer —
  blows its budget, and the train/serve e2e tests assert
  ``RETRACES.assert_within_budgets()`` instead of a reviewer eyeballing
  compile logs.
- :class:`TransferCounter` — :data:`HOST_TRANSFERS` counts the
  device↔host crossings of the ingest and inference-service hot loops,
  so "the serve loop fetches once per batch, not once per lane" is an
  assertable invariant rather than a hope.
- :class:`TransferGuard` — :data:`TRANSFER_GUARD` upgrades the counted
  contract to an *enforced* one: when armed, each dispatch/fetch hot
  window runs under a scoped ``jax.transfer_guard("disallow")`` so any
  device↔host crossing that is not a declared site (an explicit
  ``device_put``/``device_get``/``copy_to_host_async``, or an implicit
  fetch inside a ``HOST_TRANSFERS.allowed(...)`` span) raises instead
  of silently stalling the loop.  Disarmed (the default) every window
  is a no-op, so production call sites are unconditional.

Everything is thread-safe and allocation-light: spans cost two
``perf_counter`` calls and a lock-free float update per use, so they can
sit in the hot loop.
"""
from __future__ import annotations

import bisect
import contextlib
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

# fixed log-spaced span-duration buckets (seconds, 4 per decade from
# 10 µs to 100 s): every span shares them, so the per-update cost is one
# bisect + one int increment and the percentile read needs no samples
_SPAN_BOUNDS = tuple(10.0 ** (e / 4.0) for e in range(-20, 9))


class _Stat:
    __slots__ = ("count", "total", "ewma", "last", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.ewma = 0.0
        self.last = 0.0
        self.buckets = [0] * (len(_SPAN_BOUNDS) + 1)

    def update(self, dt: float, alpha: float) -> None:
        self.count += 1
        self.total += dt
        self.last = dt
        self.ewma = dt if self.count == 1 else (
            alpha * dt + (1.0 - alpha) * self.ewma)
        self.buckets[bisect.bisect_left(_SPAN_BOUNDS, dt)] += 1

    def percentile(self, q: float) -> float:
        """Approximate quantile from the fixed buckets: linear
        interpolation inside the bucket the rank lands in (the +Inf
        bucket answers its finite lower edge — conservative)."""
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.buckets):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = _SPAN_BOUNDS[i - 1] if i > 0 else 0.0
                hi = (_SPAN_BOUNDS[i] if i < len(_SPAN_BOUNDS)
                      else _SPAN_BOUNDS[-1])
                frac = min(1.0, max(0.0, (rank - cum) / c))
                return lo + (hi - lo) * frac
            cum += c
        return 0.0


class Tracer:
    """Stage timers + gauges for the training fabric.

    >>> tracer = Tracer()
    >>> with tracer.span("learner_step"):
    ...     ...
    >>> tracer.gauge("batch_queue", 5)
    >>> tracer.snapshot()["span.learner_step.ewma_ms"]
    """

    def __init__(self, alpha: float = 0.05, events=None):
        self._alpha = alpha
        self._spans: Dict[str, _Stat] = {}
        self._gauges: Dict[str, float] = {}
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()
        if events is None:
            # the process-wide structured event recorder
            # (telemetry/tracing.py): every span call site doubles as a
            # Chrome-trace slice whenever a capture window is armed —
            # zero extra instrumentation in the stage code
            from r2d2_tpu.telemetry.tracing import EVENTS

            events = EVENTS
        self._event_sink = events

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                stat = self._spans.get(name)
                if stat is None:
                    stat = self._spans[name] = _Stat()
                stat.update(dt, self._alpha)
            events = self._event_sink
            if events is not None and events.armed:
                # pass-through into the armed capture window; every
                # call site above passes a literal name
                events.complete(name, t0, dt)  # graftlint: disable=telemetry-discipline -- pass-through bridge; span() call sites pass literal names

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def snapshot(self) -> Dict[str, float]:
        """Flat dict: span.<name>.{ewma_ms,mean_ms,count,p50_ms,p95_ms,
        p99_ms}, gauge.<name>, counter.<name>.  The percentiles come
        from each span's fixed log-bucket histogram — visible per log
        interval in /statusz and the console line without a trace
        dump."""
        out: Dict[str, float] = {}
        with self._lock:
            for name, s in self._spans.items():
                out[f"span.{name}.ewma_ms"] = s.ewma * 1e3
                out[f"span.{name}.mean_ms"] = (s.total / s.count) * 1e3
                out[f"span.{name}.count"] = s.count
                out[f"span.{name}.p50_ms"] = s.percentile(0.50) * 1e3
                out[f"span.{name}.p95_ms"] = s.percentile(0.95) * 1e3
                out[f"span.{name}.p99_ms"] = s.percentile(0.99) * 1e3
            for name, v in self._gauges.items():
                out[f"gauge.{name}"] = v
            for name, v in self._counters.items():
                out[f"counter.{name}"] = v
        return out


class RetraceBudgetExceeded(AssertionError):
    """A jitted entry point traced more often than its declared budget."""


class _RetraceEntry:
    __slots__ = ("name", "budget", "traces")

    def __init__(self, name: str, budget: int):
        self.name = name
        self.budget = budget
        self.traces = 0


class RetraceGuard:
    """Counts XLA traces per jitted-function *instance*.

    ``wrap(name, fn, budget)`` returns a wrapper to hand to ``jax.jit``;
    because jax runs the Python body once per compilation (and never on a
    cache hit), the wrapper's call count IS the trace count.  Each wrap
    call creates a fresh entry, so two learners built in one process do
    not share a counter — the budget is "traces per compiled instance",
    which for the fabric's static-shape entry points is 1 (plus slack).

    The process-wide :data:`RETRACES` instance is what production entry
    points register with; tests that deliberately provoke retraces use a
    private ``RetraceGuard()`` so they never trip the global assertion.
    """

    def __init__(self, default_budget: int = 2):
        self.default_budget = default_budget
        self._entries: List[_RetraceEntry] = []
        self._lock = threading.Lock()

    def wrap(self, name: str, fn, budget: Optional[int] = None):
        entry = _RetraceEntry(name, self.default_budget
                              if budget is None else budget)
        with self._lock:
            self._entries.append(entry)

        def traced(*args, **kwargs):
            entry.traces += 1  # int += is GIL-atomic enough for a counter
            return fn(*args, **kwargs)

        traced.__name__ = getattr(fn, "__name__", name)
        traced.__qualname__ = traced.__name__
        traced.__wrapped__ = fn
        return traced

    def counts(self) -> Dict[str, int]:
        """name → max traces observed on any single instance."""
        out: Dict[str, int] = {}
        with self._lock:
            for e in self._entries:
                out[e.name] = max(out.get(e.name, 0), e.traces)
        return out

    def over_budget(self) -> List[Tuple[str, int, int]]:
        """(name, traces, budget) for every instance past its budget."""
        with self._lock:
            return [(e.name, e.traces, e.budget)
                    for e in self._entries if e.traces > e.budget]

    def assert_within_budgets(self) -> None:
        bad = self.over_budget()
        if bad:
            raise RetraceBudgetExceeded(
                "jitted entry points exceeded their retrace budgets: "
                + "; ".join(f"{n} traced {t}x (budget {b})"
                            for n, t, b in bad))

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


class TransferCounter:
    """Named counters for device↔host crossings on the hot loops."""

    def __init__(self):
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    @contextlib.contextmanager
    def allowed(self, name: str, n: int = 1) -> Iterator[None]:
        """A declared-transfer span: tick the counter AND open a
        ``jax.transfer_guard("allow")`` window (via the process-wide
        :data:`TRANSFER_GUARD`), so the one sanctioned fetch/put inside
        a ``disallow`` window neither trips the guard nor escapes the
        budget book-keeping.  Disarmed, this is exactly ``count()``."""
        self.count(name, n)
        with TRANSFER_GUARD.allow():
            yield

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


class TransferGuardTripped(RuntimeError):
    """An undeclared device↔host transfer inside a disallow window.

    Raised by :meth:`TransferGuard.disallow` wrapping jax's own guard
    error so call sites (and the OPERATIONS failure matrix) have one
    stable exception type with the window name attached."""


class TransferGuard:
    """Scoped ``jax.transfer_guard`` enforcement for the hot loops.

    The declared-transfer budget (one H2D per dispatch, one D2H per
    harvest — Podracer, PAPERS.md) has always been *counted* by
    :data:`HOST_TRANSFERS`; this makes JAX itself reject what the count
    would only reveal after the fact.  Each dispatch/fetch window wraps
    its body in ``disallow(where)``; the declared crossings inside run
    under ``HOST_TRANSFERS.allowed(name)`` (or are explicit
    ``device_put``/``device_get`` calls, which jax's ``disallow`` level
    permits by design — only *implicit* transfers trip it).

    Disarmed (the default) every window is a no-op with no jax import,
    so the guard costs one attribute read on production paths.  Tests
    and ``cfg.transfer_guard`` arm it; arming nests.  Arm AFTER the
    first compile of an entry point: trace-time constant materialization
    during compilation is outside the steady-state budget contract.

    jax's transfer guards are thread-local by design; ``arm`` flips a
    process-wide flag but each window only guards the thread that enters
    it — which is exactly the dispatch/harvest thread the budget is
    about.
    """

    def __init__(self):
        self._armed = 0
        self._windows: Dict[str, int] = {}
        self._trips: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def armed(self) -> bool:
        return self._armed > 0

    @contextlib.contextmanager
    def arm(self) -> Iterator[None]:
        with self._lock:
            self._armed += 1
        try:
            yield
        finally:
            with self._lock:
                self._armed -= 1

    @contextlib.contextmanager
    def disallow(self, where: str) -> Iterator[None]:
        """Enforcement window: armed, any *implicit* device↔host
        transfer inside raises :class:`TransferGuardTripped` naming the
        window.  Disarmed: free pass-through."""
        if not self.armed:
            yield
            return
        with self._lock:
            self._windows[where] = self._windows.get(where, 0) + 1
        import jax

        try:
            with jax.transfer_guard("disallow"):
                yield
        except Exception as e:  # jax raises a plain RuntimeError/ValueError
            if "transfer" not in str(e).lower():
                raise
            with self._lock:
                self._trips[where] = self._trips.get(where, 0) + 1
            raise TransferGuardTripped(
                f"undeclared device<->host transfer inside guard window "
                f"{where!r}: {e}") from e

    @contextlib.contextmanager
    def allow(self) -> Iterator[None]:
        """A sanctioned-transfer span inside a ``disallow`` window
        (normally entered via :meth:`TransferCounter.allowed`, which
        also books the crossing)."""
        if not self.armed:
            yield
            return
        import jax

        with jax.transfer_guard("allow"):
            yield

    def snapshot(self) -> Dict[str, int]:
        """``window.<name>`` = disallow windows entered while armed,
        ``trip.<name>`` = undeclared transfers caught (should be 0 —
        a non-zero trip counter is the OPERATIONS failure-matrix
        signal)."""
        with self._lock:
            out = {f"window.{k}": v for k, v in self._windows.items()}
            out.update({f"trip.{k}": v for k, v in self._trips.items()})
            return out

    def reset(self) -> None:
        with self._lock:
            self._windows.clear()
            self._trips.clear()


# process-wide instances: jitted entry points register with RETRACES at
# build time; the ingest / inference-service loops tick HOST_TRANSFERS
# and open TRANSFER_GUARD windows around their dispatch/fetch bodies.
# Subprocess fleets get their own (fresh) instances after spawn.
RETRACES = RetraceGuard()
HOST_TRANSFERS = TransferCounter()
TRANSFER_GUARD = TransferGuard()


@contextlib.contextmanager
def device_profile(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` device trace into ``log_dir`` (viewable
    in TensorBoard / Perfetto).  No-op when ``log_dir`` is None, so call
    sites can be unconditional."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
