"""Tracing / profiling instrumentation (SURVEY.md §5.1).

The reference has no tracing at all — only the buffer process's 10-second
stdout stats (worker.py:89-106).  This module supplies the TPU-native hooks
the survey calls for:

- :class:`Tracer` — in-process stage timers and gauges.  Spans record
  wall-time per pipeline stage (actor inference, batch assembly, H2D
  staging, learner step, priority feedback) as exponential moving averages
  with counts; gauges record instantaneous values (queue depths, buffer
  fill).  A ``snapshot()`` is a plain dict, cheap enough to attach to every
  log line.
- :func:`device_profile` — a context manager around ``jax.profiler`` trace
  capture, producing a TensorBoard-loadable trace of the XLA device
  timeline for any region of the training loop.

Everything is thread-safe and allocation-light: spans cost two
``perf_counter`` calls and a lock-free float update per use, so they can
sit in the hot loop.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional


class _Stat:
    __slots__ = ("count", "total", "ewma", "last")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.ewma = 0.0
        self.last = 0.0

    def update(self, dt: float, alpha: float) -> None:
        self.count += 1
        self.total += dt
        self.last = dt
        self.ewma = dt if self.count == 1 else (
            alpha * dt + (1.0 - alpha) * self.ewma)


class Tracer:
    """Stage timers + gauges for the training fabric.

    >>> tracer = Tracer()
    >>> with tracer.span("learner_step"):
    ...     ...
    >>> tracer.gauge("batch_queue", 5)
    >>> tracer.snapshot()["span.learner_step.ewma_ms"]
    """

    def __init__(self, alpha: float = 0.05):
        self._alpha = alpha
        self._spans: Dict[str, _Stat] = {}
        self._gauges: Dict[str, float] = {}
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                stat = self._spans.get(name)
                if stat is None:
                    stat = self._spans[name] = _Stat()
                stat.update(dt, self._alpha)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def snapshot(self) -> Dict[str, float]:
        """Flat dict: span.<name>.{ewma_ms,mean_ms,count}, gauge.<name>,
        counter.<name>."""
        out: Dict[str, float] = {}
        with self._lock:
            for name, s in self._spans.items():
                out[f"span.{name}.ewma_ms"] = s.ewma * 1e3
                out[f"span.{name}.mean_ms"] = (s.total / s.count) * 1e3
                out[f"span.{name}.count"] = s.count
            for name, v in self._gauges.items():
                out[f"gauge.{name}"] = v
            for name, v in self._counters.items():
                out[f"counter.{name}"] = v
        return out


@contextlib.contextmanager
def device_profile(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` device trace into ``log_dir`` (viewable
    in TensorBoard / Perfetto).  No-op when ``log_dir`` is None, so call
    sites can be unconditional."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
