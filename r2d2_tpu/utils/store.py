"""Versioned immutable parameter publication.

Replaces the reference's shared-memory model mutation
(``train.py:23``, ``worker.py:306-307``, pulled at ``worker.py:564-566``),
which tolerates torn reads across tensors while the learner writes.  Here
the learner publishes an immutable pytree snapshot under a lock and actors
pull by version — the torn-read race is structurally impossible
(SURVEY.md §5.2).
"""
from __future__ import annotations

import threading
from typing import Any, Optional, Tuple


class ParamStore:
    def __init__(self, params: Optional[Any] = None):
        self._lock = threading.Lock()
        self._version = 0 if params is None else 1
        self._params = params
        self._placed: dict = {}  # device -> (version, placed params)

    def publish(self, params: Any) -> int:
        """Swap in a new snapshot; returns its version (monotonic from 1)."""
        with self._lock:
            self._params = params
            self._version += 1
            # drop the previous generation's placements: entries for devices
            # whose consumers have exited would otherwise pin a full placed
            # param copy each, forever
            self._placed.clear()
            return self._version

    def get(self) -> Tuple[int, Any]:
        """Latest ``(version, params)``; params is None until first publish."""
        with self._lock:
            return self._version, self._params

    def get_placed(self, device: Any) -> Tuple[int, Any]:
        """Latest ``(version, params placed on device)``, computing the
        placement once per (version, device) and sharing it.

        Consumers that need the snapshot on a specific backend — actor
        fleets pulling learner weights to the host CPU — would otherwise
        each pay the same device→host transfer per refresh; on a tunneled
        accelerator that is the whole parameter set across the wire per
        fleet.  The transfer runs outside the lock so a slow interconnect
        never blocks ``publish``/``get``; concurrent same-version callers
        may race the transfer (placing twice, last one cached) rather
        than serialise on it.
        """
        import jax

        with self._lock:
            version, params = self._version, self._params
            cached = self._placed.get(device)
            if cached is not None and cached[0] == version:
                return cached
        if params is not None:
            params = jax.device_put(params, device)
        entry = (version, params)
        with self._lock:
            if self._version == version:  # don't cache a stale snapshot
                self._placed[device] = entry
        return entry
