"""Versioned immutable parameter publication.

Replaces the reference's shared-memory model mutation
(``train.py:23``, ``worker.py:306-307``, pulled at ``worker.py:564-566``),
which tolerates torn reads across tensors while the learner writes.  Here
the learner publishes an immutable pytree snapshot under a lock and actors
pull by version — the torn-read race is structurally impossible
(SURVEY.md §5.2).
"""
from __future__ import annotations

import threading
from typing import Any, Optional, Tuple


class ParamStore:
    def __init__(self, params: Optional[Any] = None):
        self._lock = threading.Lock()
        self._version = 0 if params is None else 1
        self._params = params

    def publish(self, params: Any) -> int:
        """Swap in a new snapshot; returns its version (monotonic from 1)."""
        with self._lock:
            self._params = params
            self._version += 1
            return self._version

    def get(self) -> Tuple[int, Any]:
        """Latest ``(version, params)``; params is None until first publish."""
        with self._lock:
            return self._version, self._params
