from r2d2_tpu.utils.math import (
    value_rescale,
    inverse_value_rescale,
    n_step_return,
    n_step_gamma_tail,
    epsilon_ladder,
    mixed_td_errors,
)
