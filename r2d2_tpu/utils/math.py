"""Pure host-side math used across the framework (numpy).

Device-side (jnp) twins of the rescale functions live in
``r2d2_tpu.learner.step``; these numpy versions are used by actors, the
replay plane, and as the oracle in tests.
"""
from __future__ import annotations

import numpy as np


def value_rescale(x: np.ndarray, eps: float = 1e-3) -> np.ndarray:
    """h(x) = sign(x)(sqrt(|x|+1)-1) + eps*x  (reference: worker.py:383-385)."""
    x = np.asarray(x)
    return np.sign(x) * (np.sqrt(np.abs(x) + 1.0) - 1.0) + eps * x


def inverse_value_rescale(x: np.ndarray, eps: float = 1e-3) -> np.ndarray:
    """Closed-form inverse of ``value_rescale`` (reference: worker.py:387-390)."""
    x = np.asarray(x)
    t = (np.sqrt(1.0 + 4.0 * eps * (np.abs(x) + 1.0 + eps)) - 1.0) / (2.0 * eps)
    return np.sign(x) * (np.square(t) - 1.0)


def n_step_return(rewards: np.ndarray, n: int, gamma: float) -> np.ndarray:
    """Discounted n-step forward returns for every step of an episode chunk.

    ``out[t] = sum_{i<n} gamma^i * rewards[t+i]`` with rewards treated as zero
    past the end.  Matches the reference's convolution construction
    (worker.py:466-469) but is a plain function instead of inline buffer code.
    """
    rewards = np.asarray(rewards, dtype=np.float64)
    padded = np.concatenate([rewards, np.zeros(n - 1, dtype=np.float64)])
    kernel = gamma ** np.arange(n - 1, -1, -1, dtype=np.float64)
    return np.convolve(padded, kernel, mode="valid").astype(np.float32)


def n_step_gamma_tail(size: int, n: int, gamma: float, terminal: bool) -> np.ndarray:
    """Per-step bootstrap discount ``gamma^k`` for an episode chunk of ``size``.

    Interior steps get ``gamma**n``; the last ``min(size, n)`` steps have fewer
    than ``n`` real rewards, so they get decreasing exponents — or exactly 0
    when the chunk ends the episode, which encodes terminality without a done
    flag (reference: worker.py:443-453).
    """
    m = min(size, n)
    tail = np.zeros(m, dtype=np.float32) if terminal else gamma ** np.arange(m, 0, -1, dtype=np.float32)
    return np.concatenate([np.full(size - m, gamma ** n, dtype=np.float32), tail])


def epsilon_ladder(actor_id: int, num_actors: int, base_eps: float = 0.4,
                   alpha: float = 7.0) -> float:
    """Ape-X per-actor epsilon: base^(1 + i/(N-1) * alpha) (reference: train.py:15-17)."""
    if num_actors == 1:
        return base_eps
    return float(base_eps ** (1.0 + actor_id / (num_actors - 1) * alpha))


def mixed_td_errors(td_error: np.ndarray, learning_steps: np.ndarray,
                    eta: float = 0.9) -> np.ndarray:
    """Per-sequence priority ``eta*max + (1-eta)*mean`` of |TD| over a ragged
    concatenation (reference: worker.py:268-276), vectorised with ``reduceat``
    instead of the reference's Python loop.
    """
    learning_steps = np.asarray(learning_steps, dtype=np.int64)
    starts = np.concatenate([[0], np.cumsum(learning_steps)[:-1]])
    maxes = np.maximum.reduceat(td_error, starts)
    means = np.add.reduceat(td_error, starts) / learning_steps
    return (eta * maxes + (1.0 - eta) * means).astype(td_error.dtype)
