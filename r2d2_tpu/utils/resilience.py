"""Shared degraded-mode primitives: deadlines, retries, circuit breakers.

The fabric's failure story before this module was binary: a plane either
worked or it raised (the fleet's act RPC died at a hardcoded 600 s
timeout, the watchdog burned its respawn budget re-spawning fleets into
the same frozen service, and the run stopped).  Podracer-scale systems
(PAPERS.md) treat partial failure as the NORMAL operating condition —
preemption, a slow neighbour, a stalled service — and the correct
response is almost never "crash all clients": it is *bounded waiting*,
*bounded retrying*, and *degrading to a local fallback* until the remote
plane recovers.  Three primitives, shared by every plane that can wedge:

- :class:`Deadline` — a monotonic time budget that composes (``remaining``
  feeds the next wait's timeout), replacing ad-hoc ``time.time() + X``
  arithmetic at every bounded-wait site.
- :class:`RetryPolicy` — jittered exponential backoff with a bounded
  attempt count.  Deterministic given its seed, so chaos drills replay.
- :class:`CircuitBreaker` — the classic closed → open → half-open state
  machine.  ``record_failure`` past the threshold opens the circuit;
  while open, callers take their local fallback path instead of waiting
  on a dead remote; after ``cooldown`` seconds one probe per cooldown is
  allowed through (half-open), and its success closes the circuit again.
  Transitions are surfaced through an ``on_transition`` callback so the
  owning plane can wire them into telemetry (``resilience.*`` — the
  serve fleets publish theirs through the stats slab, in-process users
  write the registry directly).

Users today: the serve-plane act client (failover to fleet-local
inference, ``parallel/inference_service.RemoteActClient``), the
service's batch window (``InferenceService.serve_once``), and the anakin
dispatch deadline (``learner/anakin.run_anakin_loop``).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)

# CircuitBreaker states (gauge-friendly integer codes: the slab publishes
# the state as a float and the registry renders it as a gauge)
CLOSED = 0
OPEN = 1
HALF_OPEN = 2

STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half_open"}


def bounded_event_set(event, timeout: float = 2.0, name: str = "") -> bool:
    """Best-effort ``multiprocessing.Event.set()`` with a hard bound.

    A SIGKILLed subprocess (a chaos ``kill_*`` drill, an OOM kill, a
    preemption) can die while holding the event's internal condition
    lock — the documented multiprocessing caveat the fleet plane's
    channel-retirement design exists for — after which a naked ``set()``
    on that corrupted primitive blocks its caller FOREVER (observed as a
    wedged teardown under ``kill_fleet`` chaos: the trainer hung inside
    ``Event.set`` while every child was already dead).  The set
    therefore runs on a daemon thread that is abandoned on timeout.
    Returns False when the lock never came free; callers fall through to
    their terminate/join path, which reaps the children regardless —
    a child that never saw the stop flag dies by SIGTERM like any
    kill -9-grade failure.  Trainer-side *reads* of a child-shared event
    must not exist at all (mirror the flag in a plain Python bool); this
    helper only bounds the one write a graceful drain needs to attempt.
    """
    t = threading.Thread(  # graftlint: disable=thread-discipline -- the whole point is a thread the caller can ABANDON when a SIGKILL-corrupted event lock never comes free; supervision would add a restart loop around an unbounded wait
        target=event.set, daemon=True,
        name=f"event-set-{name}" if name else "event-set")
    t.start()
    t.join(timeout)
    if t.is_alive():
        log.warning(
            "event.set()%s did not complete within %.1fs — a killed "
            "subprocess likely died holding the event's lock; "
            "abandoning the set and relying on terminate/join to reap "
            "the children", f" ({name})" if name else "", timeout)
        return False
    return True


class Deadline:
    """A monotonic time budget.

    ``Deadline(2.0)`` expires 2 seconds from construction; ``remaining()``
    is the non-negative time left (feed it to the next ``get(timeout=)``),
    ``expired`` is the terminal check.  ``budget <= 0`` means *unbounded*
    (``remaining()`` returns ``default`` forever) so call sites can take a
    config knob directly without special-casing "disabled".
    """

    def __init__(self, budget: float):
        self.budget = float(budget)
        self._t0 = time.monotonic()

    @property
    def expired(self) -> bool:
        return self.budget > 0 and time.monotonic() - self._t0 > self.budget

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def remaining(self, default: float = float("inf")) -> float:
        if self.budget <= 0:
            return default
        return max(0.0, self.budget - (time.monotonic() - self._t0))

    def poll_timeout(self, step: float) -> float:
        """A wait-step that never overshoots the budget: ``min(step,
        remaining)``, floored at a millisecond so a just-expired deadline
        still gets one non-busy poll before the caller sees ``expired``."""
        return max(0.001, min(step, self.remaining(step)))


class RetryPolicy:
    """Bounded retries with jittered exponential backoff.

    ``attempts`` counts TOTAL tries (1 = no retry at all).  Delay before
    retry ``i`` (1-based) is ``base * 2**(i-1)``, capped at ``max_delay``,
    with multiplicative jitter in ``[1-jitter, 1+jitter]`` drawn from a
    seeded generator — deterministic per policy instance, so a chaos soak
    replays.  Call sites own their retry loops (they interleave mode
    escalation and breaker bookkeeping between tries) and take
    :meth:`backoff` for the sleep schedule.
    """

    def __init__(self, attempts: int = 3, base: float = 0.05,
                 max_delay: float = 2.0, jitter: float = 0.2,
                 seed: int = 0):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        self.attempts = int(attempts)
        self.base = float(base)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        import numpy as np

        self._rng = np.random.default_rng([seed, 0x5E51])

    def backoff(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based: the delay after the
        ``attempt``-th failure)."""
        d = min(self.max_delay, self.base * (2.0 ** max(0, attempt - 1)))
        if self.jitter > 0:
            d *= 1.0 + self.jitter * float(self._rng.uniform(-1.0, 1.0))
        return max(0.0, d)


class CircuitBreaker:
    """closed → open → half-open failure gate (module docstring).

    Thread-safe.  The owner calls :meth:`allow_attempt` before each
    remote call: ``True`` means "try the remote" (closed, or half-open
    granting this caller THE probe slot), ``False`` means "take the local
    fallback".  After the call, :meth:`record_success` /
    :meth:`record_failure` advance the machine.  ``on_transition(name,
    old_state, new_state)`` is invoked OUTSIDE the lock on every state
    change — wire it to a registry/stats sink.
    """

    def __init__(self, name: str = "", failure_threshold: int = 1,
                 cooldown: float = 5.0,
                 on_transition: Optional[Callable[[str, int, int], None]]
                 = None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.cooldown = float(cooldown)
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive failures while closed
        self._opened_at = 0.0
        self._probe_out = False     # half-open: one probe in flight
        # lazy-transition callbacks queued under the lock, flushed
        # outside it by whichever public call observed the flip
        self._pending = []
        self.opens = 0              # total closed/half-open -> open edges
        self.probes = 0             # half-open attempts granted

    # ------------------------------------------------------------- state
    @property
    def state(self) -> int:
        with self._lock:
            s = self._effective_state()
            cbs = self._drain()
        for cb in cbs:
            cb()
        return s

    @property
    def state_name(self) -> str:
        return STATE_NAMES[self.state]

    def _effective_state(self) -> int:
        """Lock held.  OPEN lazily becomes HALF_OPEN once the cooldown
        elapses — there is no timer thread; the next caller observes the
        flip (and flushes its queued on_transition outside the lock, so
        the circuit_state gauge really does show all three states)."""
        if (self._state == OPEN
                and time.monotonic() - self._opened_at >= self.cooldown):
            cb = self._transition(HALF_OPEN)
            self._probe_out = False
            if cb is not None:
                self._pending.append(cb)
        return self._state

    def _drain(self) -> list:
        """Lock held; take the queued lazy-transition callbacks."""
        cbs, self._pending = self._pending, []
        return cbs

    def _transition(self, new: int):
        """Lock held; returns the callback to run outside the lock."""
        old, self._state = self._state, new
        if new == OPEN:
            self._opened_at = time.monotonic()
            self.opens += 1
        cb = self.on_transition
        if cb is None or old == new:
            return None
        return lambda: cb(self.name, old, new)

    # ------------------------------------------------------------- gates
    def allow_attempt(self) -> bool:
        """May the caller try the remote right now?  Closed: yes.
        Open (cooling down): no — degrade locally.  Half-open: yes for
        exactly one caller per cooldown window (the probe)."""
        with self._lock:
            s = self._effective_state()
            if s == CLOSED:
                out = True
            elif s == HALF_OPEN and not self._probe_out:
                self._probe_out = True
                self.probes += 1
                out = True
            else:
                out = False
            cbs = self._drain()
        for cb in cbs:
            cb()
        return out

    def record_success(self) -> None:
        """A remote call completed: closes the circuit from any state."""
        with self._lock:
            self._effective_state()   # observe a pending half-open flip
            self._failures = 0
            self._probe_out = False
            cbs = self._drain()
            cb = self._transition(CLOSED)
            if cb is not None:
                cbs.append(cb)
        for cb in cbs:
            cb()

    def record_failure(self) -> None:
        """A remote call failed terminally (its bounded retries are the
        caller's business — count ONE failure per exhausted call).
        Opens at ``failure_threshold`` consecutive failures; a failed
        half-open probe re-opens immediately (cooldown restarts)."""
        with self._lock:
            s = self._effective_state()
            cbs = self._drain()
            cb = None
            if s == HALF_OPEN:
                self._probe_out = False
                cb = self._transition(OPEN)
            else:
                self._failures += 1
                if s == CLOSED and self._failures >= self.failure_threshold:
                    cb = self._transition(OPEN)
            if cb is not None:
                cbs.append(cb)
        for cb in cbs:
            cb()

    def snapshot(self) -> dict:
        with self._lock:
            s = self._effective_state()
            cbs = self._drain()
            snap = dict(state=s, state_name=STATE_NAMES[s],
                        opens=self.opens, probes=self.probes,
                        failures=self._failures)
        for cb in cbs:
            cb()
        return snap
