"""Deterministic fault injection for the training fabric.

Podracer-style systems treat preemption as routine; the only way the
recovery paths stay honest is to force the failures on purpose.  A
:class:`ChaosInjector` is built from ``cfg.chaos_spec`` (empty string =
disabled, the production default) and wired through ``train()`` so every
recovery path the fabric claims to have can be exercised under load:

- ``kill_fleet``    — SIGKILL a random live fleet subprocess (the process
                      watchdog must respawn it on its lane shard).
- ``garble_block``  — flip bytes inside a random shm block slot (the CRC32
                      integrity word must catch it; the trainer drops the
                      block and bumps ``ReplayBuffer.stats()['corrupt_blocks']``).
- ``truncate_ckpt`` — abort a checkpoint save mid-write (payload truncated
                      / replay meta never committed; restore must skip the
                      partial step).
- ``freeze_learner``— sleep inside the learner's stop-poll for ``dur``
                      seconds (the heartbeat watchdog must detect the
                      stall and stop the fabric).
- ``freeze_service``— sleep inside the serve-plane's ``inference_serve``
                      fabric loop for ``dur`` seconds: every serve-mode
                      fleet's act RPCs start timing out, their circuit
                      breakers must open and the fleets must degrade to
                      local inference (utils/resilience.py), then
                      re-attach after the thaw — zero fleet deaths.
- ``drop_act_response``   — the service serves a batch but never posts
                      one fleet's response token (simulates a lost
                      wakeup); the fleet's bounded retry must re-request
                      and be answered, never wedging the lockstep fleet.
- ``garble_act_response`` — flip bytes inside one fleet's response
                      region AFTER its CRC32 was written; the fleet must
                      detect the mismatch and retry (bounded).
- ``stall_pump``    — sleep inside the param-pump fabric loop for
                      ``dur`` seconds: fleets keep training on frozen
                      weights, which the staleness watchdog must surface
                      as ``fleet.stale_params_s`` / a degraded health
                      verdict instead of silence.
- ``wedge_dispatch``— (anakin transport) stall the fused-loop harvest
                      for ``dur`` seconds, simulating a wedged device
                      dispatch; the bounded dispatch deadline
                      (``cfg.dispatch_deadline``) must snapshot-then-
                      abort instead of training on through a flaky
                      device or hanging forever.
- ``kill_replay_shard``   — (sharded replay, ``cfg.replay_shards`` > 1)
                      SIGKILL a random live replay shard owner process;
                      the ``replay_watch`` loop must respawn it on its
                      slot slice and restore it from the latest replay
                      snapshot (degraded: cold, its slots re-ingest
                      fresh) — the learner keeps sampling from the
                      surviving shards throughout.
- ``garble_sample_response`` — flip bytes in a shard's preassembled
                      sample-batch response after its CRC32 landed; the
                      trainer-side verification must catch it and the
                      bounded retry must re-request (never a torn batch
                      into the learner).
- ``stall_shard``   — SIGSTOP a random replay shard for ``dur`` seconds
                      (then SIGCONT): the sample RPC deadline
                      (``cfg.replay_sample_timeout``) must fire and the
                      stalled shard's rows redistribute over the healthy
                      shards' mass — zero learner stalls.
- ``kill_session_client`` — (session tier, tools/session_load_gen.py)
                      a load-gen worker drops its connection abruptly,
                      abandoning every session it owned mid-episode;
                      the SessionServer must reap them on the
                      disconnect (``serving.reaped``) — hidden-state
                      slots never leak, and the tier's health stays
                      ``ok``/``degraded``.
- ``slow_session_client`` — (session tier) one load-gen session
                      freezes for ``dur`` seconds mid-episode — a
                      straggler.  Continuous batching must keep serving
                      everyone else (the batch is whatever is pending,
                      never a lockstep window a straggler can hold
                      hostage); the session either resumes or idle-
                      reaps.
- ``poison_params`` — overwrite one learner param leaf with NaN on the
                      learner thread (the learnhealth NaN-sentry drill,
                      telemetry/learnhealth.py): the in-graph sentry /
                      host loss check must fire the ``nonfinite`` alert,
                      degrade /healthz and stop the fabric CLEANLY
                      (drain-then-save) instead of crashing the learner
                      or training on through poisoned numerics.
- ``kill_eval_sidecar`` — (league plane, ``cfg.league_eval``) SIGKILL
                      the standing eval sidecar mid-sweep; the
                      ``eval_watch`` loop must respawn it with its
                      checkpoint cursor resumed from league.jsonl (no
                      duplicate rows, no skipped members), training
                      throughput untouched; an exhausted respawn budget
                      degrades /healthz, never the fabric.
- ``partition_shard_link`` — (socket replay, ``replay_transport=
                      "socket"``) blackhole one shard link in BOTH
                      directions for ``dur`` seconds, the socket left
                      standing — a real partition.  The shard's gossip
                      goes stale and its RPCs time out; its mass must
                      leave the view, its strata redistribute over the
                      reachable shards (zero learner stalls), blocks
                      routed to it drop-with-count, and at the heal the
                      link must re-attach with no stale response or
                      feedback ever applied (epoch/seq guards).
- ``delay_shard_link``    — (socket replay) one rtt spike: the link's
                      receiver sleeps ``dur`` before its next dispatch.
                      Below the RPC deadline it must only show up in
                      the replay.net.rtt_s histogram; above it, it must
                      behave exactly like a partition (bounded,
                      redistributed, healed).
- ``half_open_shard``     — (socket replay) the classic half-open peer:
                      for ``dur`` seconds the trainer's sends are
                      silently lost while receives still work.  Sample
                      requests vanish → the deadline fires and rows
                      redistribute; the circuit opens after repeated
                      losses and the probe re-closes it at the heal —
                      never a wedge, never a torn frame.
- ``garble_net_frame``    — (socket replay) flip bytes in a received
                      frame before decode; the frame CRC must catch
                      every one (dropped + counted in
                      replay.net.garbled) and a garbled sample response
                      must be re-requested by the bounded retry — torn
                      frames never reach the ring or the learner.

Spec grammar — semicolon-separated ``kind[:key=val[,key=val...]]``::

    kill_fleet:every=500;garble_block:p=0.01;freeze_learner:at=40,dur=3

Per-kind firing controls (an *opportunity* is one call site visit):

- ``p=<float>``   fire with probability p per opportunity (seeded draw)
- ``every=<int>`` fire on every Nth opportunity
- ``at=<int>``    fire exactly once, on the Nth opportunity
- ``n=<int>``     cap total fires (default: 1 for ``at``, unlimited else)
- ``dur=<float>`` freeze/stall duration in seconds (``freeze_learner``,
                  ``freeze_service``, ``stall_pump``, ``wedge_dispatch``)

Everything is deterministic given (spec, seed): each kind gets its own
counter and a PCG64 stream seeded from (seed, kind), so a chaos soak is
replayable.
"""
from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

log = logging.getLogger(__name__)

# order matters: each kind's RNG stream is seeded from (seed, index), so
# append new kinds at the END to keep existing soak replays stable
_KINDS = ("kill_fleet", "garble_block", "truncate_ckpt", "freeze_learner",
          "freeze_service", "drop_act_response", "garble_act_response",
          "stall_pump", "wedge_dispatch", "kill_replay_shard",
          "garble_sample_response", "stall_shard", "kill_session_client",
          "slow_session_client", "kill_eval_sidecar", "poison_params",
          "partition_shard_link", "delay_shard_link", "half_open_shard",
          "garble_net_frame")


def parse_spec(spec: str) -> Dict[str, Dict[str, float]]:
    """``chaos_spec`` string → {kind: params}.  Raises ValueError on an
    unknown kind or a malformed clause (Config validation calls this so a
    typo fails at construction, not mid-run)."""
    out: Dict[str, Dict[str, float]] = {}
    for clause in filter(None, (c.strip() for c in spec.split(";"))):
        kind, _, raw = clause.partition(":")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ValueError(
                f"unknown chaos kind {kind!r} (expected one of {_KINDS})")
        params: Dict[str, float] = {}
        for kv in filter(None, (p.strip() for p in raw.split(","))):
            key, _, val = kv.partition("=")
            if key not in ("p", "every", "at", "n", "dur"):
                raise ValueError(f"unknown chaos param {key!r} in {clause!r}")
            params[key] = float(val)
        if not any(k in params for k in ("p", "every", "at")):
            raise ValueError(
                f"chaos clause {clause!r} needs a trigger (p=/every=/at=)")
        out[kind] = params
    return out


class ChaosInjector:
    """Seeded, counter-deterministic fault firing (see module docstring).
    Thread-safe: call sites live on different fabric threads."""

    def __init__(self, spec: str, seed: int = 0):
        self.kinds = parse_spec(spec)
        self._lock = threading.Lock()
        self._opportunities = {k: 0 for k in self.kinds}
        self._fires = {k: 0 for k in self.kinds}
        self._rngs = {
            k: np.random.default_rng([seed, i])
            for i, k in enumerate(_KINDS) if k in self.kinds
        }

    def __bool__(self) -> bool:
        return bool(self.kinds)

    def enabled(self, kind: str) -> bool:
        return kind in self.kinds

    def fire(self, kind: str) -> Optional[Dict[str, float]]:
        """One opportunity for ``kind``: returns the clause params when the
        fault fires, else None."""
        prm = self.kinds.get(kind)
        if prm is None:
            return None
        with self._lock:
            self._opportunities[kind] += 1
            opp = self._opportunities[kind]
            cap = prm.get("n", 1.0 if "at" in prm else math.inf)
            if self._fires[kind] >= cap:
                return None
            if "at" in prm:
                hit = opp == int(prm["at"])
            elif "every" in prm:
                hit = opp % max(1, int(prm["every"])) == 0
            else:
                hit = float(self._rngs[kind].random()) < prm["p"]
            if not hit:
                return None
            self._fires[kind] += 1
        log.warning("chaos: firing %s (opportunity %d)", kind, opp)
        return prm

    def counts(self) -> Dict[str, int]:
        """Fires per kind so far — surfaced in train() metrics/logs."""
        with self._lock:
            return dict(self._fires)

    # ---------------------------------------------------------- call sites
    def maybe_kill_fleet(self, plane: Any) -> Optional[int]:
        """SIGKILL a random live fleet process of a ProcessFleetPlane.
        Returns the killed fleet id, or None."""
        if self.fire("kill_fleet") is None:
            return None
        live = [f for f, p in enumerate(plane.procs)
                if p is not None and p.is_alive()]
        if not live:
            return None
        f = int(live[self._rngs["kill_fleet"].integers(len(live))])
        log.warning("chaos: SIGKILL fleet%d (pid %s)", f, plane.procs[f].pid)
        plane.procs[f].kill()
        return f

    def maybe_garble_block(self, plane: Any) -> Optional[int]:
        """Flip 64 bytes at a random offset inside a random slot of a
        random fleet's shm slab.  An in-flight block whose CRC was already
        written shows up as a mismatch at ingest (dropped + counted); a
        free slot is harmlessly overwritten by the next producer write.
        Returns the garbled fleet id, or None."""
        if self.fire("garble_block") is None:
            return None
        rng = self._rngs["garble_block"]
        # capture (fleet, channel) together: the fleet watchdog may retire
        # a channel concurrently, and .index() on a retired object would
        # crash the chaos loop mid-drill
        chans = [(f, c) for f, c in enumerate(plane.channels)
                 if c is not None]
        if not chans:
            return None
        f, ch = chans[int(rng.integers(len(chans)))]
        slot = int(rng.integers(ch.num_slots))
        lo = slot * ch.slot_nbytes + int(rng.integers(
            max(1, ch.slot_nbytes - 64)))
        try:
            buf = np.frombuffer(ch.shm.buf, np.uint8)
            buf[lo:lo + 64] ^= 0xFF
        except (ValueError, TypeError):  # channel closed under us
            return None
        return f

    def learner_freeze_seconds(self) -> float:
        """Seconds the learner's stop-poll should sleep this iteration
        (0.0 = no freeze injected)."""
        prm = self.fire("freeze_learner")
        return float(prm.get("dur", 2.0)) if prm else 0.0

    def service_freeze_seconds(self) -> float:
        """Seconds the ``inference_serve`` fabric loop should sleep (0.0
        = no freeze) — the serve-plane failover drill: the fleets' act
        RPCs must time out, open their circuits and degrade to local
        inference until the thaw.  One opportunity per SERVED batch (not
        per idle poll), so ``at=N`` lands the freeze under real traffic
        rather than during spawn/warm-up."""
        prm = self.fire("freeze_service")
        return float(prm.get("dur", 2.0)) if prm else 0.0

    def pump_stall_seconds(self) -> float:
        """Seconds the param-pump fabric loop should sleep this iteration
        (0.0 = no stall) — the staleness-watchdog drill."""
        prm = self.fire("stall_pump")
        return float(prm.get("dur", 2.0)) if prm else 0.0

    def dispatch_wedge_seconds(self) -> float:
        """Seconds the anakin harvest should stall this dispatch (0.0 =
        no wedge) — the bounded dispatch-deadline drill."""
        prm = self.fire("wedge_dispatch")
        return float(prm.get("dur", 2.0)) if prm else 0.0

    def maybe_kill_replay_shard(self, plane: Any) -> Optional[int]:
        """SIGKILL a random live shard of a ShardedReplayPlane — the
        respawn-with-restore drill.  Returns the killed shard id, or
        None."""
        if self.fire("kill_replay_shard") is None:
            return None
        live = [s for s, p in enumerate(plane.procs)
                if p is not None and p.is_alive()]
        if not live:
            return None
        s = int(live[self._rngs["kill_replay_shard"].integers(len(live))])
        log.warning("chaos: SIGKILL replay shard%d (pid %s)", s,
                    plane.procs[s].pid)
        plane.procs[s].kill()
        return s

    def garble_sample_response(self) -> bool:
        """One opportunity per received sample-RPC response (the sharded
        replay plane's receipt path): True = flip response bytes AFTER
        the shard's CRC landed — trainer-side verification must catch it
        and the bounded retry must re-request."""
        return self.fire("garble_sample_response") is not None

    def maybe_stall_shard(self, plane: Any) -> Optional[int]:
        """SIGSTOP a random live replay shard for ``dur`` seconds, then
        SIGCONT — the sample-RPC-deadline drill (the caller's thread
        sleeps through the stall; the shard itself is frozen).  Returns
        the stalled shard id, or None."""
        import os
        import signal as _signal

        prm = self.fire("stall_shard")
        if prm is None:
            return None
        live = [s for s, p in enumerate(plane.procs)
                if p is not None and p.is_alive()]
        if not live:
            return None
        s = int(live[self._rngs["stall_shard"].integers(len(live))])
        p = plane.procs[s]
        dur = float(prm.get("dur", 2.0))
        log.warning("chaos: SIGSTOP replay shard%d for %.1fs", s, dur)
        try:
            os.kill(p.pid, _signal.SIGSTOP)
            time.sleep(dur)
        finally:
            try:
                os.kill(p.pid, _signal.SIGCONT)
            except (ProcessLookupError, OSError):
                pass   # died while stopped: the watchdog takes over
        return s

    def maybe_kill_eval_sidecar(self, sidecar: Any) -> bool:
        """SIGKILL the league eval sidecar subprocess mid-sweep — the
        cursor-resume drill: the ``eval_watch`` respawn must continue
        the checkpoint cursor from league.jsonl with no duplicate rows,
        and training throughput must be unaffected.  Returns True when
        the kill landed."""
        if self.fire("kill_eval_sidecar") is None:
            return False
        p = getattr(sidecar, "proc", None)
        if p is None or not p.is_alive():
            return False
        log.warning("chaos: SIGKILL eval sidecar (pid %s)", p.pid)
        p.kill()
        return True

    def poison_params_now(self) -> bool:
        """One opportunity per learner stop-poll: True = the trainer
        must overwrite one param leaf with NaN (``Learner.poison_params``
        — runs on the learner thread, so the donated state handle cannot
        race a dispatch).  The learnhealth plane must then fire the
        ``nonfinite`` alert and stop the fabric cleanly."""
        return self.fire("poison_params") is not None

    def session_client_kill(self) -> bool:
        """One opportunity per load-gen client step burst: True = the
        worker must DROP its connection without closing its sessions
        (mid-episode abandon) — the SessionServer's disconnect reap must
        free every owned hidden slot (tools/session_load_gen.py)."""
        return self.fire("kill_session_client") is not None

    def session_client_slow_seconds(self) -> float:
        """Seconds one load-gen session should freeze mid-episode (0.0 =
        no straggler injected) — the continuous batch must keep serving
        the other sessions at full rate meanwhile."""
        prm = self.fire("slow_session_client")
        return float(prm.get("dur", 2.0)) if prm else 0.0

    def net_partition_seconds(self) -> float:
        """Seconds one replay shard link should be blackholed in both
        directions (0.0 = no partition).  One opportunity per sample
        request issued to a shard (traffic-aligned — ``at=``/``every=``
        land under real sampling load); the fired link is the one the
        request was headed for (parallel/replay_net.py)."""
        prm = self.fire("partition_shard_link")
        return float(prm.get("dur", 2.0)) if prm else 0.0

    def net_delay_seconds(self) -> float:
        """Seconds the link's receiver should sleep before its next
        dispatch (0.0 = no spike) — the rtt-spike drill."""
        prm = self.fire("delay_shard_link")
        return float(prm.get("dur", 0.5)) if prm else 0.0

    def net_half_open_seconds(self) -> float:
        """Seconds the trainer's sends to one link should be silently
        lost while receives still work (0.0 = healthy) — the half-open
        peer drill."""
        prm = self.fire("half_open_shard")
        return float(prm.get("dur", 1.0)) if prm else 0.0

    def garble_net_frame(self) -> bool:
        """One opportunity per received net frame (the socket replay
        link's dispatch path): True = flip frame bytes ahead of decode —
        the frame CRC must catch it and, for a sample response, the
        bounded retry must re-request."""
        return self.fire("garble_net_frame") is not None

    def drop_response(self) -> bool:
        """One opportunity per served response token: True = the service
        must NOT post this token (the fleet's bounded retry recovers)."""
        return self.fire("drop_act_response") is not None

    def garble_response(self) -> bool:
        """One opportunity per served response: True = the service flips
        response bytes AFTER the CRC landed (fleet-side CRC verification
        must catch it and retry)."""
        return self.fire("garble_act_response") is not None
