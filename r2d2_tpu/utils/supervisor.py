"""Failure detection and recovery for the training fabric (SURVEY.md §5.3).

The reference has none: its helper threads are fire-and-forget daemons
(worker.py:78-85,319) and a dead actor silently starves its queue.  Here
every fabric thread runs under a :class:`Supervisor` that:

- catches and records uncaught exceptions per thread (kind, message,
  traceback, timestamp),
- restarts the thread up to ``max_restarts`` times with a small backoff
  (crash loops escalate instead of spinning),
- exposes ``health()`` — a structured liveness snapshot suitable for the
  log loop — and ``failed`` to let the orchestrator stop the run when a
  plane is irrecoverably down instead of hanging.

Recovery is safe because every fabric loop is written to be re-enterable:
state lives in the lock-protected ReplayBuffer / ParamStore / queues, not
in thread locals, so a restarted loop resumes exactly where the dead one
left off.
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, Dict, List, Optional


class SupervisedThread:
    """One named, restartable worker loop."""

    def __init__(self, name: str, target: Callable[[], None],
                 max_restarts: int, backoff: float,
                 on_giveup: Optional[Callable[[str], None]] = None):
        self.name = name
        self.target = target
        self.max_restarts = max_restarts
        self.backoff = backoff
        self.on_giveup = on_giveup
        self.restarts = 0
        self.errors: List[Dict[str, object]] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._gave_up = False
        self._stopping = False
        self._pending_timer: Optional[threading.Timer] = None

    def _run(self) -> None:
        try:
            self.target()
        except BaseException as e:  # noqa: BLE001 — supervision boundary
            with self._lock:
                self.errors.append(dict(
                    error=type(e).__name__, message=str(e),
                    traceback=traceback.format_exc(), time=time.time()))
                if self._stopping:
                    return
                if self.restarts >= self.max_restarts:
                    self._gave_up = True
                else:
                    self.restarts += 1
                    delay = self.backoff * self.restarts
                    t = threading.Timer(delay, self.start)
                    t.daemon = True
                    self._pending_timer = t
                    t.start()
                    return
            if self.on_giveup is not None:
                self.on_giveup(self.name)

    def stop(self) -> None:
        """Inhibit further restarts and cancel any pending backoff timer.
        Does not interrupt a currently running target — loops are expected
        to observe the fabric's stop() predicate."""
        with self._lock:
            self._stopping = True
            if self._pending_timer is not None:
                self._pending_timer.cancel()
                self._pending_timer = None

    def start(self) -> None:
        with self._lock:
            if self._stopping:  # raced with stop(): timer fired pre-cancel
                return
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=self.name)
            self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def gave_up(self) -> bool:
        with self._lock:
            return self._gave_up

    def join(self, timeout: float) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


class Heartbeat:
    """Liveness pulse for a loop that thread-alive checks can't supervise
    (the learner runs on the caller's own thread): the loop calls
    :meth:`beat` every iteration; a watchdog reads :meth:`age` and treats
    a large value as a stall — frozen thread, wedged collective, dead
    interconnect.  Plain float assignment is GIL-atomic, so no lock."""

    def __init__(self):
        self._last = time.time()

    def beat(self) -> None:
        self._last = time.time()

    def age(self) -> float:
        return time.time() - self._last


class Supervisor:
    """Supervises the fabric's worker threads.

    ``start(name, loop)`` registers and launches a restartable thread;
    ``health()`` reports liveness/restart/error state; ``any_failed`` is
    True once any thread exhausted its restart budget (the orchestrator
    treats that as a stop condition — the reference would simply hang).
    """

    def __init__(self, max_restarts: int = 3, backoff: float = 0.5,
                 on_giveup: Optional[Callable[[str], None]] = None):
        self.max_restarts = max_restarts
        self.backoff = backoff
        self.threads: Dict[str, SupervisedThread] = {}
        self._failed = threading.Event()
        # optional observer invoked (with the thread name) when a thread
        # exhausts its budget — train() wires it to the telemetry
        # registry so the give-up is stamped (``supervisor.gaveup``)
        # even though the log loop may be the very thread that died
        self._on_giveup_cb = on_giveup

    def _giveup(self, name: str) -> None:
        self._failed.set()
        if self._on_giveup_cb is not None:
            try:
                self._on_giveup_cb(name)
            except Exception:  # an observer must never mask the failure
                pass

    def start(self, name: str, loop: Callable[[], None]) -> SupervisedThread:
        if name in self.threads:
            # silent replacement would orphan the old SupervisedThread —
            # its live loop and any pending backoff timer keep running
            # OUTSIDE supervision (unjoinable, uncancellable at shutdown)
            raise ValueError(
                f"thread {name!r} is already supervised; stop() it first "
                "or pick a distinct name")
        t = SupervisedThread(name, loop, self.max_restarts, self.backoff,
                             on_giveup=self._giveup)
        self.threads[name] = t
        t.start()
        return t

    @property
    def any_failed(self) -> bool:
        return self._failed.is_set()

    def health(self) -> Dict[str, Dict[str, object]]:
        out: Dict[str, Dict[str, object]] = {}
        for name, t in self.threads.items():
            with t._lock:
                last = t.errors[-1] if t.errors else None
                out[name] = dict(
                    alive=t.alive, restarts=t.restarts,
                    gave_up=t._gave_up,
                    last_error=(None if last is None
                                else f"{last['error']}: {last['message']}"))
        return out

    def join_all(self, timeout: float) -> None:
        """Stop supervision (no further restarts, pending backoff timers
        cancelled), then join every live thread."""
        for t in self.threads.values():
            t.stop()
        for t in self.threads.values():
            t.join(timeout)
