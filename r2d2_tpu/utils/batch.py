"""Synthetic training-batch construction (the replay wire format).

One canonical builder for every consumer that needs a train-step batch
without a live replay buffer: the benchmark, the multi-chip dry-run, and
tests.  Keys must stay in sync with ``ReplayBuffer.sample_batch`` and
``parallel.sharding.DEVICE_BATCH_KEYS``.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from r2d2_tpu.config import Config


def synthetic_batch(cfg: Config, action_dim: int,
                    rng: np.random.Generator) -> Dict[str, np.ndarray]:
    """A full-size host batch with every sample at maximal window sizes."""
    B, T, L = cfg.batch_size, cfg.seq_len, cfg.learning_steps
    return dict(
        obs=rng.integers(0, 256, (B, T, *cfg.stored_obs_shape), dtype=np.uint8),
        last_action=np.eye(action_dim, dtype=np.float32)[
            rng.integers(0, action_dim, (B, T))],
        last_reward=rng.standard_normal((B, T)).astype(np.float32),
        hidden=(0.1 * rng.standard_normal(
            (B, 2, cfg.lstm_layers, cfg.hidden_dim))).astype(np.float32),
        action=rng.integers(0, action_dim, (B, L)).astype(np.int32),
        n_step_reward=rng.standard_normal((B, L)).astype(np.float32),
        n_step_gamma=np.full((B, L), cfg.gamma ** cfg.forward_steps,
                             np.float32),
        burn_in=np.full((B,), cfg.burn_in_steps, np.int32),
        learning=np.full((B,), L, np.int32),
        forward=np.full((B,), cfg.forward_steps, np.int32),
        is_weights=np.ones((B,), np.float32),
    )
