"""wire-format: the slot layout and CRC conventions live in ONE module.

Five modules speak the shared-memory wire format (``replay/block.py``
defines it; ``parallel/actor_procs.py``,
``parallel/inference_service.py`` and ``parallel/replay_shards.py`` —
the sharded replay plane's block-routing and sample-RPC slabs —
transport over it), and the session tier (``serving/wire.py``) carries
the same conventions onto a local-socket transport for external
clients.  The CRC32 convention — int64 header words, payload arrays in
declared order, the 32-bit mask, written LAST — is a torn-write
detector only as long as the producer and verifier agree bit-for-bit;
a restated literal in one of the transport modules is exactly the kind
of drift that ships silently and corrupts recovery later.

The rule fires in any module that imports ``multiprocessing
.shared_memory`` or ``socket`` (the transport signatures) **other than
the wire-format modules themselves** when it:

- calls ``zlib.crc32`` directly (use ``replay.block.payload_crc32``),
- restates the 32-bit CRC mask literal ``0xFFFFFFFF``,
- re-defines a wire-format function (``slot_layout`` / ``slot_views`` /
  ``slot_crc`` / ``block_slot_spec`` / ``batch_slot_spec`` /
  ``write_block`` / ``read_block`` / ``payload_crc32``) instead of
  importing it,
- uses a wire-format name without importing it from
  ``r2d2_tpu.replay.block``,
- and likewise for the session request/response vocabulary
  (``session_request_spec`` / ``session_response_spec`` /
  ``encode_frame`` / ``decode_frame`` / ``peek_kind`` /
  ``FrameReader``), whose canonical home is
  ``r2d2_tpu.serving.wire`` (itself built ON the replay/block.py
  helpers — one CRC definition all the way down).
"""
from __future__ import annotations

import ast
from typing import List, Set

from r2d2_tpu.analysis.core import Context, Finding, dotted_name, rule

RULE = "wire-format"

WIRE_MODULE = "r2d2_tpu.replay.block"
WIRE_MODULE_SUFFIX = "replay/block.py"
WIRE_NAMES = {"slot_layout", "slot_views", "slot_crc", "block_slot_spec",
              "batch_slot_spec", "write_block", "read_block",
              "payload_crc32", "CRC_MASK", "BATCH_ROW_FIELDS"}
# the session tier's request/response vocabulary: defined once in
# serving/wire.py (on top of the replay/block.py CRC helpers), imported
# by every module that speaks the session protocol
SESSION_WIRE_MODULE = "r2d2_tpu.serving.wire"
SESSION_WIRE_MODULE_SUFFIX = "serving/wire.py"
SESSION_WIRE_NAMES = {"session_request_spec", "session_response_spec",
                      "encode_frame", "decode_frame", "peek_kind",
                      "FrameReader"}
# the cross-host replay fabric's RPC vocabulary: the net frame specs and
# message kinds are canonical in replay/netwire.py (themselves DERIVED
# from replay/block.py's slot specs and framed by serving/wire.py's
# grammar — one CRC definition all the way down); a transport module
# restating a spec or a kind constant is exactly the drift that makes a
# shard and a trainer mis-frame each other's traffic
NET_WIRE_MODULE = "r2d2_tpu.replay.netwire"
NET_WIRE_MODULE_SUFFIX = "replay/netwire.py"
NET_WIRE_NAMES = {"net_hello_spec", "net_ingest_spec",
                  "net_sample_response_spec", "net_feedback_spec",
                  "net_stats_spec", "net_save_spec",
                  "net_save_response_spec", "layout_token",
                  "max_net_frame_bytes", "ingest_shape_header",
                  "NMSG_HELLO", "NMSG_WELCOME", "NMSG_INGEST",
                  "NMSG_SAMPLE_REQ", "NMSG_SAMPLE_RSP", "NMSG_PRIO",
                  "NMSG_STATS", "NMSG_SAVE", "NMSG_SAVE_RSP"}
CRC_MASK_VALUE = 0xFFFFFFFF


def _uses_shared_memory(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.startswith("multiprocessing") for a in node.names):
                # `import multiprocessing as mp` alone isn't shm; require
                # the shared_memory submodule somewhere
                if any(a.name == "multiprocessing.shared_memory"
                       for a in node.names):
                    return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "multiprocessing" and any(
                    a.name == "shared_memory" for a in node.names):
                return True
            if node.module == "multiprocessing.shared_memory":
                return True
    return False


def _uses_socket(tree: ast.AST) -> bool:
    """The session tier's transport signature (serving/wire.py framing
    runs over plain ``socket``)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "socket" or a.name.startswith("socket.")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "socket":
                return True
    return False


def _imports_from(tree: ast.AST, module: str) -> Set[str]:
    """Names imported from one canonical wire module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            out.update(a.asname or a.name for a in node.names)
    return out


# (canonical module, its path suffix, its vocabulary) — the replay slab
# conventions, the session socket conventions and the cross-host replay
# RPC conventions, checked identically
_VOCABULARIES = (
    (WIRE_MODULE, WIRE_MODULE_SUFFIX, WIRE_NAMES),
    (SESSION_WIRE_MODULE, SESSION_WIRE_MODULE_SUFFIX, SESSION_WIRE_NAMES),
    (NET_WIRE_MODULE, NET_WIRE_MODULE_SUFFIX, NET_WIRE_NAMES),
)


@rule(RULE, "transport modules (shm or socket) import the slot layout / "
            "CRC / frame vocabulary from its canonical module instead of "
            "restating literals")
def check_wire_format(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if not (_uses_shared_memory(mod.tree) or _uses_socket(mod.tree)):
            continue
        vocabularies = [
            (module, names, _imports_from(mod.tree, module))
            for module, suffix, names in _VOCABULARIES
            if not mod.rel.endswith(suffix)]
        is_wire_module = len(vocabularies) < len(_VOCABULARIES)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and not is_wire_module:
                d = dotted_name(node.func)
                if d in ("zlib.crc32", "crc32"):
                    findings.append(Finding(
                        RULE, mod.rel, node.lineno,
                        "direct zlib.crc32 in a transport module — "
                        "compute integrity words via "
                        "replay.block.payload_crc32 so producer and "
                        "verifier can never drift"))
            elif (isinstance(node, ast.Constant)
                  and type(node.value) is int
                  and node.value == CRC_MASK_VALUE
                  and not is_wire_module):
                findings.append(Finding(
                    RULE, mod.rel, node.lineno,
                    "restated CRC mask literal 0xFFFFFFFF — import the "
                    "convention from replay.block (payload_crc32/CRC_MASK)"))
            for module, names, imported in vocabularies:
                if (isinstance(node, (ast.FunctionDef, ast.ClassDef))
                        and node.name in names):
                    findings.append(Finding(
                        RULE, mod.rel, node.lineno,
                        f"wire-format {node.name!r} re-defined here — "
                        f"import it from {module}"))
                elif (isinstance(node, ast.Name)
                      and isinstance(node.ctx, ast.Store)
                      and node.id in names):
                    # a constant restated (e.g. a NMSG_* kind literal):
                    # the same drift as a re-defined function
                    findings.append(Finding(
                        RULE, mod.rel, node.lineno,
                        f"wire-format {node.id!r} re-defined here — "
                        f"import it from {module}"))
                elif (isinstance(node, ast.Name)
                      and isinstance(node.ctx, ast.Load)
                      and node.id in names
                      and node.id not in imported):
                    findings.append(Finding(
                        RULE, mod.rel, node.lineno,
                        f"wire-format name {node.id!r} used without "
                        f"importing it from {module}"))
    return findings
