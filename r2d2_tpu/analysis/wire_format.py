"""wire-format: the shm slot layout and CRC live in ONE module.

Four modules speak the shared-memory wire format (``replay/block.py``
defines it; ``parallel/actor_procs.py``,
``parallel/inference_service.py`` and ``parallel/replay_shards.py`` —
the sharded replay plane's block-routing and sample-RPC slabs —
transport over it).  The CRC32 convention — int64 header words, payload
arrays in declared order, the 32-bit mask, written LAST — is a
torn-write detector only as long as the producer and verifier agree
bit-for-bit; a restated literal in one of the transport modules is
exactly the kind of drift that ships silently and corrupts recovery
later.

The rule fires in any module that imports ``multiprocessing
.shared_memory`` (the shm-transport signature) **other than the wire
-format module itself** when it:

- calls ``zlib.crc32`` directly (use ``replay.block.payload_crc32``),
- restates the 32-bit CRC mask literal ``0xFFFFFFFF``,
- re-defines a wire-format function (``slot_layout`` / ``slot_views`` /
  ``slot_crc`` / ``block_slot_spec`` / ``batch_slot_spec`` /
  ``write_block`` / ``read_block`` / ``payload_crc32``) instead of
  importing it,
- uses a wire-format name without importing it from
  ``r2d2_tpu.replay.block``.
"""
from __future__ import annotations

import ast
from typing import List, Set

from r2d2_tpu.analysis.core import Context, Finding, dotted_name, rule

RULE = "wire-format"

WIRE_MODULE = "r2d2_tpu.replay.block"
WIRE_MODULE_SUFFIX = "replay/block.py"
WIRE_NAMES = {"slot_layout", "slot_views", "slot_crc", "block_slot_spec",
              "batch_slot_spec", "write_block", "read_block",
              "payload_crc32", "CRC_MASK", "BATCH_ROW_FIELDS"}
CRC_MASK_VALUE = 0xFFFFFFFF


def _uses_shared_memory(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.startswith("multiprocessing") for a in node.names):
                # `import multiprocessing as mp` alone isn't shm; require
                # the shared_memory submodule somewhere
                if any(a.name == "multiprocessing.shared_memory"
                       for a in node.names):
                    return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "multiprocessing" and any(
                    a.name == "shared_memory" for a in node.names):
                return True
            if node.module == "multiprocessing.shared_memory":
                return True
    return False


def _block_imports(tree: ast.AST) -> Set[str]:
    """Wire-format names imported from the canonical module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.ImportFrom)
                and node.module == WIRE_MODULE):
            out.update(a.asname or a.name for a in node.names)
    return out


@rule(RULE, "shm transport modules import the slot layout / CRC from "
            "replay/block.py instead of restating literals")
def check_wire_format(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if mod.rel.endswith(WIRE_MODULE_SUFFIX):
            continue
        if not _uses_shared_memory(mod.tree):
            continue
        imported = _block_imports(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if d in ("zlib.crc32", "crc32"):
                    findings.append(Finding(
                        RULE, mod.rel, node.lineno,
                        "direct zlib.crc32 in an shm transport module — "
                        "compute integrity words via "
                        "replay.block.payload_crc32 so producer and "
                        "verifier can never drift"))
            elif (isinstance(node, ast.Constant)
                  and type(node.value) is int
                  and node.value == CRC_MASK_VALUE):
                findings.append(Finding(
                    RULE, mod.rel, node.lineno,
                    "restated CRC mask literal 0xFFFFFFFF — import the "
                    "convention from replay.block (payload_crc32/CRC_MASK)"))
            elif (isinstance(node, ast.FunctionDef)
                  and node.name in WIRE_NAMES):
                findings.append(Finding(
                    RULE, mod.rel, node.lineno,
                    f"wire-format function {node.name!r} re-defined here — "
                    f"import it from {WIRE_MODULE}"))
            elif (isinstance(node, ast.Name)
                  and isinstance(node.ctx, ast.Load)
                  and node.id in WIRE_NAMES
                  and node.id not in imported):
                findings.append(Finding(
                    RULE, mod.rel, node.lineno,
                    f"wire-format name {node.id!r} used without importing "
                    f"it from {WIRE_MODULE}"))
    return findings
