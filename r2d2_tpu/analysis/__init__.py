"""graftlint — repo-native static analysis (``python -m r2d2_tpu.analysis``).

Rule families (see docs/ANALYSIS.md for the full reference):

- ``jit-purity``           host effects inside jit-traced code
- ``config-integrity``     cfg.X resolution + field liveness/docs
- ``thread-discipline``    Supervisor-managed threads, locked shared writes
- ``bounded-wait``         supervised loops / thread targets never block
  without a timeout (get/wait/join need timeout=)
- ``wire-format``          shm slot layout / CRC single-sourced in replay/block
- ``telemetry-discipline`` metric names are registered literals, not
  f-strings (the variable part belongs in a label)
- ``donation-discipline``  buffer-donation contracts: no use-after-donate,
  drivetrain jit sites donate state/batch params, no per-iteration
  syncs on donated results
- ``transfer-flow``        implicit device<->host transfers outside jit
  (numpy casts of jitted results, unsharded device_put in mesh
  modules, scalarization in *_loop functions)

Importing this package registers every rule.  The analyzer itself is
pure stdlib ``ast``: the ``r2d2_tpu`` package root does pull in jax at
import time (a few seconds), but the analyzer never calls a jax API or
initializes a device backend — so it is safe to run on a host whose
accelerator claim is wedged (backend init, not ``import jax``, is what
hangs there), and the analysis pass itself finishes in milliseconds.
"""
from r2d2_tpu.analysis.core import (  # noqa: F401
    RULES,
    ConfigSchema,
    Context,
    Finding,
    Report,
    analyze_source,
    rule,
    run_analysis,
)
from r2d2_tpu.analysis import (  # noqa: F401  (import = rule registration)
    bounded_wait,
    config_integrity,
    donation,
    jit_purity,
    telemetry_discipline,
    thread_discipline,
    transfer_flow,
    wire_format,
)


def main(argv=None) -> int:
    """Console entry (pyproject ``r2d2-lint``); same driver as
    ``python -m r2d2_tpu.analysis``."""
    from r2d2_tpu.analysis.__main__ import main as _main

    return _main(argv)


def preflight(root, paths=("r2d2_tpu", "tools")) -> None:
    """Shared fail-fast gate for the long-running tools (tools/soak.py,
    tools/chaos_soak.py): run the analyzer CLI as a bounded subprocess
    over ``paths`` and ``sys.exit`` with its report if the tree is dirty
    — a multi-minute soak must not burn its wall budget proving what the
    analyzer knows up front (misspelled cfg fields, unsupervised
    threads, restated shm CRC literals — docs/ANALYSIS.md)."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-m", "r2d2_tpu.analysis", *paths, "--json"],
            cwd=root, capture_output=True, text=True, timeout=120)
    except subprocess.TimeoutExpired:
        # genuinely bounded: a wedged interpreter start-up (broken jax
        # install, hung filesystem) must fail the preflight, not park the
        # soak forever before its own watchdogs exist
        sys.exit("graftlint preflight timed out after 120 s — the "
                 "analyzer child never finished; fix the host before "
                 "burning a soak budget")
    if proc.returncode != 0:
        print(proc.stdout or proc.stderr, file=sys.stderr)
        sys.exit("graftlint preflight failed — fix the findings above "
                 "before burning a soak budget")
