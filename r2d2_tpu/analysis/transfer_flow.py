"""transfer-flow: implicit device↔host transfers outside jit.

jit-purity polices host effects *inside* the traced graph; this family
covers the other side of the boundary — host code that moves device
buffers implicitly, which is exactly what the runtime
``TRANSFER_GUARD`` windows (utils/trace.py) reject when armed.  The
declared-transfer budget (one H2D per dispatch, one D2H per harvest)
only holds if every crossing is explicit and intentional:

- ``implicit-transfer`` — ``np.asarray``/``np.array`` applied to the
  result of a jitted callable (directly, or via a name bound from its
  call).  A numpy cast of a device array is an implicit synchronous
  D2H; the declared sites use explicit ``jax.device_get`` (one fetch,
  guard-exempt under ``transfer_guard("disallow")``) inside a
  ``HOST_TRANSFERS.allowed(...)`` span.
- ``unsharded-device-put`` — ``jax.device_put(x)`` with no sharding /
  device argument in the mesh-aware modules (``parallel/``,
  ``learner/``): the buffer lands wherever jax's default device points,
  which on a multi-device mesh silently un-shards the input path.
- ``host-scalar-loop`` — ``float()``/``int()`` scalarization of a
  jitted callable's result inside a ``*_loop`` function: a
  per-iteration blocking D2H of one scalar, the classic hidden
  dispatch stall.

Message code prefixes (``implicit-transfer:``, ``unsharded-device-put:``,
``host-scalar-loop:``) are documented in docs/ANALYSIS.md; the
suppression key is the family name ``transfer-flow``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from r2d2_tpu.analysis.core import Context, Finding, dotted_name, rule
from r2d2_tpu.analysis.donation import (
    _DonateSite,
    _bound_name,
    _callee_name,
    collect_donating_sites,
)
from r2d2_tpu.analysis.jit_purity import _FuncNode

RULE = "transfer-flow"

_NP_CASTS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_MESH_SCOPES = ("r2d2_tpu/parallel/", "r2d2_tpu/learner/")
_SHARDING_KWARGS = {"device", "sharding", "donate"}


def _jit_bound_names(tree: ast.AST) -> Dict[str, _DonateSite]:
    """Every local/attr name bound to a jit/pjit result (donating or
    not) — donation.py's collector already resolves the assignment,
    decorator, factory-return and wrap idioms."""
    return collect_donating_sites(tree)


def _is_device_get(node) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) == "jax.device_get")


def _check_implicit_transfer(rel: str, fn: ast.AST,
                             jit_names: Dict[str, _DonateSite],
                             out: List[Finding],
                             seen: Set[Tuple[int, str]]) -> None:
    # names assigned (possibly tuple-unpacked) from a jitted call
    results: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            callee = _callee_name(node.value)
            if callee in jit_names:
                for t in node.targets:
                    for el in (t.elts if isinstance(t, (ast.Tuple,
                                                        ast.List))
                               else [t]):
                        name = _bound_name(el)
                        if name:
                            results.add(name)

    def emit(line: int, msg: str) -> None:
        key = (line, msg)
        if key not in seen:
            seen.add(key)
            out.append(Finding(RULE, rel, line, msg))

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func) or ""
        if d not in _NP_CASTS or not node.args:
            continue
        arg = node.args[0]
        if _is_device_get(arg):
            continue  # np.asarray(jax.device_get(x)): explicit fetch
        target: Optional[str] = None
        if isinstance(arg, ast.Call) and _callee_name(arg) in jit_names:
            target = f"{_callee_name(arg)}(...)"
        elif isinstance(arg, ast.Name) and arg.id in results:
            target = arg.id
        elif (isinstance(arg, ast.Attribute)
              and isinstance(arg.value, ast.Name)
              and arg.attr in results):
            target = arg.attr
        if target is not None:
            emit(node.lineno,
                 f"implicit-transfer: {d}({target}) materializes a "
                 f"jitted callable's device result via an implicit "
                 f"D2H — use jax.device_get inside a "
                 f"HOST_TRANSFERS.allowed(...) span")


def _check_device_put(rel: str, tree: ast.AST, out: List[Finding]
                      ) -> None:
    if not rel.startswith(_MESH_SCOPES):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if dotted_name(node.func) != "jax.device_put":
            continue
        has_placement = (len(node.args) >= 2
                         or any(kw.arg in _SHARDING_KWARGS
                                for kw in node.keywords))
        if not has_placement:
            out.append(Finding(
                RULE, rel, node.lineno,
                "unsharded-device-put: jax.device_put without an "
                "explicit sharding/device in a mesh-aware module — the "
                "buffer lands on the default device and un-shards the "
                "input path"))


def _check_host_scalar_loop(rel: str, fn: ast.AST,
                            jit_names: Dict[str, _DonateSite],
                            out: List[Finding],
                            seen: Set[Tuple[int, str]]) -> None:
    if not getattr(fn, "name", "").endswith("_loop"):
        return
    results: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            callee = _callee_name(node.value)
            if callee in jit_names:
                for t in node.targets:
                    for el in (t.elts if isinstance(t, (ast.Tuple,
                                                        ast.List))
                               else [t]):
                        if isinstance(el, ast.Name):
                            results.add(el.id)
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int")
                and len(node.args) == 1):
            continue
        arg = node.args[0]
        hit: Optional[str] = None
        if isinstance(arg, ast.Name) and arg.id in results:
            hit = arg.id
        elif (isinstance(arg, ast.Call)
              and _callee_name(arg) in jit_names):
            hit = f"{_callee_name(arg)}(...)"
        if hit is not None:
            key = (node.lineno, hit)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                RULE, rel, node.lineno,
                f"host-scalar-loop: {node.func.id}({hit}) inside loop "
                f"function {fn.name!r} blocks on a device scalar every "
                f"iteration — fetch once behind the declared harvest "
                f"site"))


@rule(RULE, "implicit device<->host transfers outside jit: numpy casts "
            "of jitted results, unsharded device_put in mesh modules, "
            "per-iteration scalarization in *_loop functions")
def check_transfer_flow(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        jit_names = _jit_bound_names(mod.tree)
        _check_device_put(mod.rel, mod.tree, findings)
        seen: Set[Tuple[int, str]] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, _FuncNode):
                _check_implicit_transfer(mod.rel, node, jit_names,
                                         findings, seen)
                _check_host_scalar_loop(mod.rel, node, jit_names,
                                        findings, seen)
    return findings
