"""config-integrity: every ``cfg.X`` resolves, every field earns its keep.

The frozen ``Config`` dataclass is referenced as bare attribute strings
(~50 fields across the tree); a typo'd ``cfg.leraning_steps`` is a
silent ``AttributeError`` at runtime — or worse, a ``getattr`` default
that quietly disables a feature.  Three checks:

1. **resolution** — every attribute access on a config-shaped receiver
   (a name that is or ends with ``cfg``/``config``, or ``*.cfg``), every
   ``getattr(cfg, "X")`` string, and every keyword of ``cfg.replace(...)``
   must name a real Config field / property / method.
2. **liveness** — every declared field must be referenced somewhere in
   the analyzed tree outside ``config.py`` itself (dead knobs rot).
3. **mention** — every field must appear (word-boundary) in the CLI
   module, README, or a ``docs/*.md`` file, so operators can discover it
   (the knob table in docs/OPERATIONS.md is the canonical home).

Checks 2 and 3 only run when the analyzed set includes the module that
defines ``Config`` (so fixture snippets exercise check 1 alone).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from r2d2_tpu.analysis.core import Context, Finding, rule

RULE = "config-integrity"

# attribute names every dataclass instance has; never worth flagging
_DATACLASS_ATTRS = {"replace", "__post_init__", "__dataclass_fields__"}


def _is_config_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        n = node.id.lower()
        return n in ("cfg", "config") or n.endswith("cfg") \
            or n.endswith("_config")
    if isinstance(node, ast.Attribute):
        a = node.attr.lower()
        return a == "cfg" or a.endswith("_cfg")
    return False


def _config_attr_uses(tree: ast.AST) -> List[Tuple[str, int, str]]:
    """(field, line, kind) for every config-shaped reference."""
    uses: List[Tuple[str, int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and _is_config_receiver(
                node.value):
            uses.append((node.attr, node.lineno, "attribute"))
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Name) and f.id == "getattr"
                    and len(node.args) >= 2
                    and _is_config_receiver(node.args[0])
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                uses.append((node.args[1].value, node.lineno, "getattr"))
            elif (isinstance(f, ast.Attribute) and f.attr == "replace"
                    and _is_config_receiver(f.value)):
                for kw in node.keywords:
                    if kw.arg is not None:
                        uses.append((kw.arg, node.lineno, "replace kwarg"))
    return uses


@rule(RULE, "cfg.X references resolve to real Config fields; every field "
            "is referenced and documented")
def check_config_integrity(ctx: Context) -> List[Finding]:
    schema = ctx.config_schema
    if schema is None:
        return []
    findings: List[Finding] = []
    valid = schema.valid_attrs | _DATACLASS_ATTRS
    referenced: Set[str] = set()
    # loose reference census for the liveness check: ANY attribute access
    # or string literal naming a field counts (receivers are heuristic;
    # liveness must not produce false "dead field" findings because a
    # config travelled under an unusual name)
    loose_attr: Dict[str, int] = {}

    analyzed_has_config = False
    for mod in ctx.modules:
        is_config_mod = (mod.rel == schema.module_rel)
        analyzed_has_config = analyzed_has_config or is_config_mod
        for name, line, kind in _config_attr_uses(mod.tree):
            if not is_config_mod:
                referenced.add(name)
            if name.startswith("__") or name in valid:
                continue
            findings.append(Finding(
                RULE, mod.rel, line,
                f"{kind} {name!r} does not resolve to a Config "
                "field/property (typo or removed knob?)"))
        if is_config_mod:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                loose_attr[node.attr] = loose_attr.get(node.attr, 0) + 1
            elif (isinstance(node, ast.Constant)
                  and isinstance(node.value, str)
                  and node.value in schema.fields):
                referenced.add(node.value)
            elif isinstance(node, ast.keyword) and node.arg is not None:
                # preset/test kwargs (test_config(burn_in_steps=...))
                # count as live uses of the knob
                if node.arg in schema.fields:
                    referenced.add(node.arg)

    if not analyzed_has_config:
        return findings

    docs = "\n".join(ctx.doc_texts())
    for field in sorted(schema.fields):
        line = schema.field_lines.get(field, 1)
        if field not in referenced and loose_attr.get(field, 0) == 0:
            findings.append(Finding(
                RULE, schema.module_rel, line,
                f"Config field {field!r} is never referenced outside "
                "config.py (dead knob — delete it or wire it up)"))
        if not re.search(rf"\b{re.escape(field)}\b", docs):
            findings.append(Finding(
                RULE, schema.module_rel, line,
                f"Config field {field!r} has no CLI/docs mention (add it "
                "to the docs/OPERATIONS.md knob table)"))
    return findings
