"""config-integrity: every ``cfg.X`` resolves, every field earns its keep.

The frozen ``Config`` dataclass is referenced as bare attribute strings
(~50 fields across the tree); a typo'd ``cfg.leraning_steps`` is a
silent ``AttributeError`` at runtime — or worse, a ``getattr`` default
that quietly disables a feature.  Three checks:

1. **resolution** — every attribute access on a config-shaped receiver
   (a name that is or ends with ``cfg``/``config``, or ``*.cfg``), every
   ``getattr(cfg, "X")`` string, and every keyword of ``cfg.replace(...)``
   must name a real Config field / property / method.
2. **liveness** — every declared field must be referenced somewhere in
   the analyzed tree outside ``config.py`` itself (dead knobs rot).
3. **mention** — every field must appear (word-boundary) in the CLI
   module, README, or a ``docs/*.md`` file, so operators can discover it
   (the knob table in docs/OPERATIONS.md is the canonical home).

Checks 2 and 3 only run when the analyzed set includes the module that
defines ``Config`` (so fixture snippets exercise check 1 alone).
"""
from __future__ import annotations

import ast
import json
import re
from typing import Dict, List, Set, Tuple

from r2d2_tpu.analysis.core import Context, Finding, rule

RULE = "config-integrity"

# attribute names every dataclass instance has; never worth flagging
_DATACLASS_ATTRS = {"replace", "__post_init__", "__dataclass_fields__"}

# --- population_spec JSON validation (r2d2_tpu/league, docs/LEAGUE.md) ----
# Inline population specs (a string literal bound to a ``population_spec``
# keyword or assignment) are config too: a misspelled member knob must
# fail lint, not silently no-op at 3 a.m.  The member-object vocabulary is
# restated here rather than imported — the analyzer is pure-stdlib AST and
# must not execute repo code; tests/test_league.py pins these against
# config.POPULATION_META_KEYS / POPULATION_MEMBER_FIELDS /
# POPULATION_PRESETS so the two can never drift.
_POPULATION_KEY = "population_spec"
_POPULATION_META_KEYS = {"name", "preset"}
_POPULATION_PRESETS = {"default", "low_resource"}
_POPULATION_MEMBER_FIELDS = {
    "game_name", "seed", "base_eps", "eps_alpha",
    "gamma", "max_episode_steps", "actor_update_interval",
    "test_epsilon", "eval_episodes", "noop_max",
}


def _population_spec_literals(tree: ast.AST):
    """(spec string, line) for every inline ``population_spec`` literal:
    keyword arguments (``Config(population_spec="[...]")``, ``replace``/
    preset kwargs) and plain assignments.  Specs built dynamically or
    passed through variables are runtime-validation territory
    (config.parse_population)."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.keyword)
                and node.arg == _POPULATION_KEY
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            yield node.value.value, node.value.lineno
        elif isinstance(node, ast.Assign):
            if (any(isinstance(t, ast.Name) and t.id == _POPULATION_KEY
                    for t in node.targets)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                yield node.value.value, node.value.lineno


def _check_population_spec(spec: str, fields: Set[str], rel: str,
                           line: int) -> List[Finding]:
    """Validate one inline spec against the Config schema — the lint
    twin of ``config.parse_population`` (structure + key resolution;
    value-range checks stay runtime-only)."""
    out: List[Finding] = []
    if not spec:
        return out   # "" = population disabled, the default
    try:
        raw = json.loads(spec)
    except ValueError as e:
        return [Finding(RULE, rel, line,
                        f"population_spec literal is not valid JSON "
                        f"({e})")]
    if not isinstance(raw, list):
        return [Finding(RULE, rel, line,
                        "population_spec must be a JSON list of member "
                        "objects")]
    for i, m in enumerate(raw):
        if not isinstance(m, dict):
            out.append(Finding(RULE, rel, line,
                               f"population member {i} is not a JSON "
                               "object"))
            continue
        preset = m.get("preset", "default")
        if preset not in _POPULATION_PRESETS:
            out.append(Finding(
                RULE, rel, line,
                f"population member {i}: unknown preset {preset!r} "
                f"(expected one of {sorted(_POPULATION_PRESETS)})"))
        for k in m:
            if k in _POPULATION_META_KEYS:
                continue
            if k not in fields:
                out.append(Finding(
                    RULE, rel, line,
                    f"population member {i} key {k!r} does not resolve "
                    "to a Config field (typo or removed knob?)"))
            elif k not in _POPULATION_MEMBER_FIELDS:
                out.append(Finding(
                    RULE, rel, line,
                    f"population member {i} key {k!r} is not "
                    "population-overridable (members share the "
                    "learner's network/replay geometry — see "
                    "config.POPULATION_MEMBER_FIELDS)"))
    return out


def _is_config_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        n = node.id.lower()
        return n in ("cfg", "config") or n.endswith("cfg") \
            or n.endswith("_config")
    if isinstance(node, ast.Attribute):
        a = node.attr.lower()
        return a == "cfg" or a.endswith("_cfg")
    return False


def _config_attr_uses(tree: ast.AST) -> List[Tuple[str, int, str]]:
    """(field, line, kind) for every config-shaped reference."""
    uses: List[Tuple[str, int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and _is_config_receiver(
                node.value):
            uses.append((node.attr, node.lineno, "attribute"))
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Name) and f.id == "getattr"
                    and len(node.args) >= 2
                    and _is_config_receiver(node.args[0])
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                uses.append((node.args[1].value, node.lineno, "getattr"))
            elif (isinstance(f, ast.Attribute) and f.attr == "replace"
                    and _is_config_receiver(f.value)):
                for kw in node.keywords:
                    if kw.arg is not None:
                        uses.append((kw.arg, node.lineno, "replace kwarg"))
    return uses


@rule(RULE, "cfg.X references resolve to real Config fields; every field "
            "is referenced and documented")
def check_config_integrity(ctx: Context) -> List[Finding]:
    schema = ctx.config_schema
    if schema is None:
        return []
    findings: List[Finding] = []
    valid = schema.valid_attrs | _DATACLASS_ATTRS
    referenced: Set[str] = set()
    # loose reference census for the liveness check: ANY attribute access
    # or string literal naming a field counts (receivers are heuristic;
    # liveness must not produce false "dead field" findings because a
    # config travelled under an unusual name)
    loose_attr: Dict[str, int] = {}

    analyzed_has_config = False
    for mod in ctx.modules:
        is_config_mod = (mod.rel == schema.module_rel)
        analyzed_has_config = analyzed_has_config or is_config_mod
        for name, line, kind in _config_attr_uses(mod.tree):
            if not is_config_mod:
                referenced.add(name)
            if name.startswith("__") or name in valid:
                continue
            findings.append(Finding(
                RULE, mod.rel, line,
                f"{kind} {name!r} does not resolve to a Config "
                "field/property (typo or removed knob?)"))
        # inline population specs validate against the same schema —
        # a misspelled member knob is a finding, not a silent no-op
        # (config.py itself is exempt: POPULATION_PRESETS et al. are
        # the vocabulary's definition site, not a user spec)
        if not is_config_mod:
            for spec, line in _population_spec_literals(mod.tree):
                findings.extend(_check_population_spec(
                    spec, schema.fields, mod.rel, line))
        if is_config_mod:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                loose_attr[node.attr] = loose_attr.get(node.attr, 0) + 1
            elif (isinstance(node, ast.Constant)
                  and isinstance(node.value, str)
                  and node.value in schema.fields):
                referenced.add(node.value)
            elif isinstance(node, ast.keyword) and node.arg is not None:
                # preset/test kwargs (test_config(burn_in_steps=...))
                # count as live uses of the knob
                if node.arg in schema.fields:
                    referenced.add(node.arg)

    if not analyzed_has_config:
        return findings

    docs = "\n".join(ctx.doc_texts())
    for field in sorted(schema.fields):
        line = schema.field_lines.get(field, 1)
        if field not in referenced and loose_attr.get(field, 0) == 0:
            findings.append(Finding(
                RULE, schema.module_rel, line,
                f"Config field {field!r} is never referenced outside "
                "config.py (dead knob — delete it or wire it up)"))
        if not re.search(rf"\b{re.escape(field)}\b", docs):
            findings.append(Finding(
                RULE, schema.module_rel, line,
                f"Config field {field!r} has no CLI/docs mention (add it "
                "to the docs/OPERATIONS.md knob table)"))
    return findings
