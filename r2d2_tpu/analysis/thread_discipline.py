"""thread-discipline: no unsupervised threads, no unlocked shared writes.

The fabric's liveness story (utils/supervisor.py) depends on every
long-running loop being Supervisor-managed: a bare ``threading.Thread``
that dies takes its plane down silently — exactly the reference's
fire-and-forget daemons this repo was built to retire.  Two checks:

1. **bare threads** — any ``threading.Thread(...)`` construction outside
   the allowlisted supervisor module is a finding.  Legitimate uses
   (bounded, joined measurement workers; subprocess-local drains) carry a
   per-line ``# graftlint: disable=thread-discipline -- <why safe>``.
2. **shared writes** — inside a thread-target function (a ``target=``
   argument or a ``*_loop``-named function), assigning an attribute of a
   closed-over object without a surrounding ``with <...lock...>:`` is a
   finding: cross-thread state belongs in a Lock-protected structure, a
   Queue, or an Event.  (Heuristic: writes to ``self`` inside methods and
   to function-local objects are exempt.)
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from r2d2_tpu.analysis.core import Context, Finding, dotted_name, rule

RULE = "thread-discipline"

# the supervision framework itself is the one sanctioned Thread site
ALLOWLISTED_SUFFIXES = ("utils/supervisor.py",)

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def _thread_calls(tree: ast.AST) -> List[ast.Call]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in ("threading.Thread", "Thread"):
                out.append(node)
    return out


def _target_functions(tree: ast.AST) -> List[ast.AST]:
    """Functions that run on their own thread: ``target=`` arguments of
    Thread calls plus the ``*_loop`` naming convention."""
    by_name = {n.name: n for n in ast.walk(tree) if isinstance(n, _FuncNode)}
    out: List[ast.AST] = []
    seen: Set[int] = set()

    def add(fn: Optional[ast.AST]) -> None:
        if fn is not None and id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)

    for call in _thread_calls(tree):
        for kw in call.keywords:
            if kw.arg == "target":
                if isinstance(kw.value, ast.Name):
                    add(by_name.get(kw.value.id))
                elif isinstance(kw.value, ast.Lambda):
                    add(kw.value)
    for name, fn in by_name.items():
        if name.endswith("_loop"):
            add(fn)
    return out


def _local_names(fn: ast.AST) -> Set[str]:
    """Parameters + names assigned inside the function (its own objects —
    writes to their attributes are thread-private)."""
    names: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.For)):
            t = getattr(node, "target", None)
            if isinstance(t, ast.Name):
                names.add(t.id)
        elif isinstance(node, ast.withitem):
            if isinstance(node.optional_vars, ast.Name):
                names.add(node.optional_vars.id)
    return names


def _lockish(expr: ast.AST) -> bool:
    """Does a with-context expression look like a lock acquisition?"""
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is not None and "lock" in name.lower():
            return True
    return False


def _shared_write_findings(rel: str, fn: ast.AST) -> List[Finding]:
    out: List[Finding] = []
    locals_ = _local_names(fn)

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.With):
            locked_here = locked or any(_lockish(item.context_expr)
                                        for item in node.items)
            for child in node.body:
                visit(child, locked_here)
            return
        if isinstance(node, _FuncNode) and node is not fn:
            return  # nested defs judged on their own if they are targets
        if isinstance(node, (ast.Assign, ast.AugAssign)) and not locked:
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id not in locals_
                        and t.value.id != "self"):
                    out.append(Finding(
                        RULE, rel, t.lineno,
                        f"thread target {getattr(fn, 'name', '<lambda>')!r} "
                        f"writes shared attribute {t.value.id}.{t.attr} "
                        "without a lock — use a Lock/Queue/Event or "
                        "suppress with the reason it is single-writer"))
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    body = fn.body if isinstance(fn.body, list) else [fn.body]  # Lambda
    for stmt in body:
        visit(stmt, False)
    return out


@rule(RULE, "threads run under the Supervisor (or carry a justified "
            "suppression); thread targets don't write shared state "
            "unlocked")
def check_thread_discipline(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if any(mod.rel.endswith(sfx) for sfx in ALLOWLISTED_SUFFIXES):
            continue
        for call in _thread_calls(mod.tree):
            findings.append(Finding(
                RULE, mod.rel, call.lineno,
                "bare threading.Thread outside the Supervisor — run the "
                "loop via utils.supervisor.Supervisor.start (restart "
                "budget + health reporting) or suppress with a reason it "
                "is fire-and-forget safe"))
        for fn in _target_functions(mod.tree):
            findings.extend(_shared_write_findings(mod.rel, fn))
    return findings
