"""graftlint core: the rule framework behind ``python -m r2d2_tpu.analysis``.

Repo-native AST static analysis (no third-party deps, no jax API calls,
no backend init — this module is importable without the package root):
a registry of *rule families*, each a function from an :class:`Context`
(the parsed module set plus repo-level metadata such as the ``Config``
field table) to a list of :class:`Finding`\\ s.  The driver filters
findings through per-line suppressions and renders human or JSON output.

Why in-repo instead of flake8 plugins: every rule here checks an invariant
*of this codebase* — jit purity over our own entry points, ``cfg.X``
resolution against our frozen dataclass, thread discipline against our
Supervisor, wire-format single-sourcing against ``replay/block.py``.
Generic linters cannot see any of that, and reviewers demonstrably stop
re-checking it by hand after a few PRs (the motivation in ISSUE 4).

Suppression syntax (per line, with an optional reason after ``--``)::

    thread = threading.Thread(...)  # graftlint: disable=thread-discipline -- joined 3 lines down

Multiple rules separate with commas; ``disable=all`` silences every rule
for that line.  Suppressed findings are still counted and reported (so a
suppression can never rot invisibly).

Adding a rule: write ``@rule("my-family", "one-line doc") def check(ctx):
...`` in a module under ``r2d2_tpu/analysis/`` and import it from
``__init__``; see docs/ANALYSIS.md for the walkthrough.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set

SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([\w,\-]+)(?:\s*--\s*(.+))?")

# rel-path suffixes never analyzed (generated / vendored would go here)
SKIP_PARTS = ("__pycache__",)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # root-relative, forward slashes
    line: int
    message: str
    # the "-- reason" text of the matching suppression comment; set only
    # on suppressed findings (baseline files record it per suppression)
    reason: Optional[str] = None

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file: AST + per-line suppression table."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        # suppressions come from genuine COMMENT tokens only — a
        # "# graftlint: disable=..." inside a string literal or docstring
        # (e.g. a pasted doc example) must never silence a real finding
        self.suppressions: Dict[int, Set[str]] = {}
        self.suppress_reasons: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = SUPPRESS_RE.search(tok.string)
                if m:
                    self.suppressions[tok.start[0]] = {
                        r.strip() for r in m.group(1).split(",")
                        if r.strip()}
                    if m.group(2):
                        self.suppress_reasons[tok.start[0]] = \
                            m.group(2).strip()
        except tokenize.TokenError:  # ast.parse above accepted it; keep
            pass                     # whatever comments tokenized cleanly

    def suppressed(self, rule_name: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return rules is not None and (rule_name in rules or "all" in rules)

    def suppress_reason(self, line: int) -> Optional[str]:
        return self.suppress_reasons.get(line)


@dataclasses.dataclass
class Rule:
    name: str
    doc: str
    check: Callable[["Context"], List[Finding]]


RULES: Dict[str, Rule] = {}


def rule(name: str, doc: str):
    """Register a rule family: ``check(ctx) -> [Finding, ...]``."""
    def deco(fn):
        RULES[name] = Rule(name, doc, fn)
        return fn
    return deco


class ConfigSchema:
    """The ``Config`` dataclass field table, parsed from its AST (never
    imported — the analyzer must run without jax on the path)."""

    def __init__(self, fields: Sequence[str], properties: Sequence[str] = (),
                 methods: Sequence[str] = (), module_rel: str = "",
                 field_lines: Optional[Dict[str, int]] = None):
        self.fields = set(fields)
        self.properties = set(properties)
        self.methods = set(methods)
        self.module_rel = module_rel
        self.field_lines = dict(field_lines or {})

    @property
    def valid_attrs(self) -> Set[str]:
        return self.fields | self.properties | self.methods

    @classmethod
    def from_module(cls, mod: Module) -> Optional["ConfigSchema"]:
        for node in mod.tree.body:
            if not (isinstance(node, ast.ClassDef) and node.name == "Config"):
                continue
            fields, props, methods, lines = [], [], [], {}
            for item in node.body:
                if (isinstance(item, ast.AnnAssign)
                        and isinstance(item.target, ast.Name)):
                    fields.append(item.target.id)
                    lines[item.target.id] = item.lineno
                elif isinstance(item, ast.FunctionDef):
                    decs = {dotted_name(d) for d in item.decorator_list}
                    (props if "property" in decs else methods).append(
                        item.name)
            return cls(fields, props, methods, mod.rel, lines)
        return None


class Context:
    """What every rule sees: the parsed modules plus repo metadata."""

    def __init__(self, modules: Sequence[Module], root: Path,
                 config_schema: Optional[ConfigSchema] = None):
        self.modules = list(modules)
        self.root = root
        if config_schema is None:
            for mod in self.modules:
                config_schema = ConfigSchema.from_module(mod)
                if config_schema is not None:
                    break
        if config_schema is None:
            # targeted run that excludes config.py (e.g. `r2d2-lint
            # some/file.py`): fall back to the repo's canonical config so
            # misspelled cfg.X still fails instead of no-opping to a
            # false "clean".  Field-side checks (liveness/docs) stay
            # gated on config.py being IN the analyzed set.
            p = root / "r2d2_tpu" / "config.py"
            if p.is_file():
                try:
                    config_schema = ConfigSchema.from_module(
                        Module(p, "r2d2_tpu/config.py",
                               p.read_text(errors="replace")))
                except SyntaxError:
                    pass
        self.config_schema = config_schema

    def doc_texts(self) -> List[str]:
        """Prose the config-integrity mention check searches: the CLI
        module plus every markdown file under docs/ and the README."""
        texts = []
        for cand in [self.root / "r2d2_tpu" / "cli.py",
                     self.root / "README.md"]:
            if cand.is_file():
                texts.append(cand.read_text(errors="replace"))
        docs = self.root / "docs"
        if docs.is_dir():
            for p in sorted(docs.rglob("*.md")):
                texts.append(p.read_text(errors="replace"))
        return texts


@dataclasses.dataclass
class Report:
    findings: List[Finding]          # unsuppressed — these fail the build
    suppressed: List[Finding]        # matched a disable comment
    errors: List[Finding]            # unparseable files
    files: int
    rules: List[str]

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def to_dict(self) -> dict:
        return dict(
            ok=self.ok,
            files=self.files,
            rules=self.rules,
            findings=[f.to_dict() for f in self.findings],
            suppressed=[f.to_dict() for f in self.suppressed],
            errors=[f.to_dict() for f in self.errors],
        )


# ---------------------------------------------------------------- helpers

def dotted_name(node) -> Optional[str]:
    """``a.b.c`` for an Attribute/Name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_py_files(paths: Sequence[Path]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            out.extend(sorted(
                f for f in p.rglob("*.py")
                if not any(part in SKIP_PARTS for part in f.parts)))
    return out


def load_modules(paths: Sequence[Path], root: Path
                 ) -> tuple[List[Module], List[Finding]]:
    modules, errors = [], []
    for f in iter_py_files(paths):
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            modules.append(Module(f, rel, f.read_text(errors="replace")))
        except SyntaxError as e:
            errors.append(Finding("parse", rel, e.lineno or 0,
                                  f"syntax error: {e.msg}"))
    return modules, errors


def run_analysis(paths: Sequence[str], root: Optional[str] = None,
                 config_schema: Optional[ConfigSchema] = None,
                 rules: Optional[Sequence[str]] = None) -> Report:
    """Run every registered rule over ``paths`` and split the findings
    into live vs suppressed.  ``root`` anchors relative paths and the
    docs lookup (defaults to cwd)."""
    rootp = Path(root) if root is not None else Path.cwd()
    modules, errors = load_modules([Path(p) for p in paths], rootp)
    ctx = Context(modules, rootp, config_schema=config_schema)
    by_rel = {m.rel: m for m in modules}
    live: List[Finding] = []
    quiet: List[Finding] = []
    names = list(rules) if rules is not None else sorted(RULES)
    for name in names:
        for f in RULES[name].check(ctx):
            mod = by_rel.get(f.path)
            if mod is not None and mod.suppressed(f.rule, f.line):
                quiet.append(dataclasses.replace(
                    f, reason=mod.suppress_reason(f.line)))
            else:
                live.append(f)
    live.sort(key=lambda f: (f.path, f.line, f.rule))
    quiet.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=live, suppressed=quiet, errors=errors,
                  files=len(modules), rules=names)


def analyze_source(source: str, name: str = "fixture.py",
                   config_schema: Optional[ConfigSchema] = None,
                   rules: Optional[Sequence[str]] = None) -> Report:
    """Analyze an in-memory snippet — the test-fixture entry point."""
    mod = Module(Path(name), name, source)
    ctx = Context([mod], Path("."), config_schema=config_schema)
    live: List[Finding] = []
    quiet: List[Finding] = []
    names = list(rules) if rules is not None else sorted(RULES)
    for rn in names:
        for f in RULES[rn].check(ctx):
            if mod.suppressed(f.rule, f.line):
                quiet.append(dataclasses.replace(
                    f, reason=mod.suppress_reason(f.line)))
            else:
                live.append(f)
    return Report(findings=live, suppressed=quiet, errors=[], files=1,
                  rules=names)
