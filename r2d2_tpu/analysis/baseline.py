"""Baseline-file mode: a versioned findings+suppressions snapshot.

``python -m r2d2_tpu.analysis --baseline GRAFTLINT_BASELINE.json``
checks the live report against the committed snapshot and exits 1 with
a diff on any drift; ``--write-baseline`` regenerates it.  The snapshot
pins, per ``(path, rule)``:

- every **suppression** in the tree with its count and the ``-- reason``
  texts (so the pinned set can only grow when a reason is recorded and
  the baseline is deliberately regenerated in the same commit), and
- every **live finding** (normally the empty list — a non-empty
  findings section means the tree was baselined dirty, which the check
  output calls out loudly).

Findings are matched on ``(path, rule, message)`` — not line numbers,
which drift with every unrelated edit; rule messages carry enough
identity (variable names, callee, finding-code prefix).  The check is
exact in both directions: a *stale* baseline entry (suppression removed
from the tree but not from the snapshot) fails too, so the committed
file can never over-claim what the tree actually suppresses.

tests/test_static_analysis.py's pinned-suppression-set test reads this
file instead of a hand-edited literal set.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Tuple

from r2d2_tpu.analysis.core import Report

BASELINE_VERSION = 1


def snapshot(report: Report) -> dict:
    sup: Dict[Tuple[str, str], dict] = {}
    for f in report.suppressed:
        e = sup.setdefault((f.path, f.rule), {"count": 0, "reasons": []})
        e["count"] += 1
        if f.reason and f.reason not in e["reasons"]:
            e["reasons"].append(f.reason)
    return {
        "version": BASELINE_VERSION,
        "findings": sorted(
            ({"path": f.path, "rule": f.rule, "message": f.message}
             for f in report.findings),
            key=lambda d: (d["path"], d["rule"], d["message"])),
        "suppressions": [
            {"path": p, "rule": r, "count": e["count"],
             "reasons": sorted(e["reasons"])}
            for (p, r), e in sorted(sup.items())],
    }


def write(path: str, report: Report) -> None:
    Path(path).write_text(json.dumps(snapshot(report), indent=1) + "\n")


def load(path: str) -> dict:
    data = json.loads(Path(path).read_text())
    ver = data.get("version")
    if ver != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: version {ver!r}, expected "
            f"{BASELINE_VERSION} — regenerate with --write-baseline")
    return data


def diff(baseline: dict, report: Report) -> List[str]:
    """Human-readable drift lines; empty means the tree matches."""
    problems: List[str] = []

    base_f = {(f["path"], f["rule"], f["message"])
              for f in baseline.get("findings", [])}
    live_f = {(f.path, f.rule, f.message) for f in report.findings}
    for p, r, m in sorted(live_f - base_f):
        problems.append(f"new finding not in baseline: {p}: [{r}] {m}")
    for p, r, m in sorted(base_f - live_f):
        problems.append(f"stale baseline finding (fixed in tree — "
                        f"regenerate): {p}: [{r}] {m}")

    base_s = {(s["path"], s["rule"]): s
              for s in baseline.get("suppressions", [])}
    live_s: Dict[Tuple[str, str], int] = {}
    for f in report.suppressed:
        k = (f.path, f.rule)
        live_s[k] = live_s.get(k, 0) + 1
    for k in sorted(set(live_s) - set(base_s)):
        problems.append(
            f"new suppression not in baseline: {k[0]} [{k[1]}] — record "
            f"a '-- reason' and regenerate with --write-baseline")
    for k in sorted(set(base_s) - set(live_s)):
        problems.append(
            f"stale baseline suppression (removed from tree — "
            f"regenerate): {k[0]} [{k[1]}]")
    for k in sorted(set(base_s) & set(live_s)):
        if base_s[k]["count"] != live_s[k]:
            problems.append(
                f"suppression count drift: {k[0]} [{k[1]}] baseline "
                f"{base_s[k]['count']}, tree {live_s[k]} — regenerate "
                f"with --write-baseline (reasons required)")
    return problems
