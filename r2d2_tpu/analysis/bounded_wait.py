"""bounded-wait: no unbounded blocking waits inside supervised loops.

The degraded-mode resilience layer (utils/resilience.py, ISSUE 7) exists
because a single unbounded wait can wedge a whole plane: a fleet blocked
forever on a response queue, a fabric loop parked on ``Event.wait()``
that nothing will ever set, a ``join()`` on a thread that cannot exit.
Every supervised loop in this repo is written against the stop-predicate
discipline — *poll with a timeout, check ``stop()``, repeat* — and this
rule keeps it that way:

Inside a **thread-target function** (a ``target=`` argument of a
``threading.Thread`` call), a function handed to ``Supervisor.start(
"name", fn)``, or any function named ``*_loop`` (the fabric loop
convention), a call of the form ``X.get()``, ``X.wait()`` or
``X.join()`` with **no arguments and no ``timeout=`` keyword** is a
finding.  ``q.get(timeout=0.2)``, ``ev.wait(0.5)``, ``t.join(5.0)`` and
``d.get("key")`` (an argument ≠ an unbounded block) all pass.

Intentionally unbounded waits — e.g. a sentinel-driven consumer whose
producer is *guaranteed* to deliver the sentinel on every exit path —
carry a per-line ``# graftlint: disable=bounded-wait -- <why the wake-up
is guaranteed>`` so the review decision stays visible and counted.
"""
from __future__ import annotations

import ast
from typing import List

from r2d2_tpu.analysis.core import Context, Finding, rule
from r2d2_tpu.analysis.thread_discipline import _target_functions

RULE = "bounded-wait"

_BLOCKING_ATTRS = ("get", "wait", "join")

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def _supervised_functions(tree: ast.AST) -> List[ast.AST]:
    """Functions handed to the Supervisor by name:
    ``<anything>.start("thread-name", fn)`` — the repo's one way of
    launching a fabric loop (utils/supervisor.py)."""
    by_name = {n.name: n for n in ast.walk(tree) if isinstance(n, _FuncNode)}
    out: List[ast.AST] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "start"
                and len(node.args) == 2
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and isinstance(node.args[1], ast.Name)):
            continue
        fn = by_name.get(node.args[1].id)
        if fn is not None:
            out.append(fn)
    return out


def _unbounded_wait_calls(fn: ast.AST) -> List[ast.Call]:
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _BLOCKING_ATTRS):
            continue
        if node.args:
            continue   # a positional arg is a timeout (or a dict key)
        if any(kw.arg == "timeout" for kw in node.keywords):
            continue
        out.append(node)
    return out


@rule(RULE, "supervised *_loop functions and thread targets only block "
            "with a timeout (get/wait/join need timeout= or a justified "
            "suppression)")
def check_bounded_wait(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        fns = list(_target_functions(mod.tree))
        seen = {id(f) for f in fns}
        fns += [f for f in _supervised_functions(mod.tree)
                if id(f) not in seen]
        for fn in fns:
            name = getattr(fn, "name", "<lambda>")
            for call in _unbounded_wait_calls(fn):
                attr = call.func.attr
                findings.append(Finding(
                    RULE, mod.rel, call.lineno,
                    f"supervised loop {name!r} blocks on .{attr}() with "
                    "no timeout — an unbounded wait wedges the plane if "
                    "the wake-up never comes; pass timeout= and poll the "
                    "stop predicate (utils/resilience.Deadline composes "
                    "budgets), or suppress with the reason the wake-up "
                    "is guaranteed"))
    return findings
