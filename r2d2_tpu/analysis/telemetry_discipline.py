"""telemetry-discipline: metric names are literals, not format strings.

The telemetry plane (r2d2_tpu/telemetry) is a *registry*: a metric name
is an identity that dashboards, scrape configs, and greps key on.  An
ad-hoc f-string name in a hot loop (``registry.inc(f"ingest.{src}")``)
silently mints an unbounded family of series — per-entity cardinality
that belongs in a LABEL (``registry.inc("ingest.blocks",
fleet=str(src))``), where the name stays greppable and the label is the
variable part.  It is also an allocation per call in loops the registry
was specifically designed to keep allocation-light.

The check: every call of a metric-writing method — ``inc``,
``counter_max``, ``set_gauge``, ``observe``, ``observe_many``,
``declare_histogram``, ``absorb_histogram`` on a registry-shaped
receiver, plus the Tracer
surface (``span``, ``gauge``, ``incr``) and the cross-process event
tracer's recording surface (``instant``, ``complete`` —
telemetry/tracing.py; variable parts go in ``flow``/``arg``, never the
event name) — must pass the metric/event name as a plain string
literal.
Receivers are matched by name shape (``registry`` / ``metrics`` /
``telemetry`` / ``tracer`` and ``*.registry`` etc.), the same heuristic
family as config-integrity's receivers; bulk absorption helpers
(``absorb_gauges``/``absorb_counters``) take a prefix + mapping and are
exempt by design — they exist to fold fixed upstream surfaces, carry
their own suppression where they synthesize names, and keep hot loops
out of it.

**Alert-rule vocabulary** (telemetry/learnhealth.py): alert rule names
are identities too — an ``alerts.jsonl`` row, a
``learnhealth.alert{rule=...}`` series, and an operator runbook entry
all key on them.  Two extra checks:

- an ``AlertRule(...)`` construction (and ``.fire(...)`` on an
  engine-shaped receiver: ``engine`` / ``alerts`` / ``*_engine`` /
  ``*alert_engine``) must pass the rule name as a string literal;
- an ``AlertRule`` ``threshold=`` keyword must not be a bare numeric
  constant — alert thresholds are operator knobs and belong in cfg
  (``cfg.alert_*``), never inline magic numbers in rule bodies.
"""
from __future__ import annotations

import ast
from typing import List

from r2d2_tpu.analysis.core import Context, Finding, rule

RULE = "telemetry-discipline"

# metric-writing methods whose first argument IS a metric/event name
_METRIC_METHODS = ("inc", "counter_max", "set_gauge", "observe",
                   "observe_many", "declare_histogram",
                   "absorb_histogram", "span", "gauge",
                   "incr", "instant", "complete")

_RECEIVER_NAMES = ("registry", "metrics", "telemetry", "tracer", "reg",
                   "tr", "events")

# alert-engine vocabulary (telemetry/learnhealth.py)
_ALERT_RECEIVER_NAMES = ("engine", "alerts")
_ALERT_THRESHOLD_KWARGS = ("threshold",)


def _is_metric_receiver(node: ast.AST) -> bool:
    """A name that plausibly holds a MetricsRegistry or Tracer."""
    if isinstance(node, ast.Name):
        n = node.id.lower()
    elif isinstance(node, ast.Attribute):
        n = node.attr.lower()
    else:
        return False
    return n in _RECEIVER_NAMES or n.endswith(
        ("registry", "tracer", "_metrics", "telemetry", "_events"))


def _name_arg(call: ast.Call):
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _is_alert_engine_receiver(node: ast.AST) -> bool:
    """A name that plausibly holds an AlertEngine."""
    if isinstance(node, ast.Name):
        n = node.id.lower()
    elif isinstance(node, ast.Attribute):
        n = node.attr.lower()
    else:
        return False
    return n in _ALERT_RECEIVER_NAMES or n.endswith(
        ("_engine", "alert_engine", "_alerts"))


def _is_alert_rule_ctor(call: ast.Call) -> bool:
    f = call.func
    return ((isinstance(f, ast.Name) and f.id == "AlertRule")
            or (isinstance(f, ast.Attribute) and f.attr == "AlertRule"))


def _is_literal_str(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


@rule(RULE, "metric names passed to the registry/tracer must be string "
            "literals (labels carry the variable part)")
def check_telemetry_discipline(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # --- alert-rule vocabulary (telemetry/learnhealth.py) ----
            if _is_alert_rule_ctor(node):
                arg = _name_arg(node)
                if arg is not None and not _is_literal_str(arg):
                    findings.append(Finding(
                        RULE, mod.rel, node.lineno,
                        "AlertRule name is not a string literal — rule "
                        "names key alerts.jsonl rows and the "
                        "learnhealth.alert{rule} series "
                        "(telemetry/learnhealth.py)"))
                for kw in node.keywords:
                    if (kw.arg in _ALERT_THRESHOLD_KWARGS
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, (int, float))
                            and not isinstance(kw.value.value, bool)):
                        findings.append(Finding(
                            RULE, mod.rel, node.lineno,
                            "AlertRule threshold is an inline magic "
                            "number — alert thresholds are operator "
                            "knobs and must come from cfg "
                            "(cfg.alert_*)"))
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "fire"
                    and _is_alert_engine_receiver(node.func.value)):
                arg = _name_arg(node)
                if arg is not None and not _is_literal_str(arg):
                    findings.append(Finding(
                        RULE, mod.rel, node.lineno,
                        "alert rule name for .fire() is not a string "
                        "literal (telemetry/learnhealth.py)"))
                continue
            # --- metric/event name literals --------------------------
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS
                    and _is_metric_receiver(node.func.value)):
                continue
            arg = _name_arg(node)
            if arg is None:
                continue      # pathological call; runtime will complain
            if _is_literal_str(arg):
                continue
            kind = type(arg).__name__
            detail = ("f-string" if isinstance(arg, ast.JoinedStr)
                      else f"non-literal ({kind})")
            findings.append(Finding(
                RULE, mod.rel, node.lineno,
                f"metric name for .{node.func.attr}() is {detail} — "
                "register a literal name and put the variable part in a "
                "label (telemetry/registry.py)"))
    return findings
