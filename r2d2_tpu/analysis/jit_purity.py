"""jit-purity: host effects inside jit-traced code.

A function traced by ``jax.jit`` / ``shard_map`` runs its Python body
ONCE per compilation; host-side effects inside it are frozen into the
graph (Podracer's compile/step-boundary discipline, PAPERS.md):

- ``time.*`` calls bake the trace-time clock into every step;
- Python / ``np.random`` RNG bakes one draw in forever (device RNG is
  ``jax.random``);
- ``.item()`` / ``float()`` / ``int()`` / ``jax.device_get`` on tracers
  force a host transfer (or raise) — either way the hot loop stalls;
- mutable default arguments alias one object across traces.

Scope: intra-module, best-effort.  Roots are functions handed to
``jax.jit`` / ``pjit`` / ``shard_map`` (as decorators, direct calls,
``functools.partial`` wrappings, retrace-guard ``.wrap(...)`` wrappings,
or factory calls whose returned inner function the jit wraps); the rule
then follows name references to other functions *in the same module*.
Cross-module callees (e.g. ``net.apply``) are covered when their own
module has jit sites, not transitively — the rule is a tripwire, not a
type system.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from r2d2_tpu.analysis.core import Context, Finding, dotted_name, rule

RULE = "jit-purity"

_JIT_NAMES = {
    "jax.jit", "jit", "pjit", "jax.pjit",
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_HOST_RNG_PREFIXES = ("np.random.", "numpy.random.", "random.")
_MUTABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


class _ModuleIndex:
    """Named function defs + factory returns for one module."""

    def __init__(self, tree: ast.AST):
        self.defs: Dict[str, List[ast.AST]] = {}
        # simple `name = factory(...)` assignments, for resolving
        # `jax.jit(fn)` where fn was produced by a local factory.  ALL
        # assignments under one name are kept: two factories binding
        # their pre-jit callable to the same local (e.g. ``wrapped =
        # RETRACES.wrap(...)`` in sibling factories) must union their
        # candidates — last-wins resolution silently dropped every
        # earlier factory's function graph from the root set
        self.assigned_calls: Dict[str, List[ast.Call]] = {}
        for node in ast.walk(tree):
            if isinstance(node, _FuncNode):
                self.defs.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and isinstance(node.value,
                                                          ast.Call):
                    self.assigned_calls.setdefault(t.id, []).append(
                        node.value)

    def returned_functions(self, func: ast.AST) -> List[ast.AST]:
        """Function nodes a factory returns (``return inner`` /
        ``return jax.jit(inner)`` / ``return lambda ...``)."""
        out: List[ast.AST] = []
        inner = {n.name: n for n in ast.walk(func)
                 if isinstance(n, _FuncNode) and n is not func}
        for node in ast.walk(func):
            if not (isinstance(node, ast.Return) and node.value is not None):
                continue
            v = node.value
            if isinstance(v, ast.Name) and v.id in inner:
                out.append(inner[v.id])
            elif isinstance(v, ast.Lambda):
                out.append(v)
            elif isinstance(v, ast.Call):
                d = dotted_name(v.func)
                if d in _JIT_NAMES and v.args:
                    out.extend(self._resolve_seed(v.args[0]))
        return out

    def _resolve_seed(self, node, _visiting: Optional[Set[str]] = None
                      ) -> List[ast.AST]:
        """Function nodes a jit-call argument ultimately names.
        ``_visiting`` breaks rebinding cycles (``fn = wrap("n", fn)``)."""
        if _visiting is None:
            _visiting = set()
        if isinstance(node, ast.Lambda):
            return [node]
        if isinstance(node, ast.Name):
            if node.id in self.defs:
                return list(self.defs[node.id])
            if node.id in _visiting:
                return []
            _visiting.add(node.id)
            out: List[ast.AST] = []
            for call in self.assigned_calls.get(node.id, []):
                out.extend(self._resolve_seed(call, _visiting))
            return out
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            if d in _PARTIAL_NAMES and node.args:
                return self._resolve_seed(node.args[0], _visiting)
            if d.endswith(".wrap") or d == "retrace_wrap":
                # utils.trace.RETRACES.wrap("name", fn, ...): the traced
                # function is the first non-string argument
                out: List[ast.AST] = []
                for a in node.args:
                    if isinstance(a, ast.Constant):
                        continue
                    out.extend(self._resolve_seed(a, _visiting))
                return out
            # factory call: the jitted function is what the factory returns
            if isinstance(node.func, ast.Name):
                out = []
                for f in self.defs.get(node.func.id, []):
                    out.extend(self.returned_functions(f))
                return out
        return []

    def roots(self, tree: ast.AST) -> List[ast.AST]:
        out: List[ast.AST] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if d in _JIT_NAMES and node.args:
                    out.extend(self._resolve_seed(node.args[0]))
            elif isinstance(node, _FuncNode):
                for dec in node.decorator_list:
                    d = dotted_name(dec)
                    if d in _JIT_NAMES:
                        out.append(node)
                    elif isinstance(dec, ast.Call):
                        dc = dotted_name(dec.func)
                        if dc in _JIT_NAMES:
                            out.append(node)
                        elif (dc in _PARTIAL_NAMES and dec.args
                              and dotted_name(dec.args[0]) in _JIT_NAMES):
                            out.append(node)
        return out


def _reachable(index: _ModuleIndex, roots: List[ast.AST]) -> List[ast.AST]:
    seen: Set[int] = set()
    order: List[ast.AST] = []
    work = list(roots)
    while work:
        fn = work.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        order.append(fn)
        for node in ast.walk(fn):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in index.defs):
                work.extend(index.defs[node.id])
    return order


def _fn_label(fn: ast.AST) -> str:
    return getattr(fn, "name", "<lambda>")


def _scan_function(rel: str, fn: ast.AST, out: List[Finding],
                   seen: Set[tuple]) -> None:
    label = _fn_label(fn)

    def emit(line: int, msg: str) -> None:
        key = (line, msg)
        if key not in seen:
            seen.add(key)
            out.append(Finding(RULE, rel, line, msg))

    for node in ast.walk(fn):
        if isinstance(node, _FuncNode + (ast.Lambda,)):
            args = node.args
            for default in list(args.defaults) + [
                    d for d in args.kw_defaults if d is not None]:
                if isinstance(default, _MUTABLE_DEFAULTS):
                    emit(default.lineno,
                         f"mutable default argument in jit-reachable "
                         f"function {_fn_label(node)!r} (one object is "
                         "shared across every trace)")
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func) or ""
        if d.startswith("time."):
            emit(node.lineno,
                 f"host clock call {d}() inside jit-reachable function "
                 f"{label!r} (the trace freezes its value)")
        elif d.startswith(_HOST_RNG_PREFIXES):
            emit(node.lineno,
                 f"host RNG call {d}() inside jit-reachable function "
                 f"{label!r} (one draw is baked into the graph; use "
                 "jax.random)")
        elif d == "jax.device_get":
            emit(node.lineno,
                 f"jax.device_get inside jit-reachable function {label!r} "
                 "(forces a host transfer per trace)")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args
              and not node.keywords):
            emit(node.lineno,
                 f".item() inside jit-reachable function {label!r} "
                 "(host transfer; keep scalars on device)")
        elif (isinstance(node.func, ast.Name)
              and node.func.id in ("float", "int", "bool")
              and len(node.args) == 1
              and not isinstance(node.args[0], ast.Constant)):
            emit(node.lineno,
                 f"{node.func.id}() scalarization inside jit-reachable "
                 f"function {label!r} (host transfer on a tracer; use "
                 "jnp casts)")


@rule(RULE, "no host clocks/RNG/transfers or mutable defaults in functions "
            "reachable from jax.jit / shard_map call sites")
def check_jit_purity(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        index = _ModuleIndex(mod.tree)
        roots = index.roots(mod.tree)
        if not roots:
            continue
        seen: Set[tuple] = set()
        for fn in _reachable(index, roots):
            _scan_function(mod.rel, fn, findings, seen)
    return findings
