"""donation-discipline: buffer-donation contracts around jit call sites.

Every drivetrain in this repo donates its TrainState and batch buffers
(``donate_argnums`` on the jit/pjit site — Podracer's in-place update
discipline, PAPERS.md).  Donation is invisible to the type system and
*silently forgiving on CPU*: reading a donated buffer after the call
works on the tier-1 host and crashes only on a real accelerator, which
is exactly the class of bug a CPU-only CI can never catch at runtime.
This family makes the contract static:

- ``use-after-donate`` — a caller reads (or mutates) a variable it
  passed in a donated position *after* the donating call, before any
  rebinding.  Intra-function dataflow over statement order; the callee
  set is resolved with the same idioms jit-purity handles (decorator,
  direct ``jax.jit(fn, donate_argnums=...)`` assignment, factory
  return, ``RETRACES.wrap`` / ``functools.partial`` chains).
- ``missed-donation`` — a jit entry point in the drivetrain modules
  (``learner/``, ``parallel/``, ``envs/anakin.py``) whose wrapped
  function takes a large-array state/batch parameter (declared name
  vocabulary below, or a ``TrainState`` annotation) with no
  ``donate_argnums``/``donate_argnames`` on the site.  A deliberate
  non-donating site suppresses with a reason (recorded in the
  graftlint baseline).
- ``result-sync`` — ``jax.device_get`` / ``np.asarray`` /
  ``.block_until_ready()`` applied to a donating entry point's result
  inside a ``*_loop`` function: a per-iteration sync that defeats the
  async dispatch the donation bought.  Harvest belongs behind the
  declared ``HOST_TRANSFERS`` sites, not in the loop body.

Messages carry a stable finding code prefix (``use-after-donate:``,
``missed-donation:``, ``result-sync:``) — docs/ANALYSIS.md documents
each; the suppression key is the family name ``donation-discipline``.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from r2d2_tpu.analysis.core import Context, Finding, dotted_name, rule
from r2d2_tpu.analysis.jit_purity import (
    _JIT_NAMES,
    _FuncNode,
    _ModuleIndex,
)

RULE = "donation-discipline"

# param names that mean "large device-resident state/batch buffer" for
# the missed-donation heuristic (exact match on the wrapped function's
# positional params); annotations ending in TrainState also qualify
_STATE_VOCAB = {
    "state", "train_state", "ts", "batch", "carry", "ring", "per_state",
    "opt_state", "buffer_state", "slab", "arrays",
}
# rel-path scopes where missed-donation applies (the drivetrains; a
# serving act fn legitimately never donates its params)
_DONATE_SCOPES = ("r2d2_tpu/learner/", "r2d2_tpu/parallel/")
_DONATE_FILES = ("r2d2_tpu/envs/anakin.py",)

_SYNC_CALLS = {"jax.device_get", "np.asarray", "numpy.asarray",
               "np.array", "numpy.array"}


@dataclasses.dataclass
class _DonateSite:
    """One jit/pjit call with donation info, bound to a local name."""
    name: str                 # local/attr name the jit result is bound to
    argnums: Tuple[int, ...]  # donated positional indices ((), if none)
    argnames: Tuple[str, ...]
    line: int
    donates: bool             # any donate kwarg present at the site
    # True when `name` is a FACTORY whose *return value* donates — the
    # argnums apply to calls of the factory's result (bound via the
    # inheritance pass), never to the factory call itself
    factory: bool = False


def _const_int_tuple(node) -> Tuple[int, ...]:
    """Literal ints out of ``donate_argnums=(0, 2)`` / ``=0``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _const_str_tuple(node) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _jit_call(node: ast.Call) -> Optional[ast.Call]:
    """The jit/pjit Call itself if ``node`` is one (following a
    ``functools.partial(jax.jit, ...)``-style head is not needed: the
    repo always calls jit directly or via the factory idioms)."""
    d = dotted_name(node.func)
    if d in _JIT_NAMES:
        return node
    return None


def _donation_kwargs(call: ast.Call
                     ) -> Tuple[Tuple[int, ...], Tuple[str, ...], bool]:
    nums: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    present = False
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            present = True
            nums = _const_int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            present = True
            names = _const_str_tuple(kw.value)
    return nums, names, present


def _bound_name(target) -> Optional[str]:
    """`x = ...` -> "x"; `self.attr = ...` / `obj.attr = ...` -> "attr"
    (attribute matching is by attr name — good enough intra-module)."""
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def _callee_name(call: ast.Call) -> Optional[str]:
    """Call-site lookup key mirroring :func:`_bound_name`."""
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def collect_donating_sites(tree: ast.AST) -> Dict[str, _DonateSite]:
    """name -> donation info, for every ``x = jax.jit(...)`` /
    ``self.attr = jax.jit(...)`` / ``return jax.jit(...)`` (the latter
    keyed by the enclosing factory's name, covering the
    ``step = make_step(...)`` idiom) and every ``@jit``-decorated def.
    A name bound at multiple sites keeps the union of donated positions
    and donates only if every site donates (conservative for
    missed-donation, liberal for use-after-donate)."""
    sites: Dict[str, _DonateSite] = {}

    def record(name: Optional[str], call: ast.Call,
               factory: bool = False) -> None:
        if not name:
            return
        nums, argnames, present = _donation_kwargs(call)
        prev = sites.get(name)
        if prev is None:
            sites[name] = _DonateSite(name, nums, argnames,
                                      call.lineno, present, factory)
        else:
            prev.argnums = tuple(sorted(set(prev.argnums) | set(nums)))
            prev.argnames = tuple(sorted(set(prev.argnames)
                                         | set(argnames)))
            prev.donates = prev.donates and present
            prev.factory = prev.factory and factory

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            if isinstance(node.value, ast.Call):
                call = _jit_call(node.value)
                if call is not None:
                    record(_bound_name(node.targets[0]), call)
        elif isinstance(node, _FuncNode):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    call = _jit_call(dec)
                    if call is not None:
                        record(node.name, call)
            # factory: `def make_step(...): ... return jax.jit(f, ...)`
            # — the *factory result* is the donating callable, and call
            # sites bind it as `step = make_step(...)`; key the site by
            # the factory name (factory=True: the argnums never apply
            # to the factory call itself) and resolve at the binding
            for inner in ast.walk(node):
                if (isinstance(inner, ast.Return)
                        and isinstance(inner.value, ast.Call)):
                    call = _jit_call(inner.value)
                    if call is not None:
                        record(node.name, call, factory=True)

    # second pass: `def make_step(): ...; step = jit(f, donate...);
    # return step` — a factory returning a local that holds the jit
    # result hands the donation info to the factory name
    for node in ast.walk(tree):
        if isinstance(node, _FuncNode) and node.name not in sites:
            for inner in ast.walk(node):
                if (isinstance(inner, ast.Return)
                        and isinstance(inner.value, ast.Name)
                        and inner.value.id in sites
                        and not sites[inner.value.id].factory):
                    src = sites[inner.value.id]
                    sites[node.name] = _DonateSite(
                        node.name, src.argnums, src.argnames,
                        node.lineno, src.donates, factory=True)
                    break

    # third pass: `step = make_step(...)` / `self._fn = make_step(...)`
    # binds the factory's RESULT — the donation info applies to calls
    # of the bound name (factory=False from here on)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.value, ast.Call)):
            callee = _callee_name(node.value)
            bound = _bound_name(node.targets[0])
            if (callee in sites and sites[callee].factory
                    and bound and bound not in sites):
                src = sites[callee]
                sites[bound] = _DonateSite(bound, src.argnums,
                                           src.argnames, node.lineno,
                                           src.donates)
    return sites


def _donated_args(call: ast.Call, site: _DonateSite) -> List[ast.Name]:
    out = []
    for i in site.argnums:
        if i < len(call.args) and isinstance(call.args[i], ast.Name):
            out.append(call.args[i])
    for kw in call.keywords:
        if kw.arg in site.argnames and isinstance(kw.value, ast.Name):
            out.append(kw.value)
    return out


def _check_use_after_donate(rel: str, fn: ast.AST,
                            sites: Dict[str, _DonateSite],
                            out: List[Finding],
                            seen: Set[Tuple[int, str]]) -> None:
    # (var, call first line, call last line, callee) — a multi-line call
    # puts argument loads on lines below its lineno; anything inside the
    # call's own span is the donation itself, not a use-after
    donations: List[Tuple[str, int, int, str]] = []
    loads: List[Tuple[str, int]] = []
    stores: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = _callee_name(node)
            site = sites.get(callee) if callee else None
            if site is not None and site.donates and not site.factory:
                end = getattr(node, "end_lineno", node.lineno) or node.lineno
                for arg in _donated_args(node, site):
                    donations.append((arg.id, node.lineno, end, callee))
        elif isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.append((node.id, node.lineno))
            elif isinstance(node.ctx, (ast.Store, ast.Del)):
                stores.append((node.id, node.lineno))
        elif isinstance(node, ast.Assign):
            # `x, y = f(x, ...)` spanning lines puts the target Store a
            # line ABOVE the donating call — also book the rebinding at
            # the value's line so it counts as after-the-call
            for t in node.targets:
                for n in ast.walk(t):
                    if (isinstance(n, ast.Name)
                            and isinstance(n.ctx, ast.Store)):
                        stores.append((n.id, node.value.lineno))

    def emit(line: int, msg: str) -> None:
        key = (line, msg)
        if key not in seen:
            seen.add(key)
            out.append(Finding(RULE, rel, line, msg))

    if not donations:
        return
    for var, call_line, call_end, callee in donations:
        rebind = [ln for v, ln in stores if v == var and ln >= call_line]
        horizon = min(rebind) if rebind else None
        for v, ln in loads:
            if v != var or ln <= call_end:
                continue
            if horizon is not None and ln >= horizon:
                continue
            emit(ln,
                 f"use-after-donate: {var!r} was passed in a donated "
                 f"position of {callee}() at line {call_line} and is "
                 f"read afterwards — the buffer is invalid on a real "
                 f"accelerator (CPU silently aliases it)")

    # loop-carried donation: a donating call inside a for/while whose
    # donated arg is never rebound in the loop body re-reads an
    # invalidated buffer on the SECOND iteration — the textual
    # load-before-call ordering above cannot see it
    for node in ast.walk(fn):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        body_stores = {n.id for stmt in node.body
                       for n in ast.walk(stmt)
                       if isinstance(n, ast.Name)
                       and isinstance(n.ctx, ast.Store)}
        for stmt in node.body:
            for inner in ast.walk(stmt):
                if not isinstance(inner, ast.Call):
                    continue
                callee = _callee_name(inner)
                site = sites.get(callee) if callee else None
                if site is None or not site.donates or site.factory:
                    continue
                for arg in _donated_args(inner, site):
                    if arg.id not in body_stores:
                        emit(inner.lineno,
                             f"use-after-donate: {arg.id!r} is donated "
                             f"to {callee}() inside a loop without "
                             f"being rebound — iteration 2 passes an "
                             f"already-donated buffer")


def _in_donation_scope(rel: str) -> bool:
    return rel.startswith(_DONATE_SCOPES) or rel in _DONATE_FILES


def _wrapped_params(index: _ModuleIndex, call: ast.Call) -> List[Tuple[str, Optional[str]]]:
    """(param name, annotation dotted name) of the function a jit call
    wraps, via jit-purity's resolver (decorator/partial/wrap/factory)."""
    params: List[Tuple[str, Optional[str]]] = []
    if not call.args:
        return params
    for fn in index._resolve_seed(call.args[0]):
        args = getattr(fn, "args", None)
        if args is None:
            continue
        for a in args.args:
            ann = dotted_name(a.annotation) if a.annotation else None
            params.append((a.arg, ann))
    return params


def _looks_like_state(params: List[Tuple[str, Optional[str]]]) -> List[str]:
    hits = []
    for name, ann in params:
        if name in _STATE_VOCAB or (ann or "").endswith("TrainState"):
            hits.append(name)
    return hits


def _check_missed_donation(rel: str, tree: ast.AST, index: _ModuleIndex,
                           out: List[Finding]) -> None:
    if not _in_donation_scope(rel):
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            call = _jit_call(node)
            if call is None:
                continue
            _nums, _names, present = _donation_kwargs(call)
            if present:
                continue
            hits = _looks_like_state(_wrapped_params(index, call))
            if hits:
                out.append(Finding(
                    RULE, rel, call.lineno,
                    f"missed-donation: jit site wraps a function taking "
                    f"large-array state param(s) {', '.join(sorted(set(hits)))} "
                    f"with no donate_argnums/donate_argnames — the "
                    f"drivetrain double-buffers every step"))
        elif isinstance(node, _FuncNode):
            for dec in node.decorator_list:
                if dotted_name(dec) in _JIT_NAMES:
                    # bare `@jax.jit` decorator: no kwargs possible
                    hits = _looks_like_state(
                        [(a.arg, dotted_name(a.annotation)
                          if a.annotation else None)
                         for a in node.args.args])
                    if hits:
                        out.append(Finding(
                            RULE, rel, dec.lineno,
                            f"missed-donation: @jit-decorated "
                            f"{node.name!r} takes large-array state "
                            f"param(s) "
                            f"{', '.join(sorted(set(hits)))} with no "
                            f"donation — use jax.jit(fn, "
                            f"donate_argnums=...) at a call site"))


def _check_result_sync(rel: str, fn: ast.AST,
                       sites: Dict[str, _DonateSite],
                       out: List[Finding]) -> None:
    if not getattr(fn, "name", "").endswith("_loop"):
        return
    results: Set[str] = set()
    order: List[ast.AST] = list(ast.walk(fn))
    for node in order:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            callee = _callee_name(node.value)
            site = sites.get(callee) if callee else None
            if site is not None and site.donates and not site.factory:
                for t in node.targets:
                    for el in (t.elts if isinstance(t, (ast.Tuple,
                                                        ast.List))
                               else [t]):
                        if isinstance(el, ast.Name):
                            results.add(el.id)
    if not results:
        return
    for node in order:
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func) or ""
        target: Optional[str] = None
        if d in _SYNC_CALLS and node.args and isinstance(node.args[0],
                                                        ast.Name):
            target = node.args[0].id
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "block_until_ready"
              and isinstance(node.func.value, ast.Name)):
            target = node.func.value.id
            d = ".block_until_ready"
        if target in results:
            out.append(Finding(
                RULE, rel, node.lineno,
                f"result-sync: {d}({target}) inside loop function "
                f"{fn.name!r} forces a per-iteration device sync on a "
                f"donating entry point's result — harvest behind the "
                f"declared HOST_TRANSFERS site instead"))


@rule(RULE, "buffer-donation contracts: no use-after-donate, drivetrain "
            "jit sites donate their state/batch params, no per-iteration "
            "syncs on donated results in *_loop functions")
def check_donation(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        sites = collect_donating_sites(mod.tree)
        index = _ModuleIndex(mod.tree)
        _check_missed_donation(mod.rel, mod.tree, index, findings)
        seen: Set[Tuple[int, str]] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, _FuncNode):
                _check_use_after_donate(mod.rel, node, sites, findings,
                                        seen)
                _check_result_sync(mod.rel, node, sites, findings)
    return findings
