"""CLI driver: ``python -m r2d2_tpu.analysis [paths...] [--json]``.

Exit status 0 = clean tree (suppressed findings allowed), 1 = findings
or unparseable files.  Default paths: ``r2d2_tpu tools`` relative to the
current directory.  ``--rules a,b`` restricts the run; ``--list-rules``
prints the registry.  ``--baseline FILE`` checks the report against a
committed findings+suppressions snapshot (exit 1 with a diff on drift);
``--write-baseline FILE`` regenerates the snapshot.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    # rule registration happens in the package __init__; importing it here
    # (not at module top) keeps `python -m r2d2_tpu.analysis` and
    # `from r2d2_tpu.analysis import main` on one import path
    from r2d2_tpu.analysis import RULES, run_analysis

    p = argparse.ArgumentParser(
        prog="r2d2_tpu.analysis",
        description="graftlint: repo-native static analysis")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to analyze "
                        "(default: r2d2_tpu tools)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset")
    p.add_argument("--root", default=None,
                   help="repo root for relative paths + docs lookup "
                        "(default: cwd)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="check findings+suppressions against this "
                        "snapshot; exit 1 with a diff on drift")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the snapshot for --baseline to check")
    args = p.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name}: {RULES[name].doc}")
        return 0

    paths = args.paths or ["r2d2_tpu", "tools"]
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            p.error(f"unknown rules: {', '.join(unknown)} "
                    f"(have: {', '.join(sorted(RULES))})")
    report = run_analysis(paths, root=args.root, rules=rules)

    if args.write_baseline:
        from r2d2_tpu.analysis import baseline as bl

        bl.write(args.write_baseline, report)
        print(f"graftlint: baseline written to {args.write_baseline} "
              f"({len(report.findings)} finding(s), "
              f"{len(report.suppressed)} suppression(s))")
        if report.findings:
            print("graftlint: WARNING — baselining a DIRTY tree: the "
                  "findings above are now pinned as accepted debt")
        return 0 if not report.errors else 1

    if args.baseline:
        from r2d2_tpu.analysis import baseline as bl

        try:
            base = bl.load(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"graftlint: cannot load baseline: {e}",
                  file=sys.stderr)
            return 1
        problems = bl.diff(base, report)
        for f in report.errors:
            print(f.format())
        for line in problems:
            print(line)
        print(f"graftlint: {len(problems)} drift line(s) vs baseline "
              f"{args.baseline}, {len(report.errors)} parse error(s) "
              f"across {report.files} files")
        return 0 if not problems and not report.errors else 1

    if args.as_json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        for f in report.errors + report.findings:
            print(f.format())
        print(f"graftlint: {len(report.findings)} finding(s), "
              f"{len(report.suppressed)} suppressed, "
              f"{len(report.errors)} parse error(s) across "
              f"{report.files} files "
              f"[rules: {', '.join(report.rules)}]")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
