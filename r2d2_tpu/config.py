"""Typed configuration for the TPU-native R2D2 framework.

Replaces the reference's flat module-global config (``/root/reference/config.py:1-37``)
with an immutable dataclass: values are captured at construction, derived
quantities are validated, and presets mirror the benchmark configurations in
``BASELINE.json``.  Nothing reads config at import time; every component takes
a ``Config`` explicitly.
"""
from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Dict, List, Optional, Tuple

# the canonical learner-mesh axes, in mesh order (parallel/mesh.py's AXES
# aliases this — defined here so Config validation needs no jax import);
# the r8-era "mp" axis folded into "tp" with the sharding table
MESH_AXES = ("dp", "fsdp", "tp")


def validate_mesh_shape(mesh_shape) -> dict:
    """The single mesh-axis rule set (axis names, duplicates, sizes),
    shared by Config.__post_init__ and parallel/mesh.make_mesh so the
    two can never drift.  Returns {axis: size or None} for the named
    axes."""
    sizes = {name: None for name in MESH_AXES}
    for name, size in mesh_shape:
        if name not in MESH_AXES:
            raise ValueError(
                f"unknown mesh axis {name!r} in mesh_shape (expected one "
                f"of {MESH_AXES}; the 'mp' axis was folded into 'tp' "
                "with the sharding table)")
        if sizes[name] is not None:
            raise ValueError(f"duplicate mesh axis {name!r}")
        if int(size) < 1:
            raise ValueError(f"mesh axis {name!r} size must be >= 1")
        sizes[name] = int(size)
    return sizes


_INT_TOKEN = re.compile(r"^\d+$")
_INT_SUFFIX = re.compile(r"^(.+?)_\d+$")


def normalize_token(token: str) -> str:
    """Wildcard integer layer indices: ``"3"`` → ``"*"``, ``"lstm_0"`` →
    ``"lstm_*"`` (all layers of a family share one layout — SNIPPETS.md
    [3]'s ``_process_sharding_name``)."""
    if _INT_TOKEN.match(token):
        return "*"
    m = _INT_SUFFIX.match(token)
    if m:
        return m.group(1) + "_*"
    return token


def parse_table(spec: str) -> Dict[str, Tuple[Optional[str], ...]]:
    """Parse a ``cfg.sharding_table`` override string.

    Format: ``pattern=axis,axis;pattern2=...`` — one entry per pattern,
    dims comma-separated, an empty slot (or no slots at all) replicates.
    E.g. ``"lstm_*.wh=,tp;head.*.kernel="`` keeps ``wh``'s input dim
    replicated but tp-splits its gates, and fully replicates the head
    kernels.  Raises ``ValueError`` on malformed entries or unknown axis
    names (validated at Config construction, not mid-run).

    Lives here (not parallel/sharding.py, which re-exports it) so Config
    validation stays jax-free — the grammar only needs ``MESH_AXES``.
    """
    out: Dict[str, Tuple[Optional[str], ...]] = {}
    for clause in filter(None, (c.strip() for c in spec.split(";"))):
        if "=" not in clause:
            raise ValueError(
                f"sharding_table clause {clause!r} is not 'pattern=axes'")
        pattern, axes = clause.split("=", 1)
        pattern = pattern.strip()
        if not pattern:
            raise ValueError("sharding_table clause with empty pattern")
        # normalize concrete layer indices to the table's wildcard form
        # ("lstm_0.wh" → "lstm_*.wh"): lookup() normalizes the LEAF path
        # before matching, so a verbatim "lstm_0" entry could never match
        # and the override would be a silent no-op
        pattern = ".".join(normalize_token(t) for t in pattern.split("."))
        dims = []
        for d in axes.split(","):
            d = d.strip()
            if d and d not in MESH_AXES:
                raise ValueError(
                    f"sharding_table axis {d!r} not in {MESH_AXES}")
            dims.append(d or None)
        if dims == [None]:
            dims = []  # "pattern=" → fully replicated
        out[pattern] = tuple(dims)
    return out


# --- population / league (r2d2_tpu/league, docs/LEAGUE.md) ----------------
# JSON member-object keys that are population metadata, not Config
# overrides.  Restated in r2d2_tpu/analysis/config_integrity.py for the
# jax-free lint pass — tests/test_league.py pins the two in sync.
POPULATION_META_KEYS = ("name", "preset")

# Config fields one population member may override.  A deliberate
# WHITELIST, not a blacklist: every member's blocks flow into ONE shared
# replay plane and act on ONE learner's params, so anything that changes
# parameter shapes (checkpoint.ARCH_FIELDS), the block wire format /
# replay geometry (block_length, learning_steps, burn_in_steps, obs
# layout), or the fabric topology must stay base-config-owned.  What
# remains is the scenario-diversity axis: the env, the exploration
# ladder, the discount (gamma is pure per-block DATA — n_step_reward /
# n_step_gamma carry it through the wire, the learner never reads
# cfg.gamma), and eval-side knobs.  ``forward_steps`` is deliberately
# NOT here: the learner's target gather bootstraps at the BASE config's
# n (learner/step._window_indices), so a member with a smaller n would
# pair an n'-step reward sum with Q(s_{t+n}) — a silently biased
# Bellman target.  Per-member n-step needs a per-row n word through the
# batch wire (ring accounting + shard RPC + in-graph meta) and is an
# explicit follow-on (docs/LEAGUE.md).  Restated in
# analysis/config_integrity.py (pinned by tests/test_league.py).
POPULATION_MEMBER_FIELDS = (
    "game_name", "seed", "base_eps", "eps_alpha",
    "gamma", "max_episode_steps", "actor_update_interval",
    "test_epsilon", "eval_episodes", "noop_max",
)

# named member presets a population_spec entry may start from
# ("preset": "low_resource"); explicit member keys override preset keys.
# "low_resource" is the acting-side slice of low_resource_config (the
# "Human-Level Control without Server-Grade Hardware" recipe, PAPERS.md)
# — the net/replay knobs of that preset are base-config territory.
# Preset names are restated in analysis/config_integrity.py (pinned).
POPULATION_PRESETS: Dict[str, Dict[str, Any]] = {
    "default": {},
    # NOTE: low_resource_config's forward_steps=3 does NOT ride the
    # member preset — per-member n-step is whitelisted out (see
    # POPULATION_MEMBER_FIELDS); the discount/exploration slice does
    "low_resource": dict(gamma=0.99, base_eps=0.3, eps_alpha=5.0),
}

MAX_POPULATION_MEMBERS = 64


def parse_population(spec: str) -> List[Dict[str, Any]]:
    """``cfg.population_spec`` JSON → normalized member list
    ``[{name, preset, overrides}, ...]``.

    The spec is a JSON list of member objects; each object holds optional
    ``name``/``preset`` metadata plus Config-field overrides drawn from
    :data:`POPULATION_MEMBER_FIELDS`.  Raises ``ValueError`` on malformed
    JSON, an unknown preset, a key that is not a Config field (typo), or
    a real field that is not population-overridable — misspelled member
    knobs fail at Config construction (and in graftlint's
    config-integrity pass), never silently no-op.  Value types are
    coerced to the field's declared default type so ``"forward_steps":
    3.0`` from hand-written JSON cannot smuggle a float into an int knob.
    """
    try:
        raw = json.loads(spec)
    except ValueError as e:
        raise ValueError(f"population_spec is not valid JSON: {e}")
    if not isinstance(raw, list) or not raw:
        raise ValueError(
            "population_spec must be a non-empty JSON list of member "
            "objects, e.g. '[{\"name\": \"base\"}, "
            "{\"preset\": \"low_resource\"}]'")
    if len(raw) > MAX_POPULATION_MEMBERS:
        raise ValueError(
            f"population_spec declares {len(raw)} members "
            f"(max {MAX_POPULATION_MEMBERS})")
    fields = Config.__dataclass_fields__
    out: List[Dict[str, Any]] = []
    for i, m in enumerate(raw):
        if not isinstance(m, dict):
            raise ValueError(
                f"population member {i} must be a JSON object, got "
                f"{type(m).__name__}")
        preset = m.get("preset", "default")
        if preset not in POPULATION_PRESETS:
            raise ValueError(
                f"population member {i}: unknown preset {preset!r} "
                f"(expected one of {tuple(POPULATION_PRESETS)})")
        name = m.get("name", preset if preset != "default" else f"m{i}")
        if not isinstance(name, str) or not name:
            raise ValueError(
                f"population member {i}: 'name' must be a non-empty "
                "string")
        overrides = dict(POPULATION_PRESETS[preset])
        for k, v in m.items():
            if k in POPULATION_META_KEYS:
                continue
            if k not in fields:
                raise ValueError(
                    f"population member {i} ({name}): {k!r} is not a "
                    "Config field (typo or removed knob?)")
            if k not in POPULATION_MEMBER_FIELDS:
                raise ValueError(
                    f"population member {i} ({name}): {k!r} is not "
                    "population-overridable — members share the "
                    "learner's network, replay geometry and fabric "
                    "topology (overridable: "
                    f"{POPULATION_MEMBER_FIELDS})")
            default = fields[k].default
            if isinstance(default, bool):
                overrides[k] = bool(v)
            elif isinstance(default, int):
                overrides[k] = int(v)
            elif isinstance(default, float):
                overrides[k] = float(v)
            else:
                overrides[k] = v
        out.append(dict(name=name, preset=preset, overrides=overrides))
    names = [m["name"] for m in out]
    if len(set(names)) != len(names):
        raise ValueError(
            f"population member names must be unique, got {names} — "
            "names label league.jsonl rows and population.* metrics")
    return out


@dataclasses.dataclass(frozen=True)
class Config:
    # --- environment -----------------------------------------------------
    # reference: config.py:1-2 (game name, (1,84,84) CHW obs). We use NHWC
    # (84,84,1) because that is the native TPU/XLA conv layout.
    game_name: str = "MsPacman"
    obs_shape: Tuple[int, int, int] = (84, 84, 1)
    frameskip: int = 4
    noop_max: int = 30
    max_episode_steps: int = 27000  # reference: config.py:17
    # Store observations space-to-depth transformed: 4x4 pixel blocks fold
    # into channels host-side ((84,84,1) -> (21,21,16) uint8, same bytes),
    # so the first conv is a 2x2/1 conv with an MXU-shaped contraction
    # instead of 8x8/4 over 1 channel (profiled ~2 ms/step cheaper on v5e,
    # and a device-side transform would cost more than it saves).  The
    # transform is exact: same linear function class, kernel entries
    # permuted.  nature/mlp torsos only.
    obs_space_to_depth: bool = True

    # --- optimisation ----------------------------------------------------
    lr: float = 1e-4            # reference: config.py:4
    adam_eps: float = 1e-3      # reference: config.py:5
    grad_norm: float = 40.0     # reference: config.py:6
    batch_size: int = 64        # reference: config.py:7
    gamma: float = 0.997        # reference: config.py:11
    training_steps: int = 100000  # reference: config.py:15

    # --- prioritised replay ----------------------------------------------
    prio_exponent: float = 0.9               # reference: config.py:12
    importance_sampling_exponent: float = 0.6  # reference: config.py:13
    learning_starts: int = 50000             # reference: config.py:8
    buffer_capacity: int = 2_000_000         # reference: config.py:16 (transitions)
    block_length: int = 400                  # reference: config.py:19

    # --- sequence windows -------------------------------------------------
    burn_in_steps: int = 40     # reference: config.py:27
    learning_steps: int = 40    # reference: config.py:28
    forward_steps: int = 5      # reference: config.py:29 (n-step bootstrap)
    stored_hidden_mode: str = "burn_in_start"
    # Which recurrent state a sequence stores for replay:
    #   "burn_in_start" — state at the sequence's burn-in start (the R2D2
    #       paper's scheme; replay/block.py docstring).
    #   "seq_start"     — the reference's indexing (worker.py:461,
    #       hidden_buffer[i * learning_steps]): identical once an episode's
    #       carried prefix is full, but for the first block of an episode it
    #       feeds a state recorded after part of the burn-in window.
    # Compat switch so the divergence can be A/B'd (tools/ab_curves.py).

    # --- actor fleet ------------------------------------------------------
    num_actors: int = 8         # reference: config.py:21
    base_eps: float = 0.4       # reference: config.py:22
    eps_alpha: float = 7.0      # reference: config.py:23
    actor_update_interval: int = 400  # reference: config.py:18

    # --- cadences ---------------------------------------------------------
    save_interval: int = 500               # reference: config.py:9
    target_net_update_interval: int = 2000  # reference: config.py:10
    weight_publish_interval: int = 4       # reference: worker.py:372
    log_interval: float = 10.0             # reference: config.py:24

    # --- network ----------------------------------------------------------
    hidden_dim: int = 512       # reference: config.py:33
    torso: str = "nature"       # "nature" (model.py:39-49) or "impala" (BASELINE configs[4])
    lstm_layers: int = 1        # BASELINE configs[4] uses 2

    # --- evaluation -------------------------------------------------------
    test_epsilon: float = 0.001  # reference: config.py:37
    eval_episodes: int = 5       # reference: test.py:17

    # --- population / league (r2d2_tpu/league, docs/LEAGUE.md) -----------
    population_spec: str = ""         # JSON list of per-member overrides
                                      # generalizing the per-actor epsilon
                                      # ladder to per-fleet member
                                      # CONFIGURATIONS (env, epsilon
                                      # ladder, n-step, discount — the
                                      # scenario-diversity axis): one
                                      # fleet subprocess per member, each
                                      # acting under base.replace(
                                      # **member overrides), blocks
                                      # member-tagged through the shm
                                      # wire into the shared replay
                                      # plane.  Keys validate against
                                      # POPULATION_MEMBER_FIELDS at
                                      # construction (and in graftlint);
                                      # requires actor_transport=
                                      # "process" with actor_fleets ==
                                      # member count.  "" = no
                                      # population (the degenerate
                                      # single-member run)
    league_eval: bool = False         # attach the standing EvalSidecar
                                      # (league/eval_service.py): a
                                      # supervised subprocess follows the
                                      # run's checkpoints, scores every
                                      # population member on its held-out
                                      # scenario suite, and publishes
                                      # league.jsonl + the /statusz
                                      # league table + league.* metrics.
                                      # Its death degrades /healthz —
                                      # training never stops for eval
    league_eval_episodes: int = 3     # rollouts per (checkpoint, member)
                                      # eval — the held-out suite size
    league_eval_interval: float = 2.0  # sidecar checkpoint-poll cadence
                                      # in seconds (the follow loop's
                                      # idle wait)
    league_eval_deadline: float = 120.0  # per-sweep time budget: a sweep
                                      # (all members on one checkpoint)
                                      # that blows it yields mid-step and
                                      # resumes the remaining members
                                      # next poll — a slow suite can lag
                                      # the trainer but never wedge the
                                      # sidecar on one checkpoint (0 =
                                      # unbounded)

    # --- TPU-native knobs (no reference equivalent) -----------------------
    compute_dtype: str = "bfloat16"   # activations dtype for conv/matmul
    param_dtype: str = "float32"
    remat: bool = False               # rematerialise the LSTM scan (long seq)
    lstm_impl: str = "auto"           # "auto" | "scan" | "pallas": the
                                      # recurrence for NO-GRAD paths
                                      # (acting/eval).  Training always
                                      # runs the scan (the Pallas backward
                                      # kernel was retired in r5 — on-chip
                                      # it measured 0.96x scan; the fused
                                      # kernel keeps its 1.07x inference
                                      # edge, ops/lstm.py)
    pallas_interpret: bool = False    # run pallas kernels interpreted (CPU tests)
    transfer_guard: bool = False      # arm jax.transfer_guard("disallow")
                                      # windows around every declared
                                      # dispatch/harvest site: an
                                      # UNDECLARED implicit device<->host
                                      # transfer in the hot loop raises
                                      # TransferGuardTripped instead of
                                      # silently stalling the stream
                                      # (docs/ANALYSIS.md; armed after
                                      # bring-up so compile-time staging
                                      # is never misattributed)
    mesh_shape: Tuple[Tuple[str, int], ...] = ()  # learner mesh axes, e.g.
                                      # (("dp", 4), ("fsdp", 2), ("tp", 2)):
                                      # dp = data parallel (batch rows,
                                      # ring slots, grad psums), fsdp =
                                      # param/moment sharding for memory,
                                      # tp = Megatron-style tensor split
                                      # of the LSTM 4H / dense output
                                      # dims.  Omitted axes default to 1;
                                      # empty = all local devices on dp.
                                      # Which param shards where is the
                                      # sharding table's decision
                                      # (parallel/sharding.py,
                                      # docs/SHARDING.md)
    sharding_table: str = ""          # per-param sharding-table override:
                                      # "pattern=axis,axis;pattern2=..."
                                      # entries extend/replace the default
                                      # table (parallel/sharding.py
                                      # DEFAULT_TABLE) — e.g.
                                      # "lstm_*.wh=,tp;head.*.kernel="
                                      # tp-splits wh's gates and fully
                                      # replicates the head kernels.
                                      # Patterns match trailing param-path
                                      # tokens with integer layer indices
                                      # wildcarded; "" keeps the default
                                      # table (docs/SHARDING.md)
    prefetch_batches: int = 4         # reference staging list depth, worker.py:312
    env_workers: int = 0              # >1: thread-pool env stepping (the
                                      # reference's N-process parallelism,
                                      # train.py:30-34); 0/1 = serial
    actor_fleets: int = 1             # independent lockstep fleets, each
                                      # its own thread: fleet A's env
                                      # stepping overlaps fleet B's batched
                                      # inference on multi-core hosts (the
                                      # reference's N actor processes,
                                      # train.py:30-34, regrouped); lanes
                                      # split contiguously, ladder epsilons
                                      # stay global
    actor_transport: str = "thread"   # "thread": fleets are threads in the
                                      # trainer process (scales only when
                                      # the env releases the GIL);
                                      # "process": each fleet is a
                                      # subprocess (parallel/actor_procs),
                                      # blocks return over preallocated
                                      # shared-memory slabs and weights
                                      # arrive on a versioned publication
                                      # queue — the reference's N-process
                                      # topology (train.py:30-34) in
                                      # TPU-native form, for GIL-bound
                                      # envs / multi-core hosts.  Fleet
                                      # inference runs on the host CPU
                                      # backend in this mode.
                                      # "anakin": the Podracer fused loop
                                      # (learner/anakin.py) — env, actor,
                                      # replay writes and train steps run
                                      # as ONE jitted on-device program
                                      # over the pure-JAX env
                                      # (envs/anakin.py); zero host
                                      # crossings on the hot path.
                                      # Requires a jittable env (v1: the
                                      # fake env only) and implies
                                      # device_replay + in_graph_per
                                      # (train() flips them on)
    actor_inference: str = "local"    # process-transport acting:
                                      # "local": each fleet subprocess
                                      # runs its own CPU-jitted act twin
                                      # (weights pumped per fleet).
                                      # "serve": fleets stop running the
                                      # network entirely — every env step
                                      # is an RPC over a per-fleet
                                      # shared-memory act slab to the
                                      # trainer's InferenceService, which
                                      # batches across ALL fleets and
                                      # runs one device act per step with
                                      # server-resident recurrent state
                                      # and ~zero-staleness weights (the
                                      # Sebulba/Seed-RL topology;
                                      # parallel/inference_service.py).
                                      # Thread transport ignores it (the
                                      # fleets already share the
                                      # trainer's act fn in-process)
    param_pump_dtype: str = "float32" # wire dtype for process-fleet
                                      # weight publication: "bfloat16"
                                      # halves the per-fleet pickled
                                      # snapshot (QuaRL: low-precision
                                      # weight transport is ~free in RL);
                                      # fleets cast back to float32 at
                                      # publish, so acting math is
                                      # unchanged — only the wire narrows
    inference_batch_window: float = 0.002  # serve mode: after the first
                                      # pending act request, wait up to
                                      # this many seconds for the other
                                      # lockstep fleets' requests before
                                      # dispatching, so F singleton
                                      # batches coalesce into one
                                      # cross-fleet batch (0 disables)
    act_response_timeout: float = 60.0  # serve mode: per-attempt deadline
                                      # a fleet waits on one act RPC
                                      # before treating the service as
                                      # unresponsive (bounded retries,
                                      # then its circuit breaker opens
                                      # and the fleet degrades to local
                                      # inference on its last pumped
                                      # weights — utils/resilience.py;
                                      # must be > 0 and comfortably above
                                      # the service's worst-case act
                                      # compile; the old behavior was a
                                      # hardcoded 600 s then a fleet-
                                      # killing RuntimeError)
    # --- session-serving tier (r2d2_tpu/serving, docs/SERVING.md) --------
    serve_port: int = -1              # session tier listen port
                                      # (127.0.0.1): > 0 binds that port,
                                      # -1 (default) binds an ephemeral
                                      # OS-assigned one (the bound port
                                      # is printed / on SessionServer
                                      # .port).  Used by `r2d2_tpu serve`
    serve_max_sessions: int = 1024    # server-resident recurrent-state
                                      # budget: concurrent sessions whose
                                      # (2, layers, H) hidden lives in
                                      # the SessionStore pool; admitting
                                      # past it LRU-evicts the least-
                                      # recently-used idle session (an
                                      # in-flight session is never
                                      # evicted — the admit sheds
                                      # instead)
    serve_max_batch: int = 256        # continuous-batching cap: the
                                      # batch loop drains up to this many
                                      # pending act requests per turn and
                                      # bucket-pads them into one of
                                      # log2(serve_max_batch)+1 pre-
                                      # compiled act entry points
                                      # (serving/batcher.py)
    serve_dtype: str = "float32"      # quantized act path: "bfloat16"
                                      # rounds every f32 param leaf
                                      # through bf16 at publish (QuaRL
                                      # weights-only quantization, the
                                      # param_pump_dtype pattern on the
                                      # serving tier), gated by the
                                      # greedy-action-parity test
    serve_session_idle_s: float = 60.0  # idle-reap timeout: a session
                                      # untouched this long (and not in
                                      # flight) is reaped — abandoned
                                      # clients must never pin hidden-
                                      # state slots
    serve_pending_max: int = 4096     # bound on the admission queue:
                                      # past it act requests are shed
                                      # with a 429-style reply (counted
                                      # in serving.rejected) — never an
                                      # unbounded wait
    serve_request_deadline: float = 5.0  # per-request deadline: a
                                      # request still queued past this
                                      # answers 408 instead of being
                                      # served stale (the client gave up)
    replay_shards: int = 1            # host replay owner processes
                                      # (parallel/replay_shards.py): 1 =
                                      # the in-process ring+sum-tree (the
                                      # default, unchanged code shape);
                                      # K > 1 splits the ring across K
                                      # spawn-started shard processes —
                                      # ingest routes blocks round-robin
                                      # over the shm block wire format,
                                      # the learner's sample thread
                                      # issues stratified sample RPCs
                                      # answered with preassembled
                                      # batches over preallocated
                                      # response slabs, and priority
                                      # feedback fans back to the owning
                                      # shards.  Strata allocate across
                                      # shards proportionally to priority
                                      # mass, so sampling stays
                                      # content-for-content
                                      # distribution-equivalent to K=1.
                                      # Host replay only (device_replay
                                      # keeps its own device sharding);
                                      # num_blocks must divide by K
    replay_sample_timeout: float = 5.0  # sharded replay: per-RPC deadline
                                      # the sample thread waits on one
                                      # shard's preassembled batch before
                                      # marking it suspect and
                                      # redistributing its rows over the
                                      # healthy shards' mass (the learner
                                      # never stalls on a dead or stalled
                                      # shard); must be > 0
    replay_transport: str = "shm"     # how the sharded replay plane's
                                      # RPCs travel: "shm" (same-host
                                      # owner processes over preallocated
                                      # shared-memory slabs — the fast
                                      # path, parallel/replay_shards.py)
                                      # or "socket" (length-framed CRC'd
                                      # TCP frames, replay/netwire.py +
                                      # parallel/replay_net.py — the
                                      # cross-host fabric; with no
                                      # replay_hosts the plane spawns
                                      # loopback shard servers itself,
                                      # keeping the whole wire path
                                      # tier-1-testable)
    replay_hosts: str = ""            # socket transport only: comma-
                                      # separated "host:port" endpoints,
                                      # one per replay shard, of already-
                                      # running `r2d2_tpu replay-shard`
                                      # servers.  Empty = managed
                                      # loopback (the plane spawns local
                                      # shard servers on ephemeral
                                      # 127.0.0.1 ports).  Remote shards
                                      # are re-attached through the epoch
                                      # handshake on reconnect, never
                                      # respawned from here
    replay_net_cooldown: float = 2.0  # socket transport: per-shard-link
                                      # circuit-breaker cooldown — while
                                      # a link's circuit is open its mass
                                      # leaves the gossiped view and its
                                      # strata redistribute; one probe
                                      # RPC per cooldown re-closes it
                                      # (utils/resilience.py); must be >0
    replay_net_send_budget: float = 2.0  # socket transport: hard bound on
                                      # one ingest frame send before the
                                      # block is dropped-with-count — a
                                      # partitioned shard must never
                                      # wedge an actor sink; must be > 0
    device_replay: bool = False       # replay data lives in HBM; batches
                                      # are gathered in-graph (device_ring)
    device_ring_layout: str = "auto"  # "replicated" (full ring per device)
                                      # | "dp" (ring sharded over dp, per-
                                      # group sampling) | "auto" (replicate
                                      # if it fits, else shard)
    superstep_k: int = 8              # train steps fused per dispatch when
                                      # device_replay (learner/step.py)
    superstep_pipeline: int = 1       # in-flight dispatches the learner
                                      # keeps ahead of its result harvest
                                      # (both learner loops): hides D2H
                                      # round-trip latency at the cost of
                                      # priority-feedback lag — up to
                                      # (pipeline+1)*superstep_k updates
                                      # under device_replay, up to pipeline
                                      # single steps in the host-staged
                                      # loop (train_sync forces 0: inline
                                      # feedback)
    act_device: str = "auto"          # actor inference backend: "auto"
                                      # (CPU when the learner owns an
                                      # accelerator), "cpu", or "default"
    in_graph_per: bool = False        # device-resident PER: prioritized
                                      # sampling, IS weights, AND priority
                                      # feedback run INSIDE the super-step
                                      # (learner/step.py), so the learner
                                      # needs zero host round trips per
                                      # dispatch and the k inner steps see
                                      # fresh priorities (the host path's
                                      # feedback lags >= k updates).
                                      # Requires device_replay; composes
                                      # with replicated AND dp-sharded
                                      # rings, single- and multi-host.
                                      # Default False only for the plain
                                      # constructor (host-replay users);
                                      # the device-replay learning presets
                                      # turn it ON — see pong_config's
                                      # rationale
    # --- robustness / recovery (SURVEY §5.3-grade, no reference equivalent)
    keep_checkpoints: int = 0         # >0: after each successful save, GC
                                      # all but the newest N COMPLETE
                                      # checkpoints (+ their replay
                                      # snapshots); in-progress saves are
                                      # never collected.  0 keeps all
    replay_snapshot: bool = True      # full-state recovery: at shutdown
                                      # (incl. SIGTERM/SIGINT drain) write
                                      # the replay ring + sum-tree +
                                      # counters + actor RNG/env state
                                      # next to the learner checkpoint so
                                      # --resume restarts with a warm
                                      # buffer.  Host-ring buffers only;
                                      # device_replay runs persist learner
                                      # state alone (docs/OPERATIONS.md)
    replay_snapshot_interval: float = 0.0  # seconds between periodic
                                      # replay snapshots mid-run (0 = only
                                      # at shutdown).  Periodic snapshots
                                      # capture the buffer consistently
                                      # (its lock) but skip thread-
                                      # transport actor state — the warm
                                      # ring is the expensive asset a
                                      # kill -9 must not lose
    learner_stall_timeout: float = 0.0  # >0: a heartbeat watchdog declares
                                      # the learner stalled after this
                                      # many seconds without a loop
                                      # iteration and stops the fabric
                                      # (set it above the worst-case XLA
                                      # compile; 0 disables)
    chaos_spec: str = ""              # deterministic fault injection
                                      # (utils/chaos.py), e.g.
                                      # "kill_fleet:every=500;garble_block:p=0.01"
                                      # — drills/soaks only; "" disables
    dispatch_deadline: float = 0.0    # anakin transport: >0 bounds one
                                      # fused-dispatch harvest to this
                                      # many seconds; a dispatch that
                                      # blows the budget (wedged device,
                                      # chaos wedge_dispatch drill) makes
                                      # the loop snapshot its full state
                                      # and abort cleanly instead of
                                      # training on through a flaky
                                      # device (0 disables — the
                                      # heartbeat watchdog + periodic
                                      # snapshots remain the backstop)
    # --- telemetry (r2d2_tpu/telemetry, docs/OBSERVABILITY.md) ------------
    telemetry_port: int = 0           # HTTP scrape endpoint (/metrics
                                      # Prometheus text, /healthz,
                                      # /statusz JSON) on 127.0.0.1:
                                      # 0 disables (default), >0 binds
                                      # that port, -1 binds an ephemeral
                                      # OS-assigned port (tests/multi-run
                                      # hosts; the bound port surfaces in
                                      # log entries and train() metrics)
    log_history_cap: int = 512        # in-memory stats entries train()
                                      # retains (a ring — the JSONL run
                                      # log under <ckpt_dir>/telemetry/
                                      # is the durable record; the old
                                      # unbounded list leaked in soaks)
    telemetry_log_max_bytes: int = 64_000_000  # run.jsonl size cap
                                      # before rotation to .1/.2/...
                                      # (append-only either way: resume
                                      # continues the same file)
    trace_buffer_events: int = 4096   # per-process event-ring capacity of
                                      # the cross-process tracer
                                      # (telemetry/tracing.py): each
                                      # process of the fabric (trainer,
                                      # fleets, replay shards) owns one
                                      # preallocated ring of this many
                                      # fixed-size records; a capture
                                      # window keeps the newest N (older
                                      # events overflow, counted in the
                                      # dump status)
    trace_steps: int = 0              # >0: arm one cross-process trace
                                      # capture at run start covering
                                      # this many train steps, dumped to
                                      # <ckpt_dir>/telemetry/trace_1.json
                                      # (Chrome trace JSON — load in
                                      # Perfetto).  0 (default) records
                                      # nothing; a live run is captured
                                      # on demand via the exporter's
                                      # /tracez endpoint instead
                                      # (--trace-steps / docs/
                                      # OBSERVABILITY.md)
    # --- learning health (telemetry/learnhealth.py, docs/OBSERVABILITY.md)
    learnhealth_interval: int = 0     # >0: every N optimizer steps the
                                      # jitted train step computes the
                                      # in-graph diagnostic bundle
                                      # (lax.cond-gated: the paper's ΔQ
                                      # stored-vs-recomputed-state
                                      # divergence via a zero-state
                                      # re-unroll, |TD|/IS-weight
                                      # histograms, grad/update/param
                                      # norms, target lag, max|Q|, the
                                      # NaN/Inf sentry) riding the
                                      # existing per-dispatch D2H fetch.
                                      # 0 (default) compiles the step
                                      # without the bundle — bit-
                                      # identical to the pre-learnhealth
                                      # program
    alert_loss_spike_factor: float = 10.0  # loss_spike alert rule: a
                                      # harvested loss above this factor
                                      # times the loss EWMA fires
                                      # learnhealth.alert{rule=
                                      # "loss_spike"} (always armed;
                                      # must be > 1)
    alert_dq_budget: float = 0.0      # >0: dq_drift alert rule — the
                                      # armed diag's mean ΔQ above this
                                      # budget fires (edge-triggered);
                                      # 0 disables (no universal ΔQ
                                      # scale exists — set it from a
                                      # healthy run's learnhealth.dq_mean)
    alert_ess_min: float = 0.0        # >0: ess_collapse alert rule —
                                      # any ring/shard whose PER
                                      # effective-sample-size fraction
                                      # drops below this (with at least
                                      # batch_size positive leaves)
                                      # fires; 0 disables
    alert_replay_ratio_min: float = 0.0  # replay_ratio alert band lower
                                      # edge (meaningful only when
                                      # alert_replay_ratio_max > 0)
    alert_replay_ratio_max: float = 0.0  # >0: replay_ratio alert rule —
                                      # the cumulative samples-per-
                                      # insert ratio leaving
                                      # [alert_replay_ratio_min, max]
                                      # fires (edge-triggered); 0
                                      # disables the band
    anakin_env_steps_per_update: int = 4  # anakin transport: fused
                                      # env/actor steps per optimizer step
                                      # inside the super-step (the
                                      # actor:learner cadence the threaded
                                      # fabric gets implicitly; 4 mirrors
                                      # train_sync's default interleave)
    anakin_episode_len: int = 32      # anakin transport: the pure-JAX
                                      # env's truncation length
                                      # (envs/anakin.py; must be <=
                                      # max_episode_steps — the fused
                                      # loop relies on truncation firing
                                      # before the episode-step cap)
    anakin_env: str = "fake"          # anakin transport: which jittable
                                      # env the fused loop steps —
                                      # "fake" (the vmapped FakeAtariEnv
                                      # twin) or "grid" (the goal-
                                      # seeking gridworld, envs/grid.py
                                      # oracle).  Both run through the
                                      # UNCHANGED fused program via the
                                      # envs/anakin.py four-method
                                      # surface (make_anakin_env)
    anakin_eval_interval: int = 0     # anakin transport: >0 runs an
                                      # in-graph GREEDY eval lane every
                                      # N fused dispatches (lax.cond-
                                      # gated: one truncation-length
                                      # episode per lane with epsilon=0,
                                      # results riding the existing
                                      # per-dispatch result vector) so
                                      # anakin learning curves need no
                                      # host env; 0 (default) disables
                                      # — the compiled program then
                                      # carries no eval branch
    fused_double_unroll: bool = False  # compute the online+target forwards
                                      # as ONE unroll vmapped over stacked
                                      # params: half the sequential LSTM
                                      # chain at double per-step batch
                                      # (learner/step.py:_double_unroll);
                                      # off until measured faster on the
                                      # target chip
    seed: int = 0

    # --- derived ----------------------------------------------------------
    @property
    def stored_obs_shape(self) -> Tuple[int, int, int]:
        """Observation shape as stored/batched/fed to the network:
        space-to-depth folded when ``obs_space_to_depth`` (envs apply the
        fold at emission, everything downstream sees only this shape)."""
        if not self.obs_space_to_depth:
            return self.obs_shape
        h, w, c = self.obs_shape
        return (h // 4, w // 4, 16 * c)

    @property
    def seq_len(self) -> int:
        """reference: config.py:30 (burn_in + learning + forward)."""
        return self.burn_in_steps + self.learning_steps + self.forward_steps

    @property
    def seqs_per_block(self) -> int:
        """Sequences per block (reference: worker.py:48)."""
        return self.block_length // self.learning_steps

    @property
    def num_blocks(self) -> int:
        """Ring size in blocks (reference: worker.py:47)."""
        return self.buffer_capacity // self.block_length

    @property
    def num_sequences(self) -> int:
        """PER leaf count (reference: worker.py:45)."""
        return self.buffer_capacity // self.learning_steps

    @property
    def max_block_steps(self) -> int:
        """Max env steps stored per block incl. burn-in prefix and the final obs."""
        return self.block_length + self.burn_in_steps + 1

    def __post_init__(self):
        if self.block_length % self.learning_steps != 0:
            raise ValueError(
                f"block_length ({self.block_length}) must be a multiple of "
                f"learning_steps ({self.learning_steps})"
            )
        if self.buffer_capacity % self.block_length != 0:
            raise ValueError("buffer_capacity must be a multiple of block_length")
        if self.forward_steps < 1:
            raise ValueError("forward_steps must be >= 1")
        if self.num_actors < 1:
            raise ValueError("num_actors must be >= 1")
        if self.env_workers < 0:
            raise ValueError("env_workers must be >= 0")
        if not (1 <= self.actor_fleets <= self.num_actors):
            raise ValueError(
                f"actor_fleets ({self.actor_fleets}) must be in "
                f"[1, num_actors={self.num_actors}]")
        if self.actor_transport not in ("thread", "process", "anakin"):
            raise ValueError(
                f"unknown actor_transport {self.actor_transport!r} "
                "(expected 'thread', 'process' or 'anakin')")
        if self.anakin_env_steps_per_update < 1:
            raise ValueError("anakin_env_steps_per_update must be >= 1")
        if self.anakin_episode_len < 1:
            raise ValueError("anakin_episode_len must be >= 1")
        if self.anakin_env not in ("fake", "grid"):
            raise ValueError(
                f"unknown anakin_env {self.anakin_env!r} (expected 'fake' "
                "or 'grid' — a custom jittable env plugs in at the "
                "envs/anakin.py four-method surface)")
        if self.anakin_eval_interval < 0:
            raise ValueError(
                "anakin_eval_interval must be >= 0 (0 disables the "
                "in-graph eval lane)")
        if (self.actor_transport == "anakin"
                and self.anakin_episode_len > self.max_episode_steps):
            raise ValueError(
                f"anakin_episode_len ({self.anakin_episode_len}) must be "
                f"<= max_episode_steps ({self.max_episode_steps}) — the "
                "fused loop has no episode-step-cap bootstrap path")
        if self.actor_inference not in ("local", "serve"):
            raise ValueError(
                f"unknown actor_inference {self.actor_inference!r} "
                "(expected 'local' or 'serve')")
        if self.actor_inference == "serve" and self.actor_transport != "process":
            raise ValueError(
                "actor_inference='serve' requires actor_transport='process' "
                "(thread fleets already share the trainer's act fn; the "
                "inference service exists to centralize subprocess acting)")
        if self.param_pump_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"unknown param_pump_dtype {self.param_pump_dtype!r} "
                "(expected 'float32' or 'bfloat16')")
        if self.inference_batch_window < 0:
            raise ValueError("inference_batch_window must be >= 0")
        if self.act_response_timeout <= 0:
            raise ValueError(
                "act_response_timeout must be > 0 (the act RPC deadline "
                "is what keeps a frozen service from wedging a fleet "
                "forever — there is no unbounded mode)")
        if self.dispatch_deadline < 0:
            raise ValueError("dispatch_deadline must be >= 0 (0 disables)")
        if not (-1 <= self.serve_port <= 65535):
            raise ValueError(
                f"serve_port must be in [-1, 65535] (-1 = ephemeral), "
                f"got {self.serve_port}")
        if self.serve_max_sessions < 1:
            raise ValueError("serve_max_sessions must be >= 1")
        if self.serve_max_batch < 1:
            raise ValueError("serve_max_batch must be >= 1")
        if self.serve_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"unknown serve_dtype {self.serve_dtype!r} "
                "(expected 'float32' or 'bfloat16')")
        if self.serve_session_idle_s <= 0:
            raise ValueError(
                "serve_session_idle_s must be > 0 (the idle reaper is "
                "what keeps abandoned sessions from pinning hidden-state "
                "slots — there is no unbounded mode)")
        if self.serve_pending_max < 1:
            raise ValueError("serve_pending_max must be >= 1")
        if self.serve_request_deadline <= 0:
            raise ValueError(
                "serve_request_deadline must be > 0 (the per-request "
                "deadline is what keeps a backlogged tier from serving "
                "replies nobody awaits — there is no unbounded mode)")
        if self.superstep_k < 1:
            raise ValueError("superstep_k must be >= 1")
        if self.superstep_pipeline < 0:
            raise ValueError("superstep_pipeline must be >= 0")
        if self.replay_shards < 1:
            raise ValueError("replay_shards must be >= 1 (1 = in-process)")
        if self.replay_shards > 1:
            if self.device_replay:
                raise ValueError(
                    "replay_shards > 1 shards the HOST replay plane; "
                    "device_replay has its own dp slot sharding "
                    "(device_ring_layout) — pick one")
            if self.actor_transport == "anakin":
                raise ValueError(
                    "replay_shards > 1 is meaningless under the anakin "
                    "transport (the fused loop keeps replay on-device)")
            if self.num_blocks % self.replay_shards:
                raise ValueError(
                    f"num_blocks ({self.num_blocks}) must divide evenly "
                    f"over replay_shards ({self.replay_shards}) so every "
                    "shard owns an equal slot slice")
        if self.replay_sample_timeout <= 0:
            raise ValueError(
                "replay_sample_timeout must be > 0 (the sample RPC "
                "deadline is what keeps a dead shard from wedging the "
                "sample thread — there is no unbounded mode)")
        if self.replay_transport not in ("shm", "socket"):
            raise ValueError(
                f"replay_transport must be 'shm' or 'socket', got "
                f"{self.replay_transport!r}")
        if self.replay_hosts and self.replay_transport != "socket":
            raise ValueError(
                "replay_hosts names remote replay-shard servers and only "
                "means anything with replay_transport='socket'")
        if self.replay_transport == "socket":
            if self.device_replay:
                raise ValueError(
                    "replay_transport='socket' moves the HOST replay "
                    "plane off-host; device_replay keeps replay in HBM — "
                    "pick one")
            if self.actor_transport == "anakin":
                raise ValueError(
                    "replay_transport='socket' is meaningless under the "
                    "anakin transport (the fused loop keeps replay "
                    "on-device)")
            if self.num_blocks % self.replay_shards:
                raise ValueError(
                    f"num_blocks ({self.num_blocks}) must divide evenly "
                    f"over replay_shards ({self.replay_shards}) so every "
                    "shard owns an equal slot slice")
            if self.replay_hosts:
                hosts = parse_replay_hosts(self.replay_hosts)
                if len(hosts) != self.replay_shards:
                    raise ValueError(
                        f"replay_hosts names {len(hosts)} endpoints but "
                        f"replay_shards is {self.replay_shards} — one "
                        "host:port per shard")
        if self.replay_net_cooldown <= 0:
            raise ValueError(
                "replay_net_cooldown must be > 0 (the circuit cooldown "
                "paces re-attach probes to a partitioned shard)")
        if self.replay_net_send_budget <= 0:
            raise ValueError(
                "replay_net_send_budget must be > 0 (the bounded ingest "
                "send is what keeps a partitioned shard from wedging an "
                "actor sink — there is no unbounded mode)")
        if self.in_graph_per and not self.device_replay:
            raise ValueError("in_graph_per requires device_replay=True "
                             "(sampling reads the HBM-resident ring)")
        # in_graph_per composes with every ring layout: the stratified
        # draw is global either way — under a dp-sharded ring the PER
        # leaves shard with the slabs and GSPMD inserts the collectives
        # (parallel/sharding.py pjit_in_graph_per_super_step)
        if self.device_ring_layout not in ("auto", "replicated", "dp"):
            raise ValueError(
                f"unknown device_ring_layout {self.device_ring_layout!r}")
        if self.act_device not in ("auto", "cpu", "default"):
            raise ValueError(f"unknown act_device {self.act_device!r}")
        if self.torso not in ("nature", "impala", "mlp"):
            raise ValueError(f"unknown torso {self.torso!r}")
        if self.lstm_layers < 1:
            raise ValueError("lstm_layers must be >= 1")
        if self.lstm_impl not in ("auto", "scan", "pallas"):
            raise ValueError(f"unknown lstm_impl {self.lstm_impl!r} "
                             "(pallas_spmd was retired in r5 with the "
                             "backward kernel — training always scans)")
        if self.keep_checkpoints < 0:
            raise ValueError("keep_checkpoints must be >= 0 (0 keeps all)")
        if self.replay_snapshot_interval < 0:
            raise ValueError("replay_snapshot_interval must be >= 0")
        if self.learner_stall_timeout < 0:
            raise ValueError("learner_stall_timeout must be >= 0")
        if not (-1 <= self.telemetry_port <= 65535):
            raise ValueError(
                f"telemetry_port must be in [-1, 65535] (0 = disabled, "
                f"-1 = ephemeral), got {self.telemetry_port}")
        if self.log_history_cap < 1:
            raise ValueError("log_history_cap must be >= 1")
        if self.telemetry_log_max_bytes < 1024:
            raise ValueError("telemetry_log_max_bytes must be >= 1024")
        if self.trace_buffer_events < 64:
            raise ValueError(
                "trace_buffer_events must be >= 64 (a capture window "
                "needs room for at least a few block lifecycles)")
        if self.trace_steps < 0:
            raise ValueError("trace_steps must be >= 0 (0 = no boot-time "
                             "capture; /tracez arms one on demand)")
        if self.learnhealth_interval < 0:
            raise ValueError(
                "learnhealth_interval must be >= 0 (0 disables the "
                "in-graph diagnostics)")
        if self.alert_loss_spike_factor <= 1.0:
            raise ValueError(
                "alert_loss_spike_factor must be > 1 (a factor <= 1 "
                "would fire on every ordinary loss fluctuation)")
        if self.alert_dq_budget < 0:
            raise ValueError("alert_dq_budget must be >= 0 (0 disables)")
        if not (0.0 <= self.alert_ess_min < 1.0):
            raise ValueError(
                "alert_ess_min must be in [0, 1) — it is a fraction of "
                "the positive leaf count (0 disables)")
        if self.alert_replay_ratio_min < 0 or self.alert_replay_ratio_max < 0:
            raise ValueError("replay-ratio alert band edges must be >= 0")
        if (self.alert_replay_ratio_max > 0
                and self.alert_replay_ratio_min
                > self.alert_replay_ratio_max):
            raise ValueError(
                "alert_replay_ratio_min must not exceed "
                "alert_replay_ratio_max")
        if self.league_eval_episodes < 1:
            raise ValueError("league_eval_episodes must be >= 1")
        if self.league_eval_interval <= 0:
            raise ValueError(
                "league_eval_interval must be > 0 (the sidecar's "
                "checkpoint poll cadence)")
        if self.league_eval_deadline < 0:
            raise ValueError(
                "league_eval_deadline must be >= 0 (0 = unbounded)")
        if self.population_spec:
            members = parse_population(self.population_spec)
            if self.actor_transport != "process":
                raise ValueError(
                    "population_spec requires actor_transport='process' "
                    "— members run as fleet subprocesses, one per "
                    "member (the thread/anakin transports have no "
                    "per-fleet config axis)")
            if len(members) != self.actor_fleets:
                raise ValueError(
                    f"population_spec declares {len(members)} members "
                    f"but actor_fleets={self.actor_fleets} — one fleet "
                    "per member; set actor_fleets to the member count")
            for m in members:
                # full member-config validation: every override
                # combination must itself construct (epsilon/knob
                # ranges all re-checked through this same __post_init__)
                dataclasses.replace(self, population_spec="",
                                    **m["overrides"])
        if self.chaos_spec:
            # fail at construction, not mid-run: parse_spec raises on an
            # unknown kind/param or a clause without a trigger
            from r2d2_tpu.utils.chaos import parse_spec

            parse_spec(self.chaos_spec)
        # mesh axes are fixed (dp, fsdp, tp) — the sharding table resolves
        # against them
        validate_mesh_shape(self.mesh_shape)
        if self.sharding_table:
            # fail at construction, not mid-compile: parse_table raises on
            # malformed clauses / unknown axis names
            parse_table(self.sharding_table)
        if self.stored_hidden_mode not in ("burn_in_start", "seq_start"):
            raise ValueError(
                f"unknown stored_hidden_mode {self.stored_hidden_mode!r}")
        if self.obs_space_to_depth:
            h, w, _ = self.obs_shape
            if h % 4 or w % 4:
                raise ValueError(
                    f"obs_space_to_depth needs obs H/W divisible by 4, got "
                    f"{self.obs_shape}")
            if self.torso == "impala":
                raise ValueError(
                    "obs_space_to_depth is for the nature/mlp torsos; the "
                    "impala torso consumes raw frames")
        # lstm_impl × remat needs no guard since r5: remat applies to the
        # training scan, and training always scans — the pallas kernel
        # only ever serves no-grad unrolls, where remat is meaningless

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


# --- presets mirroring BASELINE.json configs[0..4] ------------------------

def _clamp_fleets(base: dict, kw: dict) -> dict:
    """Presets that default ``actor_fleets`` > 1 must not make a
    scaled-down ``num_actors`` override (e.g. ``--actors 2``) invalid;
    clamp the default — but never an explicit ``actor_fleets`` override —
    to the actor count."""
    if "actor_fleets" not in kw:
        base["actor_fleets"] = min(base["actor_fleets"], base["num_actors"])
    return base

def parse_replay_hosts(spec: str):
    """``"host:port,host:port"`` → ``[(host, port), ...]``.  Raises
    ValueError on a malformed entry (Config validation calls this so a
    typo fails at construction, not at first connect)."""
    out = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        host, sep, port = entry.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"replay_hosts entry {entry!r} is not 'host:port'")
        try:
            port_n = int(port)
        except ValueError:
            raise ValueError(
                f"replay_hosts entry {entry!r} has a non-integer port")
        if not 1 <= port_n <= 65535:
            # 0 is never a valid connect target (the managed plane uses
            # it internally as the not-yet-spawned sentinel)
            raise ValueError(
                f"replay_hosts entry {entry!r}: port out of range")
        out.append((host, port_n))
    return out


def smoke_config(**kw) -> Config:
    """configs[0]: MsPacman, 1 actor, LSTM-512 CPU smoke."""
    base = dict(game_name="MsPacman", num_actors=1)
    base.update(kw)
    return Config(**base)


def pong_config(**kw) -> Config:
    """configs[1]: Pong, 64 actors.

    superstep_k=4: the priority-feedback lag is ≤ (pipeline+1)·k = 12
    updates — the reference's own lag envelope (8-batch queue + 4-batch
    staging, worker.py:300-316).  k=16 (lag 48) showed a measurable
    late-curve tax in the 4-run fabric A/B (CURVES_AB_PIPELINE_r04*:
    late-mean 22.9 vs 27.7 baseline, k=4 at parity 26.1); k=16 remains a
    throughput-bench knob, not a learning default.

    in_graph_per=True (flipped r5): the CPU A/B measured 2.2× the
    host-sampled update rate at learning parity (2 seeds × 3 network
    families, CURVES_*_INGRAPH_r04, 60-min soak SOAK_INGRAPH_LONG_r04)
    — and CPU is the feature's WORST case: it removes a per-harvest
    host round trip (~99 ms on the tunneled chip, MEASURE_TPU_r04.md
    learner.result_sync) that costs ~nothing on CPU, so the on-chip win
    is bounded below by the CPU win.  bench.py reports the host-path and
    in-graph cells side by side (system_env_frames_per_sec vs
    system_ingraph_env_frames_per_sec) so every round's artifact
    re-checks this choice on real hardware."""
    base = dict(game_name="Pong", num_actors=64, env_workers=8,
                device_replay=True, in_graph_per=True,
                superstep_k=4, superstep_pipeline=2)
    base.update(kw)
    return Config(**base)


def hard_exploration_config(game: str = "MontezumaRevenge", **kw) -> Config:
    """configs[2]: hard-exploration Atari, 256 actors.  superstep_k=4 and
    in_graph_per=True: see pong_config's rationale."""
    base = dict(game_name=game, num_actors=256, env_workers=16,
                actor_fleets=4,
                device_replay=True, in_graph_per=True,
                superstep_k=4, superstep_pipeline=2)
    base.update(kw)
    return Config(**_clamp_fleets(base, kw))


def atari57_config(game: str, **kw) -> Config:
    """configs[3]: Atari-57 sweep, 256 actors, seq-len 80 (paper hyperparams)."""
    base = dict(
        game_name=game, num_actors=256, env_workers=16, actor_fleets=4,
        burn_in_steps=40, learning_steps=40, forward_steps=5,
    )
    base.update(kw)
    return Config(**_clamp_fleets(base, kw))


def impala_deep_config(game: str = "MsPacman", **kw) -> Config:
    """configs[4]: IMPALA-deep CNN + 2-layer LSTM, seq-len 120."""
    base = dict(
        game_name=game, torso="impala", lstm_layers=2,
        burn_in_steps=40, learning_steps=75, forward_steps=5,
        block_length=375, buffer_capacity=1_500_000, remat=True,
        obs_space_to_depth=False,
    )
    base.update(kw)
    return Config(**base)


def low_resource_config(game: str = "MsPacman", **kw) -> Config:
    """Workstation-scale R2D2 after "Human-Level Control without
    Server-Grade Hardware" (PAPERS.md): a smaller recurrent net, a
    shorter replay ring, fewer actors and a shorter n-step/discount
    horizon, tuned for a single commodity host instead of a pod.  Also
    the base config the ``low_resource`` population-member preset slices
    its acting-side knobs from (POPULATION_PRESETS — a member may only
    override the scenario axis; the net/replay shrinkage here applies
    when the preset is the RUN's base config)."""
    base = dict(
        game_name=game, num_actors=16, env_workers=4, actor_fleets=2,
        hidden_dim=256, batch_size=32,
        buffer_capacity=500_000, learning_starts=20_000,
        block_length=200, burn_in_steps=20, learning_steps=40,
        forward_steps=3, gamma=0.99, base_eps=0.3, eps_alpha=5.0,
    )
    base.update(kw)
    return Config(**_clamp_fleets(base, kw))


def test_config(**kw) -> Config:
    """Tiny config for unit/integration tests: small windows, tiny buffer."""
    base = dict(
        obs_shape=(12, 12, 1), torso="mlp",
        burn_in_steps=4, learning_steps=4, forward_steps=2,
        block_length=8, buffer_capacity=160, learning_starts=16,
        batch_size=8, hidden_dim=16, num_actors=2,
        max_episode_steps=50, training_steps=20,
        compute_dtype="float32", prefetch_batches=0,
        obs_space_to_depth=False,
    )
    base.update(kw)
    return Config(**base)
