"""Single-chip learner throughput benchmark.

Measures the jitted R2D2 train step on the flagship config (Nature torso,
LSTM-512, batch 64, T=85 — reference scale knobs, config.py:7,27-33) on the
default JAX platform (the real TPU chip when run by the driver).

Prints ONE JSON line:
  {"metric": "learner_env_frames_per_sec", "value": N, "unit": "frames/s",
   "vs_baseline": N / 50000}

learner env-frames/s = batch * learning_steps * steps/s — the rate at which
the learner consumes environment frames, measured against the BASELINE.md
north star of >= 50,000 frames/s/chip.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

from r2d2_tpu.utils.batch import synthetic_batch as make_batch


def main(steps: int = 100, warmup: int = 5) -> None:
    import jax

    from r2d2_tpu.config import Config
    from r2d2_tpu.learner.step import create_train_state, jit_train_step
    from r2d2_tpu.models.network import create_network, init_params

    cfg = Config()
    action_dim = 9  # MsPacman minimal action set
    net = create_network(cfg, action_dim)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    state = create_train_state(cfg, params)
    step_fn = jit_train_step(cfg, net)

    rng = np.random.default_rng(0)
    batch = {k: jax.device_put(v) for k, v in make_batch(cfg, action_dim,
                                                         rng).items()}

    # synchronize via an actual host transfer: on the tunneled axon TPU
    # platform block_until_ready does not reliably block, so the fence is a
    # fetch of the last warmup loss — a scalar that data-depends on the full
    # forward/backward of every chained step through the donated state
    for _ in range(warmup):
        state, loss, priorities = step_fn(state, batch)
    if warmup:
        float(jax.device_get(loss))

    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss, priorities = step_fn(state, batch)
    final_loss = float(jax.device_get(loss))
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    steps_per_sec = steps / dt
    frames_per_sec = cfg.batch_size * cfg.learning_steps * steps_per_sec
    baseline = 50_000.0
    print(json.dumps({
        "metric": "learner_env_frames_per_sec",
        "value": round(frames_per_sec, 1),
        "unit": "frames/s",
        "vs_baseline": round(frames_per_sec / baseline, 3),
    }))
    print(f"# platform={jax.devices()[0].platform} "
          f"steps/s={steps_per_sec:.2f} dt={dt:.2f}s steps={steps}",
          file=sys.stderr)


if __name__ == "__main__":
    main(steps=int(sys.argv[1]) if len(sys.argv) > 1 else 100)
