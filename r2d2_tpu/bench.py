"""Single-chip benchmark: learner step, actor plane, and the full system.

Three measurements on the default JAX platform (the real TPU chip when run
by the driver):

1. **Learner micro-bench** — the jitted R2D2 train step on the flagship
   config (Nature torso, LSTM-512, batch 64, T=85 — reference scale knobs,
   config.py:7,27-33) with a pre-staged device batch.  This is the
   compute ceiling.  XLA's compiled-module cost analysis grounds it in
   hardware terms (``achieved_tflops``, ``mfu``).
2. **Actor-plane bench** — a 64-lane VectorActor (pong preset scale,
   BASELINE configs[1]) stepping fake envs with batched TPU inference;
   must sustain at least the learner's env-frame consumption rate to not
   starve it (the reference gets this from N actor processes,
   train.py:30-34).
3. **System bench** — the full threaded fabric (``train.train``: actors →
   replay → prioritized sampling → H2D prefetch → learner, priority
   feedback) on fake envs for a fixed wall budget; reports steady-state
   ``updates/s × batch × learning_steps`` and the busiest tracer spans so
   the bottleneck is named, not guessed.

Prints ONE JSON line; the headline metric stays
``learner_env_frames_per_sec`` (vs the 50k frames/s/chip north star),
with the system/actor/MFU numbers as additional fields.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import numpy as np

from r2d2_tpu.utils.batch import synthetic_batch as make_batch

NORTH_STAR_FPS = 50_000.0

# bf16 peak TFLOPS by device_kind prefix (public spec sheets); used for MFU.
_PEAK_TFLOPS = (
    ("TPU v5 lite", 197.0),   # v5e
    ("TPU v5p", 459.0),
    ("TPU v4", 275.0),
    ("TPU v6", 918.0),        # Trillium
)


# the flagship system-bench cell (the learning presets' knobs — k=4 after
# the CURVES_AB_PIPELINE_r04 lag A/B); shared by both bench entry paths so
# script-mode and import-mode always measure the same fabric
FLAGSHIP_SYSTEM_KNOBS = dict(device_replay=True, superstep_k=4,
                             superstep_pipeline=2, num_actors=64,
                             env_workers=0)


def _peak_tflops(kind: str) -> float:
    for prefix, peak in _PEAK_TFLOPS:
        if kind.startswith(prefix):
            return peak
    return 0.0


def _learner_micro_bench(steps: int, warmup: int, fused: bool = False):
    """(frames/s, steps/s, flops_per_step_or_0) for the flagship step.

    ``fused=True`` times the same step with ``fused_double_unroll`` — the
    single double-batch online+target unroll (learner/step.py) — so the
    feature's value is a measured train-step cell, not an extrapolation
    from the B=64/B=128 unroll ratio."""
    import jax

    from r2d2_tpu.config import Config
    from r2d2_tpu.learner.step import create_train_state
    from r2d2_tpu.models.network import create_network, init_params
    from r2d2_tpu.parallel.sharding import pjit_train_step

    cfg = Config(fused_double_unroll=fused)
    action_dim = 9  # MsPacman minimal action set
    net = create_network(cfg, action_dim)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    state = create_train_state(cfg, params)
    # donate_batch=False: this timing loop deliberately re-steps ONE
    # device-resident batch; the training drivetrains always donate
    step_fn = pjit_train_step(cfg, net, state_template=state,
                              donate_batch=False)

    rng = np.random.default_rng(0)
    batch = {k: jax.device_put(v) for k, v in make_batch(cfg, action_dim,
                                                         rng).items()}

    # AOT compile once; the timing loops run the same executable (jit
    # __call__ would compile a second copy of this multi-second module).
    # cost_analysis gives XLA's own FLOP count for it — grounded, not hand
    # derived.  Either is unavailable on some plugin backends → fall back
    # to the jit wrapper / omit the FLOP fields.
    flops = 0.0
    try:
        compiled = step_fn.lower(state, batch).compile()
    except Exception:
        compiled = None
    if compiled is not None:
        step_fn = compiled
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float((cost or {}).get("flops", 0.0))
        except Exception:
            pass

    # synchronize via an actual host transfer: on the tunneled axon TPU
    # platform block_until_ready does not reliably block, so the fence is a
    # fetch of the last warmup loss — a scalar that data-depends on the full
    # forward/backward of every chained step through the donated state
    for _ in range(warmup):
        state, loss, priorities = step_fn(state, batch)
    if warmup:
        float(jax.device_get(loss))

    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss, priorities = step_fn(state, batch)
    final_loss = float(jax.device_get(loss))
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    steps_per_sec = steps / dt
    frames_per_sec = cfg.batch_size * cfg.learning_steps * steps_per_sec
    return frames_per_sec, steps_per_sec, flops


def _actor_plane_bench(iterations: int = 400, num_lanes: int = 64,
                       env_workers: Optional[int] = None,
                       act_device: Optional[str] = None,
                       fleets: int = 1):
    """env-frames/s of a pong-scale lockstep fleet on fake envs.

    ``env_workers``/``act_device``/``fleets`` override the preset so
    tools/actor_scaling.py and the measurement battery can sweep the
    env-stepping pool width, CPU-twin vs on-device acting, and the number
    of independent lockstep fleets (lanes split contiguously, each fleet
    its own thread — exactly train.py's actor_fleets split)."""
    import threading

    import jax

    from r2d2_tpu.actor import VectorActor, make_act_fn
    from r2d2_tpu.config import pong_config
    from r2d2_tpu.envs.fake import FakeAtariEnv
    from r2d2_tpu.models.network import create_network, init_params
    from r2d2_tpu.utils.math import epsilon_ladder
    from r2d2_tpu.utils.store import ParamStore

    over = {}
    if env_workers is not None:
        over["env_workers"] = env_workers
    if act_device is not None:
        over["act_device"] = act_device
    cfg = pong_config(game_name="Fake", num_actors=num_lanes, **over)
    net = create_network(cfg, 4)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    store = ParamStore(params)
    act_fn = make_act_fn(cfg, net)
    sunk = []
    per = num_lanes // fleets
    actors = []
    for f in range(fleets):
        lanes = range(f * per, (f + 1) * per)
        envs = [FakeAtariEnv(obs_shape=cfg.stored_obs_shape, action_dim=4,
                             seed=i, episode_len=500) for i in lanes]
        eps = [epsilon_ladder(i, num_lanes) for i in lanes]
        actors.append(VectorActor(cfg, envs, eps, act_fn, store,
                                  sink=lambda b, p, r: sunk.append(1),
                                  rng=np.random.default_rng(1 + f)))
    for a in actors:
        a.run(max_steps=20)  # warmup: compile act fn, prime pools
    # bare Threads by design: these are bounded measurement workers, started
    # and joined inside this one timed window — a Supervisor restart would
    # silently rerun part of the workload and corrupt the timing
    threads = [threading.Thread(target=a.run,  # graftlint: disable=thread-discipline -- bounded, joined below; a restart would corrupt the measurement
                                kwargs=dict(max_steps=iterations))
               for a in actors[1:]]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    actors[0].run(max_steps=iterations)
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    for a in actors:
        a.close()
    return fleets * per * iterations / dt


def _bench_env_factory(cfg, seed):
    """Module-level (picklable) fake-env factory: the process-transport
    bench's spawn children unpickle it by reference."""
    from r2d2_tpu.envs.fake import FakeAtariEnv

    return FakeAtariEnv(obs_shape=cfg.stored_obs_shape, action_dim=4,
                        seed=seed, episode_len=500)


def _actor_plane_bench_process(num_lanes: int = 64, fleets: int = 2,
                               env_workers: int = 0,
                               budget_s: float = 300.0,
                               actor_inference: str = "local"):
    """env-frames/s of the PROCESS-fleet actor plane on fake envs — the
    same pong-scale workload as :func:`_actor_plane_bench`, through
    ``parallel/actor_procs`` instead of in-process threads, so
    tools/actor_scaling.py can put the thread-vs-process per-core slopes
    side by side.  ``actor_inference="serve"`` measures the centralized
    InferenceService path (ISSUE 3): fleets RPC a trainer-side act server
    that batches across all of them, driven here by a dedicated serve
    thread standing in for the fabric's ``inference_serve`` loop.

    The trainer only observes block-granular arrivals, and a lockstep
    fleet cuts ALL its lanes' blocks in the same iteration — arrivals are
    periodic BURSTS (strictly alternating 400-step boundary cuts and
    episode-truncation cuts at the fake env's 500-step episodes), so a
    fixed wall window aliases against the burst phase.  Instead, per
    fleet, frames are timed from the start of burst 0 to the start of
    burst 2 — a stride of 2 spans exactly one full 500-step cut cycle —
    which is phase-exact; the fleet rates sum to the plane rate.  Burst
    boundaries are identified by COUNT, not wall-clock gaps (every burst
    is exactly one block per lane, in order), so the alignment holds at
    any host speed.  Children's jax-import + act-fn compile happens
    before their first burst and is never charged."""
    import jax

    from r2d2_tpu.config import pong_config
    from r2d2_tpu.models.network import create_network, init_params
    from r2d2_tpu.parallel.actor_procs import ProcessFleetPlane
    from r2d2_tpu.utils.math import epsilon_ladder
    from r2d2_tpu.utils.store import ParamStore

    import threading

    cfg = pong_config(game_name="Fake", num_actors=num_lanes,
                      env_workers=env_workers, actor_fleets=fleets,
                      actor_transport="process",
                      actor_inference=actor_inference)
    net = create_network(cfg, 4)
    store = ParamStore(init_params(cfg, net, jax.random.PRNGKey(0)))
    eps = [epsilon_ladder(i, num_lanes) for i in range(num_lanes)]
    plane = ProcessFleetPlane(cfg, 4, _bench_env_factory, eps)
    F = plane.num_fleets
    serve_stop = threading.Event()
    # Supervisor-managed stand-in for the fabric's ``inference_serve``
    # loop: serve_once is re-enterable (pending requests live in service
    # state), so a crash restarts cleanly instead of wedging every
    # blocked fleet — same discipline train() gives the real loop
    serve_sup = None
    if plane.service is not None:
        from r2d2_tpu.utils.supervisor import Supervisor

        serve_sup = Supervisor(max_restarts=3)

        def _serve_loop():
            while not serve_stop.is_set():
                plane.service.serve_once()
    # a burst = one block per lane, so burst k starts at event index k*L
    lanes = [spec.hi - spec.lo for spec in plane.specs]
    need = [2 * L + 1 for L in lanes]     # through burst 2's first block
    events = [[] for _ in range(F)]       # per fleet: (t, frames)

    def noop_sink(block, prios, episode_reward):
        pass

    try:
        plane.start(store)
        if serve_sup is not None:
            serve_sup.start("bench_serve", _serve_loop)
        deadline = time.time() + budget_s
        while (time.time() < deadline
               and any(len(ev) < n for ev, n in zip(events, need))):
            got = plane.ingest_once(noop_sink, timeout=0.2)
            if got is None:
                continue
            src, n = got
            events[src].append((time.perf_counter(), n))
    finally:
        # stop and JOIN the serve thread BEFORE plane.shutdown closes the
        # act channels: a mid-iteration serve_once still holds slab views,
        # and SharedMemory.close under live views raises BufferError
        serve_stop.set()
        if serve_sup is not None:
            serve_sup.join_all(10)
        plane.shutdown()

    rate = 0.0
    for src in range(F):
        ev, L = events[src], lanes[src]
        if len(ev) < need[src]:
            raise RuntimeError(
                f"fleet{src} produced {len(ev)}/{need[src]} blocks in "
                f"{budget_s:.0f} s; need one full cut cycle for a "
                "phase-exact window")
        frames = sum(n for _, n in ev[0:2 * L])
        rate += frames / (ev[2 * L][0] - ev[0][0])
    return rate


def _system_bench(wall_seconds: float, *, device_replay: bool = True,
                  superstep_k: int = 4, num_actors: int = 64,
                  env_workers: int = 0, superstep_pipeline: int = 2,
                  in_graph_per: bool = False):
    """Steady-state env-frames/s of the full threaded fabric on fake envs.

    Returns (frames/s, top_spans, num_updates) where top_spans names the
    busiest tracer stages (the measured bottleneck).  The keyword knobs
    let tools/tune_system.py sweep the same measurement over a grid."""
    from r2d2_tpu.config import Config
    from r2d2_tpu.train import train

    cfg = Config().replace(
        game_name="Fake",
        num_actors=num_actors,
        env_workers=env_workers,
        buffer_capacity=200_000,   # 500-block ring ≈ 1.6 GB (in HBM)
        learning_starts=10_000,
        training_steps=1_000_000_000,  # wall-clock bound, not step bound
        log_interval=5.0,
        save_interval=1_000_000_000,
        device_replay=device_replay,  # HBM-resident ring + in-graph gather
        superstep_k=superstep_k,      # optimizer steps per dispatch — the
                                      # pong/hard-exploration presets' value
                                      # (k=4 since the CURVES_AB_PIPELINE_r04
                                      # lag A/B), so the system number
                                      # measures what the learning configs
                                      # actually run; tools/tune_system.py
                                      # sweeps the grid for the ceiling
        in_graph_per=in_graph_per,    # device-resident PER: zero host
                                      # round trips on the training path
        superstep_pipeline=superstep_pipeline,  # in-flight dispatches:
                                      # result copies start at enqueue, so
                                      # >=2 keeps the device busy while
                                      # results trail
    )
    metrics = train(cfg, max_wall_seconds=wall_seconds, verbose=False)

    # steady state: median updates/s over the logged entries after the
    # buffer reached learning_starts (those report nonzero rates)
    rates = [e["updates_per_sec"] for e in metrics.get("logs", [])
             if e["updates_per_sec"] > 0]
    ups = float(np.median(rates[-6:])) if rates else 0.0
    frames_per_sec = ups * cfg.batch_size * cfg.learning_steps

    trace = metrics.get("trace", {})
    spans = sorted(
        ((name[len("span."):-len(".mean_ms")],
          trace[name] * trace.get(name.replace(".mean_ms", ".count"), 0))
         for name in trace if name.endswith(".mean_ms")),
        key=lambda kv: -kv[1])
    top_spans = {name: round(total_ms, 1) for name, total_ms in spans[:5]}
    return frames_per_sec, top_spans, metrics.get("num_updates", 0)


def _device_probe(timeout_s: float = 240.0):
    """Check the accelerator backend answers at all, from a subprocess.

    The tunneled TPU backend can wedge indefinitely on a stale device
    claim (backend init then never returns); probing in a bounded
    subprocess turns that failure mode into a parseable artifact line
    instead of a silent driver-side timeout with no JSON at all.  A
    healthy probe exits cleanly, so its own claim is released.

    Returns ``(ok, reason)`` — reason distinguishes a genuine timeout
    from a fast failure and carries the child's stderr tail so the
    artifact reports the real error, not a guessed one."""
    import subprocess

    proc = None
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        try:
            _, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                # bounded reap: a child wedged in an uninterruptible
                # driver call may be unkillable — leak it rather than
                # recreate the indefinite no-artifact hang
                proc.communicate(timeout=10.0)
            except Exception:
                pass
            return False, ("device probe timed out — tunneled chip claim "
                           "may be wedged")
        if proc.returncode == 0:
            return True, ""
        tail = (err or b"").decode(errors="replace").strip().splitlines()
        return False, (f"device probe failed (rc={proc.returncode}): "
                       + " | ".join(tail[-3:]))
    except Exception as e:
        return False, f"device probe error: {type(e).__name__}: {e}"


def _run_phase(phase: str, timeout_s: float, extra=(), label=None):
    """Run one bench phase as a bounded subprocess; (result_dict, reason).

    Each phase holds its own backend claim and releases it on clean exit;
    a wedged phase (the k=16 tune cell of round 4 sat >20 min at zero CPU
    in an uninterruptible device call) is killed at ``timeout_s`` and
    reported, instead of hanging the driver's whole bench run with no
    artifact.  Phases run strictly one at a time — the tunneled backend
    hands the chip claim between processes."""
    import subprocess

    label = label or phase
    cmd = [sys.executable, "-m", "r2d2_tpu.bench", "--phase", phase,
           *map(str, extra)]
    # the package is run from a source tree, not installed: the child can
    # only import r2d2_tpu with the repo root as cwd, wherever the parent
    # was launched from
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, cwd=repo_root)
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.communicate(timeout=10.0)  # bounded reap (see
            except Exception:                   # _device_probe)
                pass
            return None, (f"{label} phase wedged (no result after "
                          f"{timeout_s:.0f}s; child killed)")
    except Exception as e:
        return None, f"{label} phase spawn error: {type(e).__name__}: {e}"
    tail = (err or b"").decode(errors="replace").strip().splitlines()
    if proc.returncode != 0:
        return None, (f"{label} phase failed (rc={proc.returncode}): "
                      + " | ".join(tail[-3:]))
    for line in reversed((out or b"").decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), ""
            except Exception:
                break
    return None, f"{label} phase emitted no JSON: " + " | ".join(tail[-3:])


def _phase_main(argv) -> int:
    """Child entry for one isolated phase; prints ONE JSON line."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--phase", required=True,
                   choices=("micro", "actor", "system"))
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--seconds", type=float, default=75.0)
    p.add_argument("--knobs", type=str, default="{}")
    p.add_argument("--fused", action="store_true")
    a = p.parse_args(argv)

    from r2d2_tpu.utils.compile_cache import enable as enable_compile_cache

    enable_compile_cache()
    if a.phase == "micro":
        import jax

        fps, sps, flops = _learner_micro_bench(a.steps, a.warmup,
                                               fused=a.fused)
        d = jax.devices()[0]
        out = dict(learner_fps=fps, steps_per_sec=sps, flops=flops,
                   platform=d.platform,
                   device_kind=getattr(d, "device_kind", "?"))
    elif a.phase == "actor":
        out = dict(actor_fps=_actor_plane_bench())
    else:
        fps, spans, ups = _system_bench(a.seconds, **json.loads(a.knobs))
        out = dict(system_fps=fps, top_spans=spans, updates=ups)
    print(json.dumps(out), flush=True)
    return 0


def _main_isolated(steps: int, warmup: int, system_seconds: float) -> None:
    """Driver-facing bench: every phase in its own bounded subprocess.

    Ordering is by evidential value: the headline learner micro first (a
    later wedge can no longer zero it), then the system fabric, then the
    actor plane.  The parent composes the same one-line JSON as the
    in-process path and never initializes a backend itself."""
    ok, reason = _device_probe()
    if not ok:
        _print_unreachable_artifact(reason)
        sys.exit(1)

    system_knobs = dict(FLAGSHIP_SYSTEM_KNOBS)
    ig_knobs = dict(FLAGSHIP_SYSTEM_KNOBS, in_graph_per=True)
    # compile slack + 1 s/step: a deliberately long `bench.py 20000` run
    # must not be misreported as a wedge
    micro, m_err = _run_phase("micro", 900.0 + (steps + warmup) * 1.0,
                              ("--steps", steps, "--warmup", warmup))
    # the same micro cell through the fused double unroll (one
    # double-batch online+target pass): the feature's measured value,
    # reported side by side with the two-unroll headline
    micro_fused, mf_err = _run_phase(
        "micro", 900.0 + (steps + warmup) * 1.0,
        ("--steps", steps, "--warmup", warmup, "--fused"),
        label="micro_fused")
    system, s_err = _run_phase(
        "system", system_seconds + 900.0,
        ("--seconds", system_seconds, "--knobs", json.dumps(system_knobs)))
    # the same cell on the device-PER drivetrain (in_graph_per): zero
    # host round trips on the training path — reported side by side
    system_ig, ig_err = _run_phase(
        "system", system_seconds + 900.0,
        ("--seconds", system_seconds, "--knobs", json.dumps(ig_knobs)),
        label="system_ingraph")
    actor, a_err = _run_phase("actor", 600.0)

    result = {
        "metric": "learner_env_frames_per_sec",
        "value": round(micro["learner_fps"], 1) if micro else -1.0,
        "unit": "frames/s",
        "vs_baseline": (round(micro["learner_fps"] / NORTH_STAR_FPS, 3)
                        if micro else -1.0),
        "system_env_frames_per_sec": (round(system["system_fps"], 1)
                                      if system else -1.0),
        "system_vs_baseline": (round(system["system_fps"] / NORTH_STAR_FPS,
                                     3) if system else -1.0),
        "system_knobs": system_knobs,
        "system_ingraph_env_frames_per_sec": (
            round(system_ig["system_fps"], 1) if system_ig else -1.0),
        "learner_fused_env_frames_per_sec": (
            round(micro_fused["learner_fps"], 1) if micro_fused else -1.0),
        "actor_env_frames_per_sec": (round(actor["actor_fps"], 1)
                                     if actor else -1.0),
        "host_cpus": os.cpu_count() or 0,
    }
    errors = {k: v for k, v in (("micro", m_err), ("system", s_err),
                                ("micro_fused", mf_err),
                                ("system_ingraph", ig_err),
                                ("actor", a_err)) if v}
    if errors:
        result["phase_errors"] = errors
    if micro and micro.get("flops", 0) > 0:
        achieved = micro["flops"] * micro["steps_per_sec"] / 1e12
        result["achieved_tflops"] = round(achieved, 2)
        peak = _peak_tflops(micro.get("device_kind", ""))
        if peak > 0:
            result["mfu"] = round(achieved / peak, 4)
    print(json.dumps(result))
    if micro:
        print(f"# platform={micro.get('platform')} "
              f"kind={micro.get('device_kind')} "
              f"learner_steps/s={micro['steps_per_sec']:.2f} "
              f"flops/step={micro['flops']:.3e} "
              f"system_updates={system['updates'] if system else -1} "
              "busiest_spans_total_ms="
              f"{json.dumps(system['top_spans'] if system else {})}",
              file=sys.stderr)
    if not micro:
        sys.exit(1)


def _print_unreachable_artifact(reason: str) -> None:
    artifact = {
        "metric": "learner_env_frames_per_sec",
        "value": -1.0, "unit": "frames/s", "vs_baseline": -1.0,
        "error": f"accelerator backend unreachable ({reason})",
    }
    # attach the CURRENT probe run's history (tools/probe_then_measure
    # writes one JSON line per bounded probe attempt) so an outage
    # artifact also documents how long the backend has been down.  The
    # status file is append-only across runs; attempt numbering
    # restarts at 1 per run, so slice from the last attempt==1.
    try:
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(here, "tools", "probe_status.jsonl")) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        attempts = [e for e in lines if "attempt" in e]
        starts = [i for i, e in enumerate(attempts)
                  if e.get("attempt") == 1]
        if starts:
            attempts = attempts[starts[-1]:]
        if attempts:
            artifact["probe_attempts"] = len(attempts)
            artifact["probed_from_to"] = (attempts[0].get("t"),
                                          attempts[-1].get("t"))
            artifact["any_probe_succeeded"] = any(e.get("ok")
                                                  for e in attempts)
    except Exception:
        pass
    print(json.dumps(artifact))


def main(steps: int = 100, warmup: int = 5,
         system_seconds: float = 75.0) -> None:
    import traceback

    ok, reason = _device_probe()
    if not ok:
        _print_unreachable_artifact(reason)
        sys.exit(1)

    from r2d2_tpu.utils.compile_cache import enable as enable_compile_cache

    enable_compile_cache()  # repeat bench runs skip the multi-second compiles

    import jax

    dev = jax.devices()[0]

    # The learner number is the headline metric — it must survive a crash
    # in the (larger-machinery) actor/system phases, so those report -1 on
    # failure instead of taking the whole artifact down.
    learner_fps, steps_per_sec, flops = _learner_micro_bench(steps, warmup)
    try:
        fused_fps, _, _ = _learner_micro_bench(steps, warmup, fused=True)
    except Exception:
        traceback.print_exc()
        fused_fps = -1.0
    try:
        actor_fps = _actor_plane_bench()
    except Exception:
        traceback.print_exc()
        actor_fps = -1.0
    system_knobs = dict(FLAGSHIP_SYSTEM_KNOBS)
    try:
        system_fps, top_spans, sys_updates = _system_bench(system_seconds,
                                                           **system_knobs)
    except Exception:
        traceback.print_exc()
        system_fps, top_spans, sys_updates = -1.0, {}, 0
    # same cell on the device-PER drivetrain — schema parity with the
    # script-mode (phase-isolated) artifact
    try:
        system_ig_fps, _, _ = _system_bench(
            system_seconds, **dict(FLAGSHIP_SYSTEM_KNOBS,
                                   in_graph_per=True))
    except Exception:
        traceback.print_exc()
        system_ig_fps = -1.0

    result = {
        "metric": "learner_env_frames_per_sec",
        "value": round(learner_fps, 1),
        "unit": "frames/s",
        "vs_baseline": round(learner_fps / NORTH_STAR_FPS, 3),
        "system_env_frames_per_sec": round(system_fps, 1),
        "system_vs_baseline": round(system_fps / NORTH_STAR_FPS, 3),
        # the exact fabric knobs behind the system number (the learning
        # presets' cell — CURVES_AB_PIPELINE_r04's k=4 choice), so the
        # artifact documents what was measured
        "system_knobs": system_knobs,
        "system_ingraph_env_frames_per_sec": round(system_ig_fps, 1),
        "learner_fused_env_frames_per_sec": round(fused_fps, 1),
        "actor_env_frames_per_sec": round(actor_fps, 1),
        # the actor/system planes are host-CPU-bound work: their numbers
        # only compare across machines with this context attached
        "host_cpus": os.cpu_count() or 0,
    }
    if flops > 0:
        achieved = flops * steps_per_sec / 1e12
        result["achieved_tflops"] = round(achieved, 2)
        peak = _peak_tflops(getattr(dev, "device_kind", ""))
        if peak > 0:
            result["mfu"] = round(achieved / peak, 4)
    print(json.dumps(result))
    print(f"# platform={dev.platform} kind={getattr(dev, 'device_kind', '?')} "
          f"learner_steps/s={steps_per_sec:.2f} flops/step={flops:.3e} "
          f"system_updates={sys_updates} "
          f"busiest_spans_total_ms={json.dumps(top_spans)}",
          file=sys.stderr)


def _script_main(argv) -> int:
    """Shared script entry for `python bench.py`, `python -m r2d2_tpu.bench`,
    and `r2d2 bench` — one place for the phase dispatch and the default
    steps/warmup/system_seconds, so every entry measures the same thing."""
    if "--phase" in argv:
        return _phase_main(argv)
    _main_isolated(steps=int(argv[0]) if argv else 100,
                   warmup=5, system_seconds=75.0)
    return 0


if __name__ == "__main__":
    sys.exit(_script_main(sys.argv[1:]))
