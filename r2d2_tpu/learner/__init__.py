from r2d2_tpu.learner.step import (
    TrainState,
    create_train_state,
    make_optimizer,
    make_train_step,
    loss_and_priorities,
    value_rescale,
    inverse_value_rescale,
)
