"""Anakin-mode fused on-device training loop (``actor_transport="anakin"``).

The Podracer architectures paper (PAPERS.md) observes that when the
environment itself is jittable, the actor/replay/learner split collapses:
env-step → act → block-cut → replay-write → train-step become ONE compiled
program, and the host's only jobs are dispatching it and reading a few
scalars back.  This module is that program for the R2D2 stack:

- the env is a pure-JAX four-method env (``cfg.anakin_env`` →
  :func:`~r2d2_tpu.envs.anakin.make_anakin_env`: the vmapped
  FakeAtariEnv twin or the gridworld — any env on that surface inherits
  this whole fast path);
- the actor is an in-graph twin of :class:`~r2d2_tpu.actor.VectorActor`'s
  hot loop — per-lane ladder epsilons, LSTM carry, deferred block-boundary
  cuts with bootstrap Q, episode lifecycle — over a device-resident twin
  of :class:`~r2d2_tpu.replay.block.VectorLocalBuffer`;
- block cutting reproduces :func:`~r2d2_tpu.replay.block.assemble_block`'s
  math (window sizes, stored-hidden selection, n-step targets, actor-side
  initial priorities) as masked static-shape jnp ops, and writes finished
  blocks straight into the existing device ring
  (:class:`~r2d2_tpu.replay.device_ring.DeviceRing` arrays + its
  ``in_graph_per`` leaf/metadata state) via donated masked scatters — a
  lane that did not cut this step scatters to the out-of-bounds sentinel
  slot and is dropped (``mode="drop"``), so the write is one fixed-shape
  op regardless of how many lanes cut;
- training is the unchanged :func:`~r2d2_tpu.learner.step.make_train_step`
  fed by the unchanged in-graph PER sampler
  (:func:`~r2d2_tpu.learner.step._in_graph_sample` + ``gather_batch``).

Each dispatch of the fused super-step runs ``k × (E env/actor steps + 1
optimizer step)`` under ``jax.lax.scan`` (E =
``cfg.anakin_env_steps_per_update``), crossing the host boundary exactly
twice: one uint32 dispatch counter up, one small flat float vector
(k losses + counter deltas, then the eval pair / learnhealth rows when
armed) down.  Both crossings are ticked on
``HOST_TRANSFERS`` and the e2e tests pin them to a constant per dispatch,
independent of lane count, batch size and k — the "zero host crossings"
acceptance gate of ROADMAP open item 2.

Numerical parity with the host block cutter (pinned by
tests/test_anakin.py): integer fields, observation bytes, gamma tails
(host-precomputed float32 power tables, so XLA's ``pow`` never enters)
and stored hiddens are bit-exact vs :class:`LocalBuffer`; n-step returns
and priorities match to float32 round-off (the host accumulates those in
float64, which CPU-jax cannot reproduce without x64 mode — the divergence
is ≤ a few f32 ulps and covered by tolerance assertions).

Unlike the host ring writer, block slots keep whatever bytes the lane's
stream buffer held past the used window instead of zero-padding: the
sampling clamp invariant (replay_buffer.py) already guarantees those
positions are loss-masked, and skipping the zero-fill keeps the write a
pure scatter.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from r2d2_tpu.config import Config
from r2d2_tpu.envs.anakin import make_anakin_env
from r2d2_tpu.learner.step import (
    TrainState,
    _in_graph_sample,
    _loss_net,
    make_train_step,
)
from r2d2_tpu.models.network import R2D2Network
from r2d2_tpu.replay.device_ring import gather_batch
from r2d2_tpu.utils.math import epsilon_ladder
from r2d2_tpu.utils.resilience import Deadline
from r2d2_tpu.utils.trace import HOST_TRANSFERS, RETRACES, TRANSFER_GUARD

log = logging.getLogger(__name__)

# host-facing stats appended to the losses in the per-dispatch result
# vector, in this order (all float32; the deltas are per-dispatch)
STATS_FIELDS = ("env_steps", "fill", "episodes", "reward_sum", "blocks")

# in-graph greedy eval lane fields, appended after STATS_FIELDS when
# cfg.anakin_eval_interval > 0 (zeros on off-cadence dispatches)
EVAL_FIELDS = ("eval_episodes", "eval_return_sum")


def _mesh_hooks(table):
    """The fused program's layout-invariance hooks over one table:
    ``rep`` pins a value replicated (threefry draws, the stratified
    draw's cumsum input — the PR 8 pins extended to the fused program),
    ``rows`` pins sampled batch rows to dp (so the gather and the
    forward/backward shard exactly as the pjit drivetrains')."""
    rep_sh = table.replicated()
    dp_sh = NamedSharding(table.mesh, P("dp"))

    def rep(x):
        return jax.lax.with_sharding_constraint(x, rep_sh)

    def rows(x):
        return jax.lax.with_sharding_constraint(x, dp_sh)

    return rep, rows


def _make_eval_lane(cfg: Config, net: R2D2Network, env: Any,
                    action_dim: int):
    """The in-graph greedy eval lane: every ``cfg.anakin_eval_interval``
    dispatches (``lax.cond``-gated — off-cadence dispatches pay a zeros
    fill, not the rollout), run ONE truncation-length episode per lane
    with epsilon = 0 from a fresh env state (stream: a distinct
    ``fold_in`` derivation over the dispatch index, so eval episodes are
    reproducible and never perturb the training streams), and return
    ``(2,)`` f32 ``[episodes, return_sum]`` riding the existing
    per-dispatch result vector — anakin learning curves without a host
    env.  Greedy argmax + per-lane env draws are elementwise in the lane
    axis, so the lane needs no extra layout pins."""
    N, A = cfg.num_actors, action_dim
    layers, H = cfg.lstm_layers, cfg.hidden_dim
    act_net = _loss_net(cfg, net)
    interval = cfg.anakin_eval_interval
    steps = cfg.anakin_episode_len

    def eval_rollout(params, dispatch_idx):
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 0x45564C),
            dispatch_idx)
        est = env.init_state(key)
        carry0 = (est, env.observe(est),
                  jnp.zeros((N, A), jnp.float32), jnp.zeros(N, jnp.float32),
                  jnp.zeros((N, 2, layers, H), jnp.float32),
                  jnp.zeros(N, jnp.float32), jnp.zeros(N, bool))

        def estep(c, _):
            est, obs, la, lr, hidden, ret, done = c
            q, h2 = act_net.apply(params, obs, la, lr, hidden,
                                  method=R2D2Network.act)
            a = jnp.argmax(q, axis=1).astype(jnp.int32)
            est2, reward, trunc = env.step(est, a)
            # the truncating step's reward still counts (it ends the
            # episode); anything after a lane's done flag does not
            ret = ret + jnp.where(done, 0.0, reward)
            done = done | trunc
            one_hot = jnp.zeros((N, A), jnp.float32).at[
                jnp.arange(N), a].set(1.0)
            return (est2, env.observe(est2), one_hot, reward, h2, ret,
                    done), None

        carry, _ = jax.lax.scan(estep, carry0, None, length=steps)
        ret, done = carry[5], carry[6]
        return jnp.stack([done.sum().astype(jnp.float32), ret.sum()])

    def eval_lane(params, dispatch_idx):
        do = (dispatch_idx % jnp.uint32(interval)) == 0
        return jax.lax.cond(do,
                            lambda _: eval_rollout(params, dispatch_idx),
                            lambda _: jnp.zeros(2, jnp.float32), 0)

    return eval_lane


def _gamma_tables(cfg: Config):
    """Host-precomputed float32 discount constants, bit-identical to
    ``utils.math.n_step_gamma_tail``'s values: ``tail[e]`` is numpy's
    float32 ``gamma ** e`` (the tail entries), ``interior`` is the python
    ``gamma ** n`` cast to f32 (the interior fill), ``kernel[i]`` is the
    f32-rounded f64 ``gamma ** i`` for the n-step return sum."""
    n, g = cfg.forward_steps, cfg.gamma
    tail = g ** np.arange(0, n + 1, dtype=np.float32)
    interior = np.float32(g ** n)
    kernel = (g ** np.arange(0, n, dtype=np.float64)).astype(np.float32)
    return jnp.asarray(tail), jnp.asarray(interior), kernel


def _make_assemble(cfg: Config, action_dim: int, done: bool):
    """Single-lane block assembly (vmapped by the emitter): the jnp twin
    of :func:`replay.block.assemble_block` over the lane's preallocated
    stream/window buffers, with every per-sequence quantity computed at
    the static maximum K and masked past ``num_sequences``.

    ``done`` is static — the two call sites are statically terminal
    (episode-end cuts) or statically bootstrapped (boundary cuts), exactly
    like the host actor's two ``finish`` calls."""
    BL, L, n = cfg.block_length, cfg.learning_steps, cfg.forward_steps
    K, cap = cfg.seqs_per_block, cfg.max_block_steps
    burn_max = cfg.burn_in_steps
    seq_start_mode = cfg.stored_hidden_mode == "seq_start"
    tail_tbl, interior, kernel = _gamma_tables(cfg)

    def assemble(bufs: Dict[str, jnp.ndarray], prefix, size, last_q):
        s, c = size, prefix
        boot = (jnp.zeros(action_dim, jnp.float32) if done else last_q)
        qv = jax.lax.dynamic_update_index_in_dim(bufs["qval"], boot, s, 0)

        t = jnp.arange(BL, dtype=jnp.int32)
        tmask = t < s
        r = jnp.where(tmask, bufs["reward"], 0.0)

        # n-step returns: sum_{i<n} gamma^i * r[t+i] (utils.math
        # n_step_return; f32 here vs the host's f64 accumulate — ulp-level)
        r_ext = jnp.concatenate([r, jnp.zeros(n - 1, jnp.float32)]) \
            if n > 1 else r
        nstep = jnp.zeros(BL, jnp.float32)
        for i in range(n):  # static unroll, n is small (<= ~5)
            nstep = nstep + kernel[i] * jax.lax.slice_in_dim(r_ext, i, i + BL)

        # bootstrap discount tail (utils.math n_step_gamma_tail, exact:
        # table lookups of the host's own f32 values)
        steps_left = s - t                     # >= 1 wherever tmask
        e = jnp.clip(steps_left, 0, n)
        tail_val = (jnp.float32(0.0) if done else tail_tbl[e])
        gtail = jnp.where(steps_left > n, interior, tail_val)
        gtail = jnp.where(tmask, gtail, 0.0)

        # per-sequence windows (worker.py:471-474 invariants)
        seq = jnp.arange(K, dtype=jnp.int32)
        num_seq = (s + L - 1) // L
        valid = seq < num_seq
        burn = jnp.where(valid, jnp.minimum(seq * L + c, burn_max), 0)
        learn = jnp.where(valid, jnp.minimum(L, s - seq * L), 0)
        fwd = jnp.where(valid,
                        jnp.minimum(n, s + 1 - jnp.cumsum(learn)), 0)

        # stored recurrent state at each sequence's burn-in start (or the
        # reference's seq-start indexing under the compat switch)
        hidx = seq * L if seq_start_mode else c + seq * L - burn
        hidx = jnp.clip(hidx, 0, cap - 1)
        hiddens = jnp.where(valid[:, None, None, None],
                            bufs["hidden"][hidx], 0.0)

        # actor-side initial priorities (block.py:104-110: plain max-Q
        # n-step TD, replicating the reference's asymmetry vs the learner)
        qmax = qv.max(axis=1)                                  # (BL+1,)
        mf = jnp.minimum(s, n)
        maxq_t = qmax[jnp.minimum(t + mf, s)]
        q_taken = qv[t, bufs["action"].astype(jnp.int32)]
        td = jnp.abs(nstep + gtail * maxq_t - q_taken)
        td = jnp.where(tmask, td, 0.0)
        td2 = td.reshape(K, L)
        lmask = jnp.arange(L)[None, :] < learn[:, None]
        seg_max = jnp.where(lmask, td2, 0.0).max(axis=1)
        seg_mean = jnp.where(lmask, td2, 0.0).sum(axis=1) \
            / jnp.maximum(learn, 1)
        prios = jnp.where(valid, 0.9 * seg_max + 0.1 * seg_mean, 0.0)

        return dict(
            slot=dict(obs=bufs["obs"], last_action=bufs["last_action"],
                      last_reward=bufs["last_reward"],
                      action=bufs["action"], n_step_reward=nstep,
                      n_step_gamma=gtail, hidden=hiddens),
            priorities=prios,
            meta=jnp.stack([burn, learn, fwd], axis=1).astype(jnp.int32),
            first_burn=burn[0].astype(jnp.int32),
            learning_total=learn.sum().astype(jnp.int32),
        )

    return assemble


def _make_emit(cfg: Config, action_dim: int, done: bool):
    """Batched cut-and-write: assemble every lane's candidate block, then
    scatter the ``cut`` lanes' blocks into ring slots ``ptr..`` (logical
    FIFO order preserved: cut lanes take consecutive slots in lane order,
    exactly the order the host actor's per-lane sink calls would land).
    Non-cut lanes scatter to the sentinel slot ``num_blocks`` and are
    dropped, so the write is one fixed-shape donated update."""
    NB, K = cfg.num_blocks, cfg.seqs_per_block
    alpha = cfg.prio_exponent
    assemble = jax.vmap(_make_assemble(cfg, action_dim, done))

    def emit(ast, arrays, prios, seq_meta, first, cut, last_q):
        bufs = dict(obs=ast["buf_obs"], last_action=ast["buf_last_action"],
                    last_reward=ast["buf_last_reward"],
                    hidden=ast["buf_hidden"], action=ast["buf_action"],
                    reward=ast["buf_reward"], qval=ast["buf_qval"])
        blocks = assemble(bufs, ast["prefix"], ast["size"], last_q)

        cut_i = cut.astype(jnp.int32)
        offs = jnp.cumsum(cut_i) - cut_i              # rank among cut lanes
        slot = jnp.where(cut, (ast["ptr"] + offs) % NB, NB)   # NB = dropped

        arrays = {k: arrays[k].at[slot].set(blocks["slot"][k], mode="drop")
                  for k in arrays}
        leaf = (slot * K)[:, None] + jnp.arange(K)[None, :]
        prios = prios.at[leaf.reshape(-1)].set(
            (blocks["priorities"] ** alpha).reshape(-1), mode="drop")
        seq_meta = seq_meta.at[slot].set(blocks["meta"], mode="drop")
        first = first.at[slot].set(blocks["first_burn"], mode="drop")

        # fill accounting mirrors ReplayBuffer.add: subtract the
        # overwritten slot's learning total, add the new one
        slot_safe = jnp.minimum(slot, NB - 1)
        old_tot = jnp.where(cut, ast["block_learning_total"][slot_safe], 0)
        new_tot = jnp.where(cut, blocks["learning_total"], 0)
        blt = ast["block_learning_total"].at[slot].set(
            blocks["learning_total"], mode="drop")
        ast = {**ast,
               "ptr": (ast["ptr"] + cut_i.sum()) % NB,
               "block_learning_total": blt,
               "fill": ast["fill"] + (new_tot - old_tot).sum(),
               "env_steps_d": ast["env_steps_d"] + new_tot.sum(),
               "blocks_d": ast["blocks_d"] + cut_i.sum()}
        return ast, arrays, prios, seq_meta, first

    return emit


def _make_actor_step(cfg: Config, net: R2D2Network, env: Any,
                     action_dim: int, cut_cond: bool = True,
                     replicate=None):
    """One fused env/actor step for the whole fleet — the jnp twin of one
    ``VectorActor.run`` iteration, same sub-step order (boundary cuts with
    this step's bootstrap Q first, then act/step/record, then episode-end
    cuts and lane resets).  Returns ``(carry', trace)``; the production
    scan discards ``trace`` (XLA dead-code-eliminates it), the parity
    tests keep it to drive the host LocalBuffer oracle.

    ``cut_cond`` (default on) wraps each emit/retention block in a
    ``lax.cond`` on ``jnp.any(cut)``: on the (block_length-1)/block_length
    majority of steps where NO lane cuts, the full-buffer block assembly,
    retention gathers, and ring scatters are skipped entirely instead of
    executing as all-masked no-ops.  Bit-exact by construction — a no-cut
    emit writes only to the dropped sentinel slot and a no-cut retention
    is the identity — and pinned vs the ``cut_cond=False`` path in
    tests/test_anakin.py.

    ``replicate`` (mesh mode) pins the fleet-wide exploration draws to a
    replicated layout: with non-partitionable threefry, GSPMD
    back-propagating a dp sharding onto a counter-based ``(N,)`` draw
    changes the generated BITS (the PR 8 finding on the stratified
    draw's uniforms), so without the pin a dp=2 run would explore
    differently than dp=1.  Per-lane vmapped draws (the env's reset
    streams) are elementwise in the lane axis and need no pin."""
    N, A, BL = cfg.num_actors, action_dim, cfg.block_length
    cap = cfg.max_block_steps
    eps = jnp.asarray([epsilon_ladder(i, cfg.num_actors, cfg.base_eps,
                                      cfg.eps_alpha)
                       for i in range(cfg.num_actors)], jnp.float32)
    act_net = _loss_net(cfg, net)  # the scan recurrence, grad-safe twin
    emit_boundary = _make_emit(cfg, action_dim, done=False)
    emit_done = _make_emit(cfg, action_dim, done=True)
    env_keys = tuple(env.STATE_KEYS)
    lanes = jnp.arange(N)

    def actor_step(params, ast, arrays, prios, seq_meta, first):
        q, new_hidden = act_net.apply(
            params, ast["obs"], ast["last_action"], ast["last_reward"],
            ast["hidden"], method=R2D2Network.act)

        # 1) deferred block-boundary cuts: this step's Q at the new state
        #    is the bootstrap (worker.py:550-554 semantics, no 2nd forward)
        pend = ast["finish_pending"]

        def _boundary(ops):
            a, arr, p, sm, fb = ops
            a, arr, p, sm, fb = emit_boundary(a, arr, p, sm, fb, pend, q)
            return _retain_prefix(cfg, a, pend), arr, p, sm, fb

        if cut_cond:
            ast, arrays, prios, seq_meta, first = jax.lax.cond(
                jnp.any(pend), _boundary, lambda ops: ops,
                (ast, arrays, prios, seq_meta, first))
        else:
            ast, arrays, prios, seq_meta, first = _boundary(
                (ast, arrays, prios, seq_meta, first))
        ast = {**ast, "finish_pending": jnp.zeros(N, bool)}

        # 2) ladder-epsilon exploration
        key, k1, k2 = jax.random.split(ast["act_key"], 3)
        u = jax.random.uniform(k1, (N,))
        rand_a = jax.random.randint(k2, (N,), 0, A, dtype=jnp.int32)
        if replicate is not None:
            # layout-invariance pin: see the factory docstring
            u, rand_a = replicate(u), replicate(rand_a)
        explore = u < eps
        actions = jnp.where(explore, rand_a,
                            jnp.argmax(q, axis=1).astype(jnp.int32))

        # 3) env step (no auto-reset: the post-step obs is recorded first)
        env_state = {k: ast["env_" + k] for k in env_keys}
        env_state, reward, truncated = env.step(env_state, actions)
        obs_step = env.observe(env_state)

        # 4) batched bookkeeping + local-buffer add (VectorLocalBuffer
        #    .add_batch, one scatter per field)
        one_hot = jnp.zeros((N, A), bool).at[lanes, actions].set(True)
        p = ast["prefix"] + ast["size"] + 1
        s = ast["size"]
        ast = {**ast,
               "buf_obs": ast["buf_obs"].at[lanes, p].set(obs_step),
               "buf_last_action":
                   ast["buf_last_action"].at[lanes, p].set(one_hot),
               "buf_last_reward":
                   ast["buf_last_reward"].at[lanes, p].set(reward),
               "buf_hidden": ast["buf_hidden"].at[lanes, p].set(new_hidden),
               "buf_action":
                   ast["buf_action"].at[lanes, s].set(
                       actions.astype(jnp.uint8)),
               "buf_reward": ast["buf_reward"].at[lanes, s].set(reward),
               "buf_qval": ast["buf_qval"].at[lanes, s].set(q),
               "obs": obs_step,
               "last_action": one_hot.astype(jnp.float32),
               "last_reward": reward,
               "hidden": new_hidden,
               "size": s + 1,
               "sum_reward": ast["sum_reward"] + reward,
               "episode_steps": ast["episode_steps"] + 1,
               "act_key": key,
               **{f"env_{k}": env_state[k] for k in env_keys}}

        # 5) episode-end cuts (terminal: zero bootstrap); same cond fast
        #    path — episode ends are rarer still than block boundaries
        def _done_cut(ops):
            return emit_done(*ops, truncated, jnp.zeros((N, A), jnp.float32))

        if cut_cond:
            ast, arrays, prios, seq_meta, first = jax.lax.cond(
                jnp.any(truncated), _done_cut, lambda ops: ops,
                (ast, arrays, prios, seq_meta, first))
        else:
            ast, arrays, prios, seq_meta, first = _done_cut(
                (ast, arrays, prios, seq_meta, first))

        # 6) episode accounting, env reset, lane reset (VectorActor
        #    ._reset_lane: fresh obs, zero agent state, vbuf.reset_lane)
        ast = {**ast,
               "episodes_d": ast["episodes_d"] + truncated.sum(),
               "reward_d": ast["reward_d"]
               + jnp.where(truncated, ast["sum_reward"], 0.0).sum()}
        env_state = env.reset_lanes(env_state, truncated)
        obs_reset = env.observe(env_state)
        tr = truncated
        trc = tr[:, None]
        obs_next = jnp.where(tr.reshape((N,) + (1,) * (obs_step.ndim - 1)),
                             obs_reset, obs_step)
        noop = jnp.zeros((N, A), bool).at[:, 0].set(True)
        ast = {**ast,
               "obs": obs_next,
               "last_action": jnp.where(trc, 0.0, ast["last_action"]),
               "last_reward": jnp.where(tr, 0.0, ast["last_reward"]),
               "hidden": jnp.where(tr[:, None, None, None], 0.0,
                                   ast["hidden"]),
               "episode_steps": jnp.where(tr, 0, ast["episode_steps"]),
               "sum_reward": jnp.where(tr, 0.0, ast["sum_reward"]),
               "prefix": jnp.where(tr, 0, ast["prefix"]),
               "size": jnp.where(tr, 0, ast["size"]),
               "buf_obs": ast["buf_obs"].at[:, 0].set(
                   jnp.where(tr.reshape((N,) + (1,) * (obs_step.ndim - 1)),
                             obs_reset, ast["buf_obs"][:, 0])),
               "buf_last_action": ast["buf_last_action"].at[:, 0].set(
                   jnp.where(trc, noop, ast["buf_last_action"][:, 0])),
               "buf_last_reward": ast["buf_last_reward"].at[:, 0].set(
                   jnp.where(tr, 0.0, ast["buf_last_reward"][:, 0])),
               "buf_hidden": ast["buf_hidden"].at[:, 0].set(
                   jnp.where(tr[:, None, None, None], 0.0,
                             ast["buf_hidden"][:, 0])),
               **{f"env_{k}": env_state[k] for k in env_keys}}

        # 7) deferred boundary cut next step (worker.py block-cut rule)
        ast = {**ast,
               "finish_pending": (ast["size"] == BL) & ~tr
               & (ast["episode_steps"] < cfg.max_episode_steps)}

        trace = dict(pending=pend, q=q, hidden=new_hidden, actions=actions,
                     reward=reward, truncated=tr, obs_step=obs_step,
                     obs_next=obs_next)
        return (ast, arrays, prios, seq_meta, first), trace

    return actor_step


def _retain_prefix(cfg: Config, ast: dict, cut: jnp.ndarray) -> dict:
    """Post-boundary-cut retention: keep the trailing ``burn_in + 1``
    stream entries in place as the next block's warm prefix
    (VectorLocalBuffer.finish), realised as a per-lane index-shift gather
    applied only to cut lanes."""
    cap = cfg.max_block_steps
    N = ast["size"].shape[0]
    entries = ast["prefix"] + ast["size"] + 1
    keep = jnp.minimum(cfg.burn_in_steps + 1, entries)
    lo = entries - keep
    j = jnp.arange(cap, dtype=jnp.int32)
    src = jnp.where(j[None, :] < keep[:, None], j[None, :] + lo[:, None],
                    j[None, :])                                 # (N, cap)
    rows = jnp.arange(N)[:, None]

    def shift(name):
        arr = ast[name]
        shifted = arr[rows, src]
        return jnp.where(cut.reshape((N, 1) + (1,) * (arr.ndim - 2)),
                         shifted, arr)

    return {**ast,
            "buf_obs": shift("buf_obs"),
            "buf_last_action": shift("buf_last_action"),
            "buf_last_reward": shift("buf_last_reward"),
            "buf_hidden": shift("buf_hidden"),
            "prefix": jnp.where(cut, keep - 1, ast["prefix"]),
            "size": jnp.where(cut, 0, ast["size"])}


def _zero_deltas(ast: dict) -> dict:
    """Per-dispatch counters start at zero inside the program, so the
    returned values ARE the dispatch's deltas — the host accumulates them
    in Python ints (no on-device counter can wrap)."""
    return {**ast,
            "env_steps_d": jnp.zeros((), jnp.int32),
            "episodes_d": jnp.zeros((), jnp.int32),
            "reward_d": jnp.zeros((), jnp.float32),
            "blocks_d": jnp.zeros((), jnp.int32)}


def _stats_vec(ast: dict) -> jnp.ndarray:
    """(5,) float32, ordered as :data:`STATS_FIELDS`."""
    return jnp.stack([
        ast["env_steps_d"].astype(jnp.float32),
        ast["fill"].astype(jnp.float32),
        ast["episodes_d"].astype(jnp.float32),
        ast["reward_d"],
        ast["blocks_d"].astype(jnp.float32)])


def make_anakin_state(cfg: Config, action_dim: int, env: Any,
                      key: jax.Array) -> dict:
    """The fused loop's full device-resident carry (host-built, one
    device_put): env state (whatever pytree ``env.STATE_KEYS`` names),
    batched agent state, the VectorLocalBuffer twin, ring
    pointer/accounting, and the exploration RNG."""
    N, A, BL = cfg.num_actors, action_dim, cfg.block_length
    cap = cfg.max_block_steps
    obs_shape = cfg.stored_obs_shape
    layers, H = cfg.lstm_layers, cfg.hidden_dim

    env_key, act_key = jax.random.split(key)
    env_state = env.init_state(env_key)
    obs0 = env.observe(env_state)

    buf_la = np.zeros((N, cap, A), bool)
    buf_la[:, 0, 0] = True                    # noop one-hot at stream start
    ast = dict(
        **{f"env_{k}": env_state[k] for k in env.STATE_KEYS},
        obs=obs0,
        last_action=jnp.zeros((N, A), jnp.float32),
        last_reward=jnp.zeros(N, jnp.float32),
        hidden=jnp.zeros((N, 2, layers, H), jnp.float32),
        buf_obs=jnp.zeros((N, cap, *obs_shape), jnp.uint8
                          ).at[:, 0].set(obs0),
        buf_last_action=jnp.asarray(buf_la),
        buf_last_reward=jnp.zeros((N, cap), jnp.float32),
        buf_hidden=jnp.zeros((N, cap, 2, layers, H), jnp.float32),
        buf_action=jnp.zeros((N, BL), jnp.uint8),
        buf_reward=jnp.zeros((N, BL), jnp.float32),
        buf_qval=jnp.zeros((N, BL + 1, A), jnp.float32),
        prefix=jnp.zeros(N, jnp.int32),
        size=jnp.zeros(N, jnp.int32),
        sum_reward=jnp.zeros(N, jnp.float32),
        episode_steps=jnp.zeros(N, jnp.int32),
        finish_pending=jnp.zeros(N, bool),
        act_key=act_key,
        ptr=jnp.zeros((), jnp.int32),
        block_learning_total=jnp.zeros(cfg.num_blocks, jnp.int32),
        fill=jnp.zeros((), jnp.int32),
    )
    return _zero_deltas(ast)


def _anakin_shardings(table, state_template, ast_template, layout: str):
    """(state, ast, ring, prios, seq_meta, first) sharding trees for the
    fused entry points — every piece resolved through the ONE sharding
    table (parallel/sharding.py): params/moments per the param-path
    patterns (fsdp/tp), lane state per ``anakin.lane.*`` (dp), ring/PER
    per ``ring.*``/``per.*`` under the ring layout."""
    per = table.per_shardings(layout)
    return (table.state_shardings(state_template),
            table.anakin_state_shardings(ast_template, layout),
            table.ring_shardings(layout),
            per["prios"], per["seq_meta"], per["first"])


def make_anakin_super_step(cfg: Config, net: R2D2Network,
                           env: Any, action_dim: int,
                           cut_cond: bool = True, table=None,
                           state_template=None, ast_template=None,
                           layout: str = "replicated"):
    """The fused program: ``k × (E env/actor steps + 1 train step)`` in one
    dispatch.  Signature::

        super_step(train_state, anakin_state, ring_arrays, prios,
                   seq_meta, first_burn, dispatch_idx u32)
          -> (train_state', anakin_state', ring_arrays', prios',
              seq_meta', first_burn', flat f32)

    All six state arguments are donated; ``flat`` is the per-inner-step
    losses followed by the :data:`STATS_FIELDS` deltas (then the
    :data:`EVAL_FIELDS` pair when ``cfg.anakin_eval_interval > 0``, then
    the learnhealth diagnostic rows when armed) — the dispatch's ONLY
    device→host payload at every mesh shape.  The sampling stream is
    ``fold_in(PRNGKey(cfg.seed), dispatch_idx)``, matching the
    ``in_graph_per`` drivetrain's scheme (learner/step.py).

    ``table`` (mesh mode) makes this THE one
    ``jax.jit(in_shardings=..., out_shardings=..., donate_argnums=...)``
    entry point over the dp × fsdp × tp mesh: lanes/carry/local buffers
    shard over dp, params/moments per the table's patterns, ring/PER per
    ``layout``; the stratified draw and the fleet-wide exploration
    draws are pinned replicated (the PR 8 cumsum/threefry pins), and
    sampled batch rows are pinned to dp so the train step shards exactly
    as the pjit drivetrains'.  ``table=None`` is the single-device path
    — the same program, default placement."""
    k, E = cfg.superstep_k, cfg.anakin_env_steps_per_update
    lh = getattr(cfg, "learnhealth_interval", 0) > 0
    rep = rows = None
    if table is not None:
        if state_template is None or ast_template is None:
            raise ValueError(
                "mesh-mode make_anakin_super_step needs state_template "
                "and ast_template to resolve the table shardings — "
                "compiling without them would silently bypass the layout")
        rep, rows = _mesh_hooks(table)
    step = make_train_step(cfg, net, learnhealth=lh)
    actor_step = _make_actor_step(cfg, net, env, action_dim,
                                  cut_cond=cut_cond, replicate=rep)
    eval_lane = (_make_eval_lane(cfg, net, env, action_dim)
                 if cfg.anakin_eval_interval > 0 else None)

    def super_step(train_state: TrainState, ast, arrays, prios, seq_meta,
                   first, dispatch_idx):
        ast = _zero_deltas(ast)
        keys = jax.random.split(
            jax.random.fold_in(jax.random.PRNGKey(cfg.seed), dispatch_idx),
            k)

        def update(carry, key_t):
            ts, ast, arrays, prios, seq_meta, first = carry

            def env_it(c, _):
                c2, _trace = actor_step(ts.params, *c)
                return c2, None

            (ast, arrays, prios, seq_meta, first), _ = jax.lax.scan(
                env_it, (ast, arrays, prios, seq_meta, first), None,
                length=E)
            # mesh mode: the draw reads a REPLICATED view of the leaves
            # and its uniforms are pinned replicated (learner/step.py's
            # in_graph_per rationale — associative_scan partitioning
            # changes final-ulp rounding, threefry partitioning changes
            # bits); the sampled rows then pin to dp so gather/forward
            # shard over the mesh
            p_draw = prios if rep is None else rep(prios)
            idx, w, ints = _in_graph_sample(cfg, key_t, p_draw, seq_meta,
                                            first, constrain_rep=rep)
            if rows is not None:
                ints, w = rows(ints), rows(w)
            batch = gather_batch(cfg, arrays, ints, w)
            if lh:
                ts, loss, new_p, diag = step(ts, batch)
            else:
                ts, loss, new_p = step(ts, batch)
            # same feedback exponentiation as the in_graph_per super-step
            prios = prios.at[idx].set(new_p ** cfg.prio_exponent)
            return ((ts, ast, arrays, prios, seq_meta, first),
                    ((loss, diag) if lh else loss))

        (train_state, ast, arrays, prios, seq_meta, first), ys = (
            jax.lax.scan(update, (train_state, ast, arrays, prios,
                                  seq_meta, first), keys))
        if lh:
            losses, diags = ys
        else:
            losses, diags = ys, None
        parts = [losses, _stats_vec(ast)]
        if eval_lane is not None:
            parts.append(eval_lane(train_state.params, dispatch_idx))
        if diags is not None:
            parts.append(diags.reshape(-1))
        flat = jnp.concatenate(parts)
        return train_state, ast, arrays, prios, seq_meta, first, flat

    wrapped = RETRACES.wrap("learner.anakin_super_step", super_step)
    if table is None:
        return jax.jit(wrapped, donate_argnums=(0, 1, 2, 3, 4, 5))
    from r2d2_tpu.parallel.sharding import (
        _check_batch,
        _silence_benign_donation_warning,
    )

    _silence_benign_donation_warning()
    _check_batch(cfg, table.mesh)
    sh = _anakin_shardings(table, state_template, ast_template, layout)
    return jax.jit(wrapped,
                   in_shardings=sh + (table.replicated(),),
                   out_shardings=sh + (table.replicated(),),
                   donate_argnums=(0, 1, 2, 3, 4, 5))


def make_anakin_rollout(cfg: Config, net: R2D2Network, env: Any,
                        action_dim: int, steps: int, table=None,
                        state_template=None, ast_template=None,
                        layout: str = "replicated"):
    """The warm-up program: ``steps`` fused env/actor steps with ring/PER
    writes but NO train step — dispatched until the in-graph fill counter
    reaches ``learning_starts``.  Params are read-only (not donated).
    ``table`` shards it exactly like :func:`make_anakin_super_step`."""
    rep = None
    if table is not None:
        rep, _ = _mesh_hooks(table)
    actor_step = _make_actor_step(cfg, net, env, action_dim, replicate=rep)

    def rollout(params, ast, arrays, prios, seq_meta, first):
        ast = _zero_deltas(ast)

        def env_it(c, _):
            c2, _trace = actor_step(params, *c)
            return c2, None

        (ast, arrays, prios, seq_meta, first), _ = jax.lax.scan(
            env_it, (ast, arrays, prios, seq_meta, first), None,
            length=steps)
        return ast, arrays, prios, seq_meta, first, _stats_vec(ast)

    wrapped = RETRACES.wrap("learner.anakin_rollout", rollout)
    if table is None:
        return jax.jit(wrapped, donate_argnums=(1, 2, 3, 4, 5))
    st_sh, ast_sh, ring_sh, pr_sh, sm_sh, fb_sh = _anakin_shardings(
        table, state_template, ast_template, layout)
    return jax.jit(wrapped,
                   in_shardings=(st_sh.params, ast_sh, ring_sh, pr_sh,
                                 sm_sh, fb_sh),
                   out_shardings=(ast_sh, ring_sh, pr_sh, sm_sh, fb_sh,
                                  table.replicated()),
                   donate_argnums=(1, 2, 3, 4, 5))


def make_debug_rollout(cfg: Config, net: R2D2Network, env: Any,
                       action_dim: int, steps: int, cut_cond: bool = True):
    """Parity-test harness: like :func:`make_anakin_rollout` but keeps the
    per-step trace (q, hidden, actions, rewards, cut masks, observations)
    so tests can replay the exact trajectory into the host LocalBuffer
    oracle.  ``cut_cond=False`` builds the pre-r9 always-emit variant for
    the fast-path bit-exactness pin.  Not retrace-guarded or donated —
    test-only."""
    actor_step = _make_actor_step(cfg, net, env, action_dim,
                                  cut_cond=cut_cond)

    def rollout(params, ast, arrays, prios, seq_meta, first):
        def env_it(c, _):
            return actor_step(params, *c)

        return jax.lax.scan(env_it, (ast, arrays, prios, seq_meta, first),
                            None, length=steps)

    return jax.jit(rollout)  # graftlint: disable=donation-discipline -- test-only parity harness: the host oracle replays the same inputs after the call, so nothing may be donated


# --------------------------------------------------------------------------
# host-side driver
# --------------------------------------------------------------------------

class AnakinPlane:
    """Owns the fused loop's device state and its dispatch/harvest cycle.

    The host's entire job: dispatch the compiled program, read back the
    small flat result vector, and keep Python-int mirrors of the
    counters (no on-device counter can overflow that way).  Every
    device→host crossing ticks ``HOST_TRANSFERS`` (``anakin.result_fetch``
    once per dispatch; ``anakin.snapshot_fetch`` per full-state snapshot)
    so the "host-free hot loop" claim is an assertable invariant.

    The ring handles live in the :class:`DeviceRing` passed in — the fused
    program donates them and the plane stores the returned generation back
    after every dispatch, so the ring object stays the single owner (same
    handle discipline as the ``in_graph_per`` drivetrain).

    ``table`` (a :class:`~r2d2_tpu.parallel.sharding.ShardingTable`, with
    ``state_template`` = the run's TrainState or its avals) makes the
    plane mesh-native: the carry/ring/PER state places per the table, the
    compiled programs are the sharded entry points, and the snapshot path
    stays LAYOUT-FREE (``write_state`` host-gathers, ``read_state``
    re-places under the CURRENT table — a dp=2 snapshot resumes on a
    dp=1 mesh and vice versa, the checkpoint-resharding contract).
    """

    def __init__(self, cfg: Config, net: R2D2Network, action_dim: int,
                 ring: Any, start_env_steps: int = 0, table=None,
                 state_template=None):
        if not getattr(cfg, "in_graph_per", False):
            raise ValueError("the anakin plane requires in_graph_per=True "
                             "(train._train_anakin flips it on)")
        if cfg.num_blocks < cfg.num_actors:
            raise ValueError(
                f"anakin needs num_blocks ({cfg.num_blocks}) >= num_actors "
                f"({cfg.num_actors}): every lane may cut a block in the "
                "same fused step and the masked scatter writes them to "
                "distinct slots")
        if cfg.anakin_episode_len > cfg.max_episode_steps:
            raise ValueError(
                f"anakin_episode_len ({cfg.anakin_episode_len}) must be "
                f"<= max_episode_steps ({cfg.max_episode_steps}): the "
                "fused loop relies on truncation firing before the "
                "episode-step cap (the cap path needs a second forward "
                "the fused program does not run)")
        self.cfg = cfg
        self.ring = ring
        self.action_dim = action_dim
        # learnhealth plane: with a nonzero cadence the fused program's
        # flat result vector carries the per-inner-step diagnostic rows;
        # train._train_anakin attaches the run's LearnHealthMonitor
        self._lh = getattr(cfg, "learnhealth_interval", 0) > 0
        self._eval = cfg.anakin_eval_interval > 0
        self.monitor = None
        self.table = table
        self._layout = getattr(ring, "layout", "replicated")
        self.env = make_anakin_env(cfg, action_dim)
        # double fold_in: the PER sampling stream is the SINGLE-fold
        # fold_in(PRNGKey(seed), dispatch_idx) over the full u32 range
        # (learner/step.py), so a single-fold plane root would collide
        # with one dispatch's stream — two folds is a distinct
        # derivation path for the env/exploration streams
        self.state = make_anakin_state(
            cfg, action_dim, self.env,
            jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 0x414B),
                1))
        self._ast_sh = self._ring_sh = self._per_sh = None
        if table is not None:
            # mesh mode: place the carry per the table and compile the
            # sharded entry points.  The lane axis falls back to
            # replication via the table's divisibility guard when
            # num_actors does not divide dp — semantics identical either
            # way, the layout is a pure perf choice.
            self._ast_sh = table.anakin_state_shardings(self.state,
                                                        self._layout)
            self._ring_sh = table.ring_shardings(self._layout)
            self._per_sh = table.per_shardings(self._layout)
            self.state = jax.device_put(self.state, self._ast_sh)
        self.super_step = make_anakin_super_step(
            cfg, net, self.env, action_dim, table=table,
            state_template=state_template, ast_template=self.state,
            layout=self._layout)
        self.roll_steps = cfg.superstep_k * cfg.anakin_env_steps_per_update
        self.rollout = make_anakin_rollout(
            cfg, net, self.env, action_dim, steps=self.roll_steps,
            table=table, state_template=state_template,
            ast_template=self.state, layout=self._layout)
        self._frames_per_dispatch = self.roll_steps * cfg.num_actors

        # host-int counter mirrors (absolute; deltas arrive per dispatch).
        # The lock covers them: the dispatch thread folds deltas in while
        # the log thread's stats() does a read-and-reset of the interval
        # accumulators — same contract (and remedy) as ReplayBuffer.stats
        self._stats_lock = threading.Lock()
        self.env_steps = int(start_env_steps)
        self.fill = 0
        self.frames = 0
        self.super_steps = 0
        self.blocks = 0
        self.episodes_total = 0
        self.reward_total = 0.0
        self.training_steps = 0
        self.dispatch_no = 0
        # in-graph greedy eval lane (cfg.anakin_eval_interval): totals
        # accumulate across resumes, last_eval_return is the most recent
        # dispatch's mean greedy return (the learning-curve gauge)
        self.eval_episodes_total = 0
        self.eval_return_total = 0.0
        self.last_eval_return = float("nan")
        # interval accumulators, reset by stats() (ReplayBuffer.stats
        # semantics so the log loop code is shared-shaped)
        self._interval_episodes = 0
        self._interval_reward = 0.0
        self._interval_loss = 0.0
        self._interval_eval_episodes = 0

    # ----------------------------------------------------------- dispatch
    def _handles(self):
        meta = self.ring.per_meta()
        return (self.ring.snapshot(), self.ring.take_prios(),
                meta["seq_meta"], meta["first"])

    def _store(self, arrays, prios, seq_meta, first) -> None:
        self.ring.arrays = arrays
        self.ring.put_prios(prios)
        self.ring.put_per_meta(seq_meta, first)

    def rollout_step(self, params) -> None:
        """One warm-up dispatch (env/actor/ring-write only), harvested
        synchronously — the fill counter gates the switch to training."""
        with TRANSFER_GUARD.disallow("anakin.rollout"):
            ast, arrays, prios, seq_meta, first, stats = self.rollout(
                params, self.state, *self._handles())
            self.state = ast
            self._store(arrays, prios, seq_meta, first)
            with self._stats_lock:
                self.frames += self._frames_per_dispatch
            with HOST_TRANSFERS.allowed("anakin.result_fetch"):
                stats_np = np.asarray(jax.device_get(stats))
        self._absorb(stats_np)

    def dispatch(self, train_state: TrainState):
        """One fused super-step dispatch.  Returns ``(train_state', flat)``
        with the result vector's D2H copy already started — harvest later
        (pipelined) via :meth:`harvest`."""
        with TRANSFER_GUARD.disallow("anakin.dispatch"):
            # the loop's ONE recurring H2D: the dispatch index scalar
            with HOST_TRANSFERS.allowed("anakin.dispatch_put"):
                idx = jnp.asarray(self.dispatch_no & 0xFFFFFFFF,
                                  jnp.uint32)
            self.dispatch_no += 1
            train_state, ast, arrays, prios, seq_meta, first, flat = (
                self.super_step(train_state, self.state,
                                *self._handles(), idx))
            self.state = ast
            self._store(arrays, prios, seq_meta, first)
            with self._stats_lock:
                self.frames += self._frames_per_dispatch
                self.super_steps += 1
            try:
                flat.copy_to_host_async()  # explicit: guard-exempt
            except Exception:
                pass  # no async copies on this backend: harvest pays it
        return train_state, flat

    def harvest(self, flat) -> np.ndarray:
        """Fetch one dispatch's result vector — the loop's ONLY recurring
        device→host crossing — and fold its deltas into the host
        counters.  Returns the k inner-step losses."""
        with TRANSFER_GUARD.disallow("anakin.harvest"):
            with HOST_TRANSFERS.allowed("anakin.result_fetch"):
                v = np.asarray(jax.device_get(flat))
        k = self.cfg.superstep_k
        losses = v[:k]
        stats = v[k:k + len(STATS_FIELDS)]
        off = k + len(STATS_FIELDS)
        if self._eval:
            # the eval lane's [episodes, return_sum] pair rides the same
            # vector; zeros on off-cadence dispatches
            ep, rsum = float(v[off]), float(v[off + 1])
            off += len(EVAL_FIELDS)
            if ep > 0:
                with self._stats_lock:
                    self.eval_episodes_total += int(ep)
                    self.eval_return_total += rsum
                    self.last_eval_return = rsum / ep
                    self._interval_eval_episodes += int(ep)
        if self.monitor is not None:
            # the monitor owns non-finite handling (trips a clean fabric
            # stop + the nonfinite alert) and absorbs the diag rows the
            # fused program appended to the same flat vector
            self.monitor.note_losses(losses)
            if self._lh:
                self.monitor.absorb_diags(v[off:].reshape(k, -1))
        else:
            assert np.isfinite(losses).all(), (
                f"non-finite loss in anakin super-step: {losses}")
        self._absorb(stats)
        with self._stats_lock:
            self.training_steps += k
            self._interval_loss += float(losses.sum())
        return losses

    def _absorb(self, s: np.ndarray) -> None:
        d = dict(zip(STATS_FIELDS, s.tolist()))
        with self._stats_lock:
            self.env_steps += int(d["env_steps"])
            self.fill = int(d["fill"])
            self.blocks += int(d["blocks"])
            self.episodes_total += int(d["episodes"])
            self.reward_total += float(d["reward_sum"])
            self._interval_episodes += int(d["episodes"])
            self._interval_reward += float(d["reward_sum"])

    @property
    def ready(self) -> bool:
        return self.fill >= self.cfg.learning_starts

    def stats(self) -> Dict[str, float]:
        """ReplayBuffer.stats()-shaped snapshot for the log loop (the
        interval accumulators reset on read, like the buffer's)."""
        with self._stats_lock:
            out = dict(size=self.fill, env_steps=self.env_steps,
                       training_steps=self.training_steps,
                       num_episodes=self._interval_episodes,
                       episode_reward=self._interval_reward,
                       sum_loss=self._interval_loss,
                       frames=self.frames, super_steps=self.super_steps,
                       blocks=self.blocks,
                       episodes_total=self.episodes_total,
                       eval_episodes=self.eval_episodes_total,
                       interval_eval_episodes=self._interval_eval_episodes,
                       eval_return=self.last_eval_return)
            self._interval_episodes = 0
            self._interval_reward = 0.0
            self._interval_loss = 0.0
            self._interval_eval_episodes = 0
        return out

    # ----------------------------------------------------------- snapshot
    _COUNTER_FIELDS = ("env_steps", "fill", "frames", "super_steps",
                       "blocks", "episodes_total", "reward_total",
                       "training_steps", "dispatch_no",
                       "eval_episodes_total", "eval_return_total")

    def _payload(self) -> Dict[str, np.ndarray]:
        """Host copies of the ENTIRE on-device loop state: anakin carry
        (env phase/t/keys, agent obs/LSTM carry, local buffers), ring
        arrays, and the PER leaf/metadata state.  Call only with no
        dispatch in flight (the driver drains its pipeline first)."""
        arrays, prios, seq_meta, first = self._handles()
        with HOST_TRANSFERS.allowed("anakin.snapshot_fetch"):
            host = jax.device_get(dict(state=self.state, ring=arrays,
                                       prios=prios, seq_meta=seq_meta,
                                       first=first))
        flat: Dict[str, np.ndarray] = {}
        for k, v in host["state"].items():
            flat[f"state_{k}"] = np.asarray(v)
        for k, v in host["ring"].items():
            flat[f"ring_{k}"] = np.asarray(v)
        flat["per_prios"] = np.asarray(host["prios"])
        flat["per_seq_meta"] = np.asarray(host["seq_meta"])
        flat["per_first"] = np.asarray(host["first"])
        return flat

    def write_state(self, path: str) -> Dict[str, Any]:
        """Serialise the full anakin loop state into ``path`` (the
        ``Checkpointer.save_replay`` writer contract — same atomic
        tmp-dir/rename machinery as host-ring replay snapshots).  Returns
        the JSON-able meta ``read_state`` validates against."""
        flat = self._payload()
        with open(path, "wb") as f:  # file handle: savez must not append .npz
            np.savez(f, **flat)
        return dict(
            kind="anakin",
            layout=[[k, list(v.shape), v.dtype.name]
                    for k, v in sorted(flat.items())],
            counters={k: getattr(self, k) for k in self._COUNTER_FIELDS},
        )

    def read_state(self, path: str, meta: Dict[str, Any]) -> None:
        """Restore the state :meth:`write_state` captured.  Raises
        ``ValueError`` on a geometry/config mismatch (the caller warns and
        resumes cold).  The snapshot is LAYOUT-FREE (host-gathered
        global arrays), so it restores under ANY mesh shape — each array
        is re-placed per the CURRENT table here, the same resharding
        contract as learner checkpoints (docs/SHARDING.md)."""
        if meta.get("kind") != "anakin":
            raise ValueError("snapshot is not an anakin loop snapshot")
        with np.load(path) as z:
            flat = {k: z[k] for k in z.files}
        want = [[k, list(v.shape), v.dtype.name]
                for k, v in sorted(flat.items())]
        have = [[k, list(v.shape), np.dtype(v.dtype).name]
                for k, v in sorted(self._payload_template().items())]
        if want != have:
            raise ValueError(
                "anakin snapshot layout mismatch — written under a "
                "different config geometry; resuming cold")

        def place(v, sh):
            return (jax.device_put(v, sh) if sh is not None
                    else jnp.asarray(v))

        self.state = {
            k[len("state_"):]: place(
                v, None if self._ast_sh is None
                else self._ast_sh[k[len("state_"):]])
            for k, v in flat.items() if k.startswith("state_")}
        self.ring.arrays = {
            k[len("ring_"):]: place(
                v, None if self._ring_sh is None
                else self._ring_sh[k[len("ring_"):]])
            for k, v in flat.items() if k.startswith("ring_")}
        per = self._per_sh
        self.ring.put_prios(place(flat["per_prios"],
                                  None if per is None else per["prios"]))
        self.ring.put_per_meta(
            place(flat["per_seq_meta"],
                  None if per is None else per["seq_meta"]),
            place(flat["per_first"],
                  None if per is None else per["first"]))
        c = meta.get("counters", {})
        for k in self._COUNTER_FIELDS:
            if k in c:
                setattr(self, k, type(getattr(self, k))(c[k]))

    def _payload_template(self) -> Dict[str, Any]:
        """Shape/dtype template of :meth:`_payload` WITHOUT fetching
        device bytes (for layout validation before overwriting state)."""
        arrays, prios, seq_meta, first = self._handles()
        out: Dict[str, Any] = {}
        for k, v in self.state.items():
            out[f"state_{k}"] = jax.ShapeDtypeStruct(jnp.shape(v), v.dtype)
        for k, v in arrays.items():
            out[f"ring_{k}"] = jax.ShapeDtypeStruct(jnp.shape(v), v.dtype)
        out["per_prios"] = jax.ShapeDtypeStruct(jnp.shape(prios),
                                                prios.dtype)
        out["per_seq_meta"] = jax.ShapeDtypeStruct(jnp.shape(seq_meta),
                                                   seq_meta.dtype)
        out["per_first"] = jax.ShapeDtypeStruct(jnp.shape(first),
                                                first.dtype)
        return out


def run_anakin_loop(learner: Any, plane: AnakinPlane,
                    stop: Optional[Any] = None, tracer: Optional[Any] = None,
                    max_steps: Optional[int] = None,
                    snapshot_fn: Optional[Any] = None,
                    chaos: Optional[Any] = None) -> Dict[str, Any]:
    """The anakin drivetrain: warm-up rollouts until the in-graph ring
    fill passes ``learning_starts``, then pipelined fused super-steps with
    the publish/save cadences of the other device drivetrains
    (:meth:`Learner._superstep_loop` semantics; updates advance by k per
    dispatch).  ``snapshot_fn(step)``, when given, is called at
    ``cfg.replay_snapshot_interval``-second crossings ON this thread (the
    dispatch thread owns the device handles, so periodic full-state
    snapshots cannot race a dispatch).  Returns summary metrics incl. the
    full per-update loss curve.

    ``cfg.dispatch_deadline`` (> 0) bounds each harvest — the loop's one
    blocking device wait — by fetching on a helper thread with a bounded
    join, so even a device wait that NEVER returns cannot hang the loop.
    Two wedge grades, both ending in a clean abort
    (``metrics["dispatch_wedged"]``) instead of hammering a flaky device
    or hanging forever — the Podracer stance: preemption/failure is
    routine, so park the state where ``--resume`` finds it and get out
    of the way:

    - *slow* (the fetch completed but blew the budget — it gets one
      extra budget of grace to come back): drain the pipeline, write a
      full resumable snapshot via ``snapshot_fn``, abort;
    - *hard* (the fetch did not return within twice the budget; the
      chaos ``wedge_dispatch`` site drills this by stalling the fetch
      thread past the grace window):
      abandon the fetch thread — a device wait cannot be interrupted,
      only walked away from — skip the drain (it would block on the same
      device), attempt the snapshot on a BOUNDED helper thread, and
      abort; if even the snapshot attempt times out, the last periodic
      snapshot remains the resume point."""
    import time

    cfg = learner.cfg
    if tracer is None:
        from r2d2_tpu.utils.trace import Tracer
        tracer = Tracer()
    k = cfg.superstep_k
    t0 = time.time()
    updates = learner.num_updates
    target = cfg.training_steps if max_steps is None else updates + max_steps
    losses_all: list = []
    pending: list = []
    last_snap = time.time()
    wedged = False
    hard_wedged = False
    abandoned = threading.Event()   # set when a hard wedge walks away

    def harvest_one() -> None:
        nonlocal wedged, hard_wedged
        flat = pending.pop(0)

        def fetch():
            # the chaos stall lives INSIDE the fetch so the drill
            # exercises the real hard-wedge path: a device wait that
            # does not come back within the budget
            if chaos is not None:
                stall = chaos.dispatch_wedge_seconds()
                if stall > 0:
                    log.warning("chaos: wedging the anakin dispatch "
                                "harvest for %.1fs", stall)
                    time.sleep(stall)
            if abandoned.is_set():
                # the loop declared a hard wedge and may be mid-snapshot:
                # a late harvest would fold this dispatch's counters into
                # state the snapshot thread is reading (while its losses
                # are discarded anyway) — never mutate after abandonment
                return None
            return plane.harvest(flat)

        if cfg.dispatch_deadline <= 0:           # unbounded: fetch inline
            losses_all.extend(fetch().tolist())
            return
        budget = Deadline(cfg.dispatch_deadline)
        box: list = []

        def run():
            try:
                box.append(("ok", fetch()))
            except BaseException as e:           # re-raised on the loop
                box.append(("err", e))

        t = threading.Thread(target=run, name="anakin-harvest",  # graftlint: disable=thread-discipline -- bounded-join fetch; abandoned on a hard wedge BY DESIGN, a Supervisor restart would re-block on the dead device
                             daemon=True)
        t.start()
        t.join(budget.remaining())
        if t.is_alive():
            # over budget — grant one extra budget of grace so a
            # slow-but-COMPLETING fetch lands in the slow grade below
            # (drain + full snapshot) instead of being abandoned
            t.join(cfg.dispatch_deadline)
        if t.is_alive():
            # HARD wedge: the device wait never returned.  It cannot be
            # interrupted, only abandoned — this dispatch's losses are
            # lost, and the drain/snapshot paths must not touch the
            # device unbounded (see the caller)
            log.error(
                "anakin dispatch harvest exceeded its %.1fs budget and "
                "has not returned after as much grace — treating the "
                "device as hard-wedged: abandoning the fetch, "
                "best-effort snapshot, aborting cleanly (resume with "
                "--resume)", cfg.dispatch_deadline)
            abandoned.set()
            wedged = hard_wedged = True
            return
        tag, val = box[0]
        if tag == "err":
            raise val
        losses_all.extend(val.tolist())
        if budget.expired:
            log.error(
                "anakin dispatch harvest took %.1fs (budget %.1fs) — "
                "treating the device as wedged: draining, snapshotting "
                "and aborting cleanly (resume with --resume)",
                budget.elapsed(), cfg.dispatch_deadline)
            wedged = True

    # cfg.transfer_guard: arm the process guard once warm-up ends, so
    # every disallow window in dispatch/harvest/rollout actually runs
    # jax.transfer_guard("disallow") — an undeclared implicit crossing
    # raises TransferGuardTripped instead of silently stalling the
    # stream.  Armed AFTER the rollout warm-up: compile-time constant
    # staging belongs to bring-up, not the steady-state budget.
    from contextlib import ExitStack

    guard_stack = ExitStack()
    guard_armed = False
    try:
        while updates < target and not wedged:
            if stop is not None and stop():
                break
            if not plane.ready:
                with tracer.span("anakin.rollout_dispatch"):
                    plane.rollout_step(learner.state.params)
                continue
            if cfg.transfer_guard and not guard_armed:
                guard_stack.enter_context(TRANSFER_GUARD.arm())
                guard_armed = True
            with tracer.span("learner.step_dispatch"):
                learner.state, flat = plane.dispatch(learner.state)
            pending.append(flat)
            while len(pending) > cfg.superstep_pipeline and not wedged:
                with tracer.span("learner.result_sync"):
                    harvest_one()

            prev, updates = updates, updates + k
            if (learner.param_store is not None
                    and updates // cfg.weight_publish_interval
                    > prev // cfg.weight_publish_interval):
                learner._publish()
            if (learner.checkpointer is not None
                    and updates // cfg.save_interval
                    > prev // cfg.save_interval):
                learner.env_steps = plane.env_steps
                learner._save(updates, t0)
            if (snapshot_fn is not None
                    and cfg.replay_snapshot_interval > 0
                    and time.time() - last_snap
                    > cfg.replay_snapshot_interval):
                while pending and not hard_wedged:
                    harvest_one()   # snapshots need no dispatch in flight
                if not hard_wedged:
                    snapshot_fn(updates)
                    last_snap = time.time()
        while pending and not hard_wedged:
            harvest_one()
    finally:
        guard_stack.close()
    if wedged and snapshot_fn is not None:
        # the resumable artifact of the clean abort: full loop state,
        # parked where --resume restores it bit-exact.  On a HARD wedge
        # the snapshot itself reads device handles and can block on the
        # same dead device — bound the attempt instead of trading a hang
        # for a hang (if it times out, the last periodic snapshot stays
        # the resume point)
        if not hard_wedged:
            snapshot_fn(updates)
        else:
            snapped = threading.Event()

            def snap():
                try:
                    snapshot_fn(updates)
                    snapped.set()
                except Exception:
                    log.exception("hard-wedge snapshot attempt failed")

            st = threading.Thread(target=snap, name="anakin-wedge-snap",  # graftlint: disable=thread-discipline -- one best-effort bounded-join snapshot at abort; nothing to supervise after it
                                  daemon=True)
            st.start()
            st.join(max(10.0, 10.0 * cfg.dispatch_deadline))
            if not snapped.is_set():
                log.error("hard-wedge snapshot did not complete in time "
                          "— aborting without a fresh snapshot")

    learner.env_steps = plane.env_steps
    if hard_wedged:
        # the shared epilogue's final checkpoint save device_gets params
        # from the SAME wedged device — bound it like the snapshot above
        # so a dead device cannot turn the clean abort back into a hang
        # (on a timeout the last complete step checkpoint stays the
        # params half of the resume pair)
        fin_box: dict = {}

        def fin():
            try:
                fin_box["metrics"] = learner._finish_device_run(
                    losses_all[-100:], t0)
            except Exception:
                log.exception("hard-wedge epilogue save failed")

        ft = threading.Thread(target=fin, name="anakin-wedge-fin",  # graftlint: disable=thread-discipline -- one bounded-join epilogue save at abort; nothing to supervise after it
                              daemon=True)
        ft.start()
        ft.join(max(10.0, 10.0 * cfg.dispatch_deadline))
        metrics = fin_box.get("metrics")
        if metrics is None:
            log.error("hard-wedge final save did not complete in time — "
                      "summarizing without it")
            metrics = dict(
                num_updates=learner.num_updates,
                env_steps=learner.env_steps,
                minutes=learner.start_minutes + (time.time() - t0) / 60.0,
                mean_loss=(float(np.mean(losses_all[-100:]))
                           if losses_all else float("nan")))
    else:
        metrics = learner._finish_device_run(losses_all[-100:], t0)
    metrics["losses"] = losses_all
    metrics["dispatch_wedged"] = wedged
    metrics["env_steps"] = plane.env_steps
    metrics["anakin_frames"] = plane.frames
    metrics["anakin_super_steps"] = plane.super_steps
    metrics["episodes"] = plane.episodes_total
    metrics["mean_episode_return"] = (
        plane.reward_total / plane.episodes_total
        if plane.episodes_total else float("nan"))
    # in-graph greedy eval lane totals (cfg.anakin_eval_interval)
    metrics["eval_episodes"] = plane.eval_episodes_total
    metrics["mean_eval_return"] = (
        plane.eval_return_total / plane.eval_episodes_total
        if plane.eval_episodes_total else float("nan"))
    return metrics
