"""The jitted R2D2 train step.

Capability-parity with the reference learner's gradient path
(worker.py:318-390): burn-in + stored-state LSTM unroll, n-step **double-Q**
targets under value rescaling, importance-weighted MSE over the learning
window, grad-clip-40 Adam, mixed max/mean per-sequence priorities, periodic
hard target-net sync.

TPU-first redesign:
- The reference runs three packed-sequence forwards per step (online no-grad,
  target no-grad, online grad — worker.py:346-352).  Here a single unroll per
  network suffices: the full-T Q sequence is gathered at the online window
  indices (grad path) and at the n-step-shifted target indices (stop-grad
  path), which is mathematically identical and ~⅓ cheaper.
- Window selection is static-shape: per-sample ``(burn_in, learning,
  forward)`` become gather indices and a validity mask, replacing the
  per-sample Python slice loops of model.py:102-111,143.  The edge-padding
  semantics for episodes that end inside the n-step window (model.py:103-109)
  are reproduced by clamping target indices to ``burn_in+learning+forward-1``.
- Priorities (worker.py:268-276, a host-side Python loop in the reference,
  forcing a device→host sync every step) are computed inside the jit as
  masked segment max/mean and returned as one small array.
- Target sync (worker.py:376-377) happens in-graph via a step-counter select,
  so the whole training loop state lives on device.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from r2d2_tpu.config import Config
from r2d2_tpu.models.network import R2D2Network


def value_rescale(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    """h(x) = sign(x)(sqrt(|x|+1)-1) + eps*x (worker.py:383-385)."""
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def inverse_value_rescale(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    t = (jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps)) - 1.0) / (2.0 * eps)
    return jnp.sign(x) * (jnp.square(t) - 1.0)


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    target_params: Any
    opt_state: Any


def make_optimizer(cfg: Config) -> optax.GradientTransformation:
    """Adam(lr, eps) + global-norm clip 40 (worker.py:289,364)."""
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_norm),
        optax.adam(cfg.lr, eps=cfg.adam_eps),
    )


def create_train_state(cfg: Config, params) -> TrainState:
    opt = make_optimizer(cfg)
    # copy params into the state: the jitted step donates its input state,
    # so the state must not alias buffers the caller still holds
    params = jax.tree.map(jnp.copy, params)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        target_params=jax.tree.map(jnp.copy, params),
        opt_state=opt.init(params),
    )


def _window_indices(cfg: Config, burn_in, learning, forward):
    """Gather indices into the unrolled (B, T, A) Q sequence.

    Sample layout along T is [burn_in | learning | forward] from t=0
    (replay assembles windows that way; see replay_buffer.sample_batch).

    - online index for learning step i:  burn_in + i
    - target index for learning step i:  min(burn_in + n + i,
                                             burn_in + learning + forward - 1)
      reproducing model.py:102-109 (start at burn_in + max_forward_steps,
      edge-pad when the episode ended inside the forward window).
    """
    L, n = cfg.learning_steps, cfg.forward_steps
    steps = jnp.arange(L)[None, :]                       # (1, L)
    b = burn_in[:, None]
    idx_online = b + steps                                # (B, L)
    last_valid = (burn_in + learning + forward - 1)[:, None]
    idx_target = jnp.minimum(b + n + steps, last_valid)
    mask = steps < learning[:, None]                      # (B, L)
    return idx_online, idx_target, mask


def _gather_time(q_seq, idx):
    # q_seq: (B, T, A); idx: (B, L) → (B, L, A)
    return jnp.take_along_axis(q_seq, idx[:, :, None], axis=1)


def mixed_priorities(abs_td, mask, learning, eta=0.9):
    """Masked per-sequence 0.9·max + 0.1·mean of |TD| (worker.py:268-276)."""
    masked = jnp.where(mask, abs_td, 0.0)
    seg_max = masked.max(axis=1)
    seg_mean = masked.sum(axis=1) / jnp.maximum(learning, 1)
    return eta * seg_max + (1.0 - eta) * seg_mean


def _double_unroll(cfg: Config, net: R2D2Network, params, target_params,
                   batch) -> tuple:
    """(q_online, q_target_seq), each (B, T, A).

    Default: two independent unrolls (reference semantics — worker.py's
    separate online/target forwards).  With ``cfg.fused_double_unroll``,
    ONE unroll vmapped over the stacked (online, target) param pytrees:
    the recurrence walks T sequential steps once instead of twice, at
    double per-step batch — on the round-4 v5e measurement a B=128 unroll
    costs only 1.30x a B=64 one, so the fusion trades a free batch
    doubling for half the latency-bound scan chain.

    ``net`` must be a scan-recurrence network — callers go through
    :func:`_loss_net`, which enforces it (the Pallas kernel is
    inference-only since r5 and would fail under the surrounding
    grad / vmap)."""
    if not cfg.fused_double_unroll:
        q_online, _ = net.apply(params, batch["obs"], batch["last_action"],
                                batch["last_reward"], batch["hidden"],
                                method=R2D2Network.unroll)      # (B, T, A)
        q_target_seq, _ = net.apply(target_params, batch["obs"],
                                    batch["last_action"],
                                    batch["last_reward"], batch["hidden"],
                                    method=R2D2Network.unroll)
        return q_online, jax.lax.stop_gradient(q_target_seq)

    stacked = jax.tree.map(
        lambda p, t: jnp.stack([p, t]),
        params, jax.lax.stop_gradient(target_params))
    q_both, _ = jax.vmap(
        lambda p: net.apply(p, batch["obs"], batch["last_action"],
                            batch["last_reward"], batch["hidden"],
                            method=R2D2Network.unroll))(stacked)
    return q_both[0], jax.lax.stop_gradient(q_both[1])


def _loss_net(cfg: Config, net: R2D2Network) -> R2D2Network:
    """The network the LOSS must unroll: the scan recurrence, always.

    Built once per step-factory call (NOT per trace — the r4 advisor
    flagged the shadow-network-inside-the-loss trap).  The Pallas
    inference kernel resolves for acting/eval nets on TPU but has no
    backward (ops/lstm.py, retired r5); all impls share one param
    pytree, so swapping the engine is free."""
    from r2d2_tpu.models.network import create_network, resolve_lstm_impl

    if resolve_lstm_impl(cfg) == "scan":
        return net
    return create_network(cfg.replace(lstm_impl="scan"), net.action_dim)


def loss_and_priorities(cfg: Config, net: R2D2Network, params, target_params,
                        batch: Dict[str, jnp.ndarray], with_aux: bool = False):
    """``with_aux`` additionally returns the forward-pass intermediates
    the learnhealth diagnostics consume ``(td, mask, q_learn, max_abs_q)``
    — stop-gradiented values, never a second forward."""
    q_online, q_target_seq = _double_unroll(cfg, net, params, target_params,
                                            batch)

    idx_online, idx_target, mask = _window_indices(
        cfg, batch["burn_in"], batch["learning"], batch["forward"])

    # online Q(s_t, a_t) over the learning window — the grad path
    q_learn = _gather_time(q_online, idx_online)                  # (B, L, A)
    q_taken = jnp.take_along_axis(
        q_learn, batch["action"][:, :, None], axis=2)[:, :, 0]    # (B, L)

    # double-Q: online argmax at t+n, target evaluates (worker.py:345-347)
    q_online_tn = jax.lax.stop_gradient(_gather_time(q_online, idx_target))
    a_star = jnp.argmax(q_online_tn, axis=-1)                     # (B, L)
    q_boot = jnp.take_along_axis(
        _gather_time(q_target_seq, idx_target),
        a_star[:, :, None], axis=2)[:, :, 0]                      # (B, L)

    # rescaled n-step target (worker.py:349)
    target = value_rescale(
        batch["n_step_reward"] + batch["n_step_gamma"]
        * inverse_value_rescale(q_boot))

    td = target - q_taken
    weighted_sq = batch["is_weights"][:, None] * jnp.square(td)
    valid = mask.sum()
    loss = jnp.where(mask, weighted_sq, 0.0).sum() / jnp.maximum(valid, 1)

    priorities = mixed_priorities(jnp.abs(td), mask, batch["learning"])
    if not with_aux:
        return loss, priorities
    aux = jax.lax.stop_gradient(
        (td, mask, q_learn, jnp.abs(q_online).max()))
    return loss, (priorities, aux)


def make_train_step(cfg: Config, net: R2D2Network,
                    learnhealth: bool = False):
    """Returns ``train_step(state, batch) -> (state, loss, priorities)``
    — the pure function.  The ONE place it is jitted is
    ``parallel/sharding.pjit_train_step`` (table-driven shardings,
    state+batch donation); a 1-device mesh is the single-device case.

    ``learnhealth`` (and ``cfg.learnhealth_interval > 0``) appends the
    in-graph diagnostic vector (telemetry/learnhealth.py) to the
    signature: ``-> (state, loss, priorities, diag (DIAG_SIZE,) f32)``.
    The diagnostics — including the paper's ΔQ zero-state re-unroll —
    run under ``lax.cond`` on the step counter, so the
    ``learnhealth_interval - 1`` disarmed steps between cadence points
    pay only a zeros fill."""
    opt = make_optimizer(cfg)
    net = _loss_net(cfg, net)  # grad paths always run the scan recurrence
    lh = learnhealth and getattr(cfg, "learnhealth_interval", 0) > 0
    if lh:
        from r2d2_tpu.telemetry.learnhealth import DIAG_SIZE, make_diag_fn

        diag_fn = make_diag_fn(cfg, net)

    def train_step(state: TrainState, batch: Dict[str, jnp.ndarray]):
        grad_fn = jax.value_and_grad(
            lambda p: loss_and_priorities(cfg, net, p, state.target_params,
                                          batch, with_aux=lh),
            has_aux=True)
        (loss, priorities), grads = grad_fn(state.params)
        if lh:
            priorities, aux = priorities
        updates, new_opt_state = opt.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        step = state.step + 1
        sync = (step % cfg.target_net_update_interval) == 0
        new_target = jax.tree.map(
            lambda p, t: jnp.where(sync, p, t), new_params, state.target_params)

        new_state = TrainState(step=step, params=new_params,
                               target_params=new_target,
                               opt_state=new_opt_state)
        if not lh:
            return new_state, loss, priorities
        armed = (step % cfg.learnhealth_interval) == 0
        diag = jax.lax.cond(
            armed,
            lambda op: diag_fn(*op),
            lambda op: jnp.zeros((DIAG_SIZE,), jnp.float32),
            (state.params, batch, loss, grads, updates, new_params,
             new_target, aux))
        return new_state, loss, priorities, diag

    return train_step


def make_super_step_fn(cfg: Config, net: R2D2Network, k: int, gather=None,
                       learnhealth: bool = False):
    """The unjitted ``k``-fused-steps function — batches gathered in-graph
    from the device-resident replay ring (replay/device_ring.py).

    This is the latency-immune learner drivetrain: one dispatch + one small
    H2D (the (k, B, 6) index bundle) + one small D2H (stacked losses and
    priorities) amortise host↔device round trips over ``k`` optimizer
    steps, while batch bytes never cross the boundary at all.  The inner
    step is exactly ``make_train_step`` — target sync and the step counter
    advance per inner step, so k super-steps ≡ k·1 plain steps.

    ``gather(arrays, ints_t (B,6), w_t (B,)) -> batch`` defaults to the
    plain in-graph gather (GSPMD partitions it under a dp-sharded ring —
    no hand-written shard_map variant since r9).

    Signature: ``super_step(state, ring_arrays, ints (k,B,6) i32,
    is_weights (k,B) f32) -> (state, losses (k,), priorities (k,B))``
    (``learnhealth``: ``+ diags (k, DIAG_SIZE)`` — the per-inner-step
    diagnostic vectors, zeros off-cadence).  Jitted only by
    ``parallel/sharding.pjit_super_step`` (table-driven shardings; a
    1-device mesh is the single-device case).
    """
    from r2d2_tpu.replay.device_ring import gather_batch

    if gather is None:
        gather = functools.partial(gather_batch, cfg)
    lh = learnhealth and getattr(cfg, "learnhealth_interval", 0) > 0
    step = make_train_step(cfg, net, learnhealth=lh)

    def super_step(state: TrainState, arrays, ints, is_weights):
        def body(st, x):
            ints_t, w_t = x
            batch = gather(arrays, ints_t, w_t)
            if lh:
                st, loss, priorities, diag = step(st, batch)
                return st, (loss, priorities, diag)
            st, loss, priorities = step(st, batch)
            return st, (loss, priorities)

        state, ys = jax.lax.scan(body, state, (ints, is_weights))
        if lh:
            losses, priorities, diags = ys
            return state, losses, priorities, diags
        losses, priorities = ys
        return state, losses, priorities

    return super_step


def _compensated_cumsum(x):
    """Prefix sums of ``x`` (f32) with double-float (two-sum) carries —
    near-f64 accuracy, validated against an f64 oracle.  (Not "correctly
    rounded": the compensated operator is not exactly associative, so
    ``associative_scan``'s tree shapes can differ from a sequential
    double-float sum by a final-rounding ulp or two — far below stratum
    -boundary resolution, which is what the oracle tests pin.)

    The host SumTree accumulates node sums in float64
    (replay/sum_tree.py); a plain f32 ``jnp.cumsum`` over the ~50k-leaf
    flagship array accumulates O(n·eps) drift that can shift stratum
    boundaries relative to the host tree's.  Carrying the rounding error
    in a second f32 lane (error-free two-sum, folded back each step)
    removes the accumulated drift while staying pure f32 — portable to
    TPU, where f64 support is not guaranteed.  Verified 0 stratum
    -boundary disagreements vs an np.float64 oracle across seeds, incl.
    adversarial 1e-6/1e3 mixed-priority spreads at the largest per-slab
    leaf count a v5e ring holds
    (tests/test_in_graph_per.py::test_compensated_cumsum_matches_f64,
    ::test_compensated_cumsum_adversarial_spread_per_slab)."""

    def dd_add(a, b):
        ah, al = a
        bh, bl = b
        s = ah + bh
        bb = s - ah
        err = (ah - (s - bb)) + (bh - bb)
        lo = err + al + bl
        hi = s + lo
        return hi, lo - (hi - s)

    hi, _ = jax.lax.associative_scan(dd_add, (x, jnp.zeros_like(x)))
    return hi


def _in_graph_sample_raw(cfg: Config, key, prios, seq_meta, first_burn,
                         n_rows: int, constrain_rep=None):
    """``n_rows`` stratified proportional draws from a leaf slab:
    (idx (n,), q (n,) f32 inclusion densities, ints (n, 6) i32).
    The density q = prio/mass is the *raw* per-row inclusion
    probability scale — the caller turns it into IS weights (min-
    normalised over whatever scope it owns: the whole batch here, the
    pod-wide batch in the grouped/multi-host samplers).  Host twin:
    ``ReplayBuffer._grouped_densities`` (same q definition)."""
    K, L = cfg.seqs_per_block, cfg.learning_steps
    cum = _compensated_cumsum(prios)   # f64-accurate prefixes in f32
    total = cum[-1]
    u = jax.random.uniform(key, (n_rows,))
    if constrain_rep is not None:
        # mesh mode: with non-partitionable threefry, the generated BITS
        # change when GSPMD back-propagates a dp sharding onto this
        # output — pinning it replicated keeps the draw bit-identical to
        # the single-device one under every layout
        u = constrain_rep(u)
    targets = (jnp.arange(n_rows, dtype=jnp.float32) + u) * (total / n_rows)
    idx = jnp.searchsorted(cum, targets, side="right")
    idx = jnp.minimum(idx, prios.shape[0] - 1)
    idx = jnp.where(prios[idx] > 0, idx, jnp.argmax(prios))
    block_idx = idx // K
    seq_idx = (idx % K).astype(jnp.int32)
    meta = seq_meta[block_idx, seq_idx]                         # (n, 3)
    burn = meta[:, 0]
    start = first_burn[block_idx] + seq_idx * L
    ints_t = jnp.stack(
        [block_idx.astype(jnp.int32), start - burn, seq_idx, burn,
         meta[:, 1], meta[:, 2]], axis=1)
    # an all-zero slab (violates the ready-gate precondition) must not
    # emit NaN densities — clamp to 1.0; the gathered rows are zero
    # padding whose loss contribution the window masks bound anyway
    q = jnp.where(total > 0, prios[idx] / total, 1.0)
    return idx, q, ints_t


def _in_graph_sample(cfg: Config, key, prios, seq_meta, first_burn,
                     constrain_rep=None):
    """One prioritized batch draw on-device: (idx (B,), is_weights (B,)
    f32, ints (B, 6) i32).

    STRATIFIED proportional sampling, the host sum-tree's exact joint
    scheme (replay/sum_tree.py:sample): the total mass splits into B
    equal strata with one uniform draw each — same variance-reduced
    batch composition, not just matching marginals — realised in-graph
    as cumsum + searchsorted instead of B tree descents.  Zero-priority
    leaves (empty slots, block padding) are zero-width cumsum bins,
    unreachable with side='right'; the float-edge fallback snaps to the
    max-priority leaf (the host's clamp guard analogue) so a scatter can
    never make padding sampleable.  IS weights are the reference scheme:
    w = (p/min sampled p)^-beta (identical to the host's, the mass
    normalisation cancels).  The ints bundle reproduces ``sample_meta``'s
    index arithmetic (replay_buffer.py:372-390) from the device-resident
    metadata, so ``gather_batch`` sees identical inputs either way."""
    idx, q, ints_t = _in_graph_sample_raw(
        cfg, key, prios, seq_meta, first_burn, cfg.batch_size,
        constrain_rep=constrain_rep)
    w = (q / q.min()) ** (-cfg.importance_sampling_exponent)
    return idx, w.astype(jnp.float32), ints_t


def make_in_graph_per_super_step_fn(cfg: Config, net: R2D2Network, k: int,
                                    constrain=None,
                                    replicate_for_draw=None,
                                    learnhealth: bool = False):
    """``k`` fused steps with DEVICE-side PER: sample → gather → step →
    priority scatter, all inside one dispatch.

    vs :func:`make_super_step_fn` (host-sampled bundles): the learner
    loop no longer round-trips priorities through the host at all — on a
    high-latency interconnect (the tunneled chip measures ~100 ms/RTT,
    MEASURE_TPU_r04.md: ``learner.result_sync`` ≈ 99 ms/harvest) the
    dispatch cadence becomes pure device compute.  It is also *tighter*
    feedback than the reference's queue (worker.py:300-316 lags 8+4
    batches) or our host path (lags ≥ k): step j+1 samples from the
    priorities step j just wrote.

    Signature: ``super_step(state, ring_arrays, prios (NB*K,) f32
    [donated], seq_meta (NB,K,3) i32, first_burn (NB,) i32,
    dispatch_idx u32) -> (state, prios', losses (k,))``
    (``learnhealth``: ``+ diags (k, DIAG_SIZE)``).  The sampling
    stream is ``fold_in(PRNGKey(cfg.seed), dispatch_idx)`` — distinct per
    dispatch with no seed/counter bit-packing to alias or overflow.
    Jitted only by ``parallel/sharding.pjit_in_graph_per_super_step``.
    """
    from r2d2_tpu.replay.device_ring import gather_batch

    lh = learnhealth and getattr(cfg, "learnhealth_interval", 0) > 0
    step = make_train_step(cfg, net, learnhealth=lh)

    def super_step(state: TrainState, arrays, prios, seq_meta, first_burn,
                   dispatch_idx):
        keys = jax.random.split(
            jax.random.fold_in(jax.random.PRNGKey(cfg.seed), dispatch_idx),
            k)

        def body(carry, key_t):
            st, p = carry
            # mesh mode: the draw runs over a REPLICATED view of the
            # priority leaves — _compensated_cumsum's associative_scan
            # changes tree shape (and so its final-ulp rounding) when
            # GSPMD partitions it, and an ulp at a stratum boundary
            # flips which slot that stratum draws.  Replicating the
            # (leaves,)-sized scan makes the draw bit-identical under
            # every layout for pennies; the gather/forward stay sharded.
            p_draw = p if replicate_for_draw is None else (
                replicate_for_draw(p))
            idx, w, ints_t = _in_graph_sample(
                cfg, key_t, p_draw, seq_meta, first_burn,
                constrain_rep=replicate_for_draw)
            if constrain is not None:
                # mesh mode: the (replicated) sampled bundle's batch rows
                # are pinned to dp here, so GSPMD shards the gather and
                # the forward/backward over the mesh exactly as the
                # host-sampled path's dp-sharded H2D bundles do
                ints_t, w = constrain(ints_t, w)
            batch = gather_batch(cfg, arrays, ints_t, w)
            if lh:
                st, loss, new_p, diag = step(st, batch)
            else:
                st, loss, new_p = step(st, batch)
            # feedback: same exponentiation the host tree applies
            # (sum_tree.py:60); duplicate-idx writes resolve arbitrarily,
            # as does the host's sequential last-wins — both harmless
            p = p.at[idx].set(new_p ** cfg.prio_exponent)
            return (st, p), ((loss, diag) if lh else loss)

        (state, prios), ys = jax.lax.scan(body, (state, prios), keys)
        if lh:
            losses, diags = ys
            return state, prios, losses, diags
        return state, prios, ys

    return super_step
