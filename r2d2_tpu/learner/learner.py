"""Learner host loop: the drivetrain around the jitted train step.

Capability-parity with the reference learner's ``run`` (worker.py:300-381):
staged batch prefetch, periodic weight publication, periodic checkpointing.
Target-net sync is already *inside* the jitted step (in-graph select), so
the host loop only drives data and cadences.

TPU-first redesign:
- The prefetch thread moves batches host→device (``jax.device_put`` with
  the mesh sharding) **ahead of** the compute stream, so H2D overlaps the
  previous step — the async analogue of the reference's host-side staging
  list (worker.py:309-316).
- Weight publication is a versioned immutable snapshot (ParamStore), not a
  shared-memory mutation (worker.py:306-307).
- Multi-device: pass a Mesh and the same loop drives the GSPMD-sharded
  step; the loop code is identical.
- Checkpointing saves the full TrainState with resume (checkpoint.py),
  beating the reference's save-only ``torch.save`` (worker.py:380-381).
"""
from __future__ import annotations

import contextlib
import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.checkpoint import Checkpointer
from r2d2_tpu.config import Config
from r2d2_tpu.learner.step import TrainState
from r2d2_tpu.models.network import R2D2Network
from r2d2_tpu.parallel.mesh import trivial_mesh
from r2d2_tpu.parallel.sharding import (
    DEVICE_BATCH_KEYS,
    ShardingTable,
    pjit_train_step,
)
from r2d2_tpu.utils.store import ParamStore
from r2d2_tpu.utils.trace import HOST_TRANSFERS, TRANSFER_GUARD

def _aval_tree(tree):
    """ShapeDtypeStruct avals (shape/dtype/sharding) for every leaf —
    for AOT-lowering a super-step WITHOUT touching live device buffers.
    Call under the buffer lock when the leaves are donated ring handles:
    a concurrent actor commit donates them, and lowering from a live
    array could read a deleted buffer (ADVICE r4)."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            jnp.shape(x), x.dtype, sharding=getattr(x, "sharding", None)),
        tree)


# batch_source() -> host batch dict (blocking); returns None to stop early.
BatchSource = Callable[[], Optional[Dict[str, np.ndarray]]]
# priority_sink(idxes, priorities, old_ptr, loss)
PrioritySink = Callable[[np.ndarray, np.ndarray, int, float], None]


class Learner:
    def __init__(self, cfg: Config, net: R2D2Network, state: TrainState,
                 mesh: Optional[Any] = None,
                 param_store: Optional[ParamStore] = None,
                 checkpointer: Optional[Checkpointer] = None,
                 start_env_steps: int = 0, start_minutes: float = 0.0,
                 table: Optional[ShardingTable] = None):
        self.cfg = cfg
        self.net = net
        self.mesh = mesh  # None = single-device (a trivial 1x1x1 mesh)
        self.param_store = param_store
        self.checkpointer = checkpointer
        self.env_steps = start_env_steps
        self.start_minutes = start_minutes
        self._replicate_params = None  # lazily-built multihost resharder
        self._copy_params = None       # lazily-built one-dispatch snapshotter
        self._saved_steps: set = set()  # steps THIS run saved (see _save)
        # learnhealth plane (telemetry/learnhealth.py): with a nonzero
        # cadence every drivetrain's compiled step carries the in-graph
        # diagnostic vector, folded into the existing result fetch; the
        # trainer attaches a LearnHealthMonitor to absorb it
        self._lh = getattr(cfg, "learnhealth_interval", 0) > 0
        self.monitor: Optional[Any] = None

        # ONE train-step entry point for every topology: the table-driven
        # pjit step (parallel/sharding.py).  A 1-device trivial mesh makes
        # the single-device learner the degenerate case of the same code
        # path — no separate jit variant, no mesh branches.
        self.table = table if table is not None else ShardingTable(
            mesh if mesh is not None else trivial_mesh(), cfg)
        self._step_fn = pjit_train_step(cfg, net, self.table,
                                        state_template=state)
        self._shardings = self.table.batch_shardings()
        self.state = self.table.place_state(state)

        if self.param_store is not None:
            self._publish()

    def _publish(self) -> None:
        # deep-copy: the jitted step donates the state, so a published
        # snapshot must not alias state buffers or the next update would
        # delete it out from under the actors
        if jax.process_count() > 1 and self.mesh is not None:
            # Multi-host: the state lives on the GLOBAL mesh, and any jit
            # on global arrays is an SPMD launch every process must make
            # in lockstep.  The actor thread consumes published params at
            # arbitrary times, so handing it global arrays would let it
            # issue unsynchronised collective launches that corrupt the
            # collective stream (observed as a pod-wide deadlock in the
            # learner's own allgathers).  Publish HOST arrays instead:
            # reshard to replicated in-graph (a lockstep collective, made
            # here on the learner thread — mp-sharded leaves live on
            # other hosts) and fetch; actors then re-commit them to a
            # local device and their inference jits stay process-local.
            if self._replicate_params is None:
                from jax.sharding import NamedSharding, PartitionSpec

                rep = NamedSharding(self.mesh, PartitionSpec())
                # built once: a fresh jit per publish would re-trace (and
                # without a compile cache, re-compile) the reshard program
                # on the learner hot loop every publish
                self._replicate_params = jax.jit(lambda p: p,
                                                 out_shardings=rep)
            self.param_store.publish(jax.device_get(
                self._replicate_params(self.state.params)))
        else:
            if self._copy_params is None:
                # one jitted executable for the whole-tree copy: a bare
                # tree_map of jnp.copy issues one dispatch PER LEAF, which
                # on a tunneled/remote link puts ~leaf-count round-trip
                # overheads on the dispatch path every publish (and k=4
                # publishes once per super-step dispatch)
                self._copy_params = jax.jit(
                    lambda p: jax.tree.map(jnp.copy, p))
            self.param_store.publish(self._copy_params(self.state.params))

    @property
    def num_updates(self) -> int:
        return int(jax.device_get(self.state.step))

    def _note_results(self, losses_np: np.ndarray,
                      diags_np: Optional[np.ndarray] = None,
                      strict: bool = True) -> None:
        """Route harvested losses (+ learnhealth diagnostics) to the
        attached monitor.  Without a monitor, ``strict`` preserves the
        historical fail-fast on a non-finite loss; with one, the monitor
        trips the fabric's clean stop and fires the ``nonfinite`` alert
        instead of crashing the learner thread mid-donation."""
        m = self.monitor
        if m is not None:
            m.note_losses(losses_np)
            if diags_np is not None and diags_np.size:
                m.absorb_diags(diags_np)
            return
        if strict:
            assert np.isfinite(losses_np).all(), (
                f"non-finite loss in super-step: {losses_np}")

    def poison_params(self) -> None:
        """Chaos drill hook (``poison_params`` site, utils/chaos.py):
        overwrite the first param leaf with NaN so the next step's loss
        and grads go non-finite — the learnhealth NaN-sentry e2e.  Must
        run on the learner thread (the state handle is donated per
        dispatch)."""
        leaves, treedef = jax.tree.flatten(self.state.params)
        leaves[0] = leaves[0] * jnp.nan  # multiply keeps the sharding
        self.state = self.state.replace(
            params=jax.tree.unflatten(treedef, leaves))

    def _stage(self, batch: Dict[str, np.ndarray]
               ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Split host bookkeeping from device fields and start the H2D copy.

        Multi-host: each process's batch holds only its dp rows, assembled
        into one global sharded array (parallel/distributed.py) — batch
        data never crosses DCN."""
        host = {k: batch[k] for k in batch if k not in DEVICE_BATCH_KEYS}
        if jax.process_count() > 1 and self.mesh is not None:
            from r2d2_tpu.parallel.distributed import host_local_batch

            dev = host_local_batch(
                self.mesh, {k: batch[k] for k in DEVICE_BATCH_KEYS},
                shardings=self._shardings)
        else:
            with TRANSFER_GUARD.disallow("learner.stage"):
                # explicit device_put with a sharding: guard-exempt —
                # the window catches any *implicit* H2D sneaking in
                dev = {k: jax.device_put(batch[k], self._shardings[k])
                       for k in DEVICE_BATCH_KEYS}
        return dev, host

    def run(self, batch_source: BatchSource,
            priority_sink: Optional[PrioritySink] = None,
            max_steps: Optional[int] = None,
            stop: Optional[Callable[[], bool]] = None,
            tracer: Optional[Any] = None) -> Dict[str, float]:
        """Drive training until ``cfg.training_steps`` (or ``max_steps`` more
        updates, or ``stop()``).  Returns summary metrics.

        Results (loss + priorities) are harvested behind up to
        ``cfg.superstep_pipeline`` in-flight steps with their D2H copies
        started at dispatch time — same latency-hiding scheme as the
        device-replay driver (:meth:`_superstep_loop`); priority feedback
        lags ≤ pipeline steps (0 = fully synchronous, the train_sync
        setting).

        ``tracer`` (utils/trace.Tracer) records per-stage spans: batch wait,
        jitted step dispatch, and the device→host result sync."""
        cfg = self.cfg
        if tracer is None:
            from r2d2_tpu.utils.trace import Tracer
            tracer = Tracer()
        t0 = time.time()
        target = cfg.training_steps if max_steps is None else (
            self.num_updates + max_steps)

        # prefetch_batches == 0 → fully synchronous staging (deterministic;
        # used by train_sync and tests).  Otherwise a Supervisor-managed
        # thread keeps up to ``prefetch_batches`` device-resident batches
        # ahead of compute.  Supervision (vs the former bare daemon
        # thread): a transient staging crash — an H2D hiccup, a flaky
        # batch source — restarts the loop and the run continues, and only
        # an exhausted restart budget ends the stream; the loop is
        # re-enterable because its whole state is the bounded queue.
        pf_sup = None
        if cfg.prefetch_batches > 0:
            from r2d2_tpu.utils.supervisor import Supervisor

            staged: "queue.Queue" = queue.Queue(maxsize=cfg.prefetch_batches)
            done = threading.Event()

            def prefetch():
                while not done.is_set():
                    batch = batch_source()
                    item = None if batch is None else self._stage(batch)
                    # bounded put that re-checks done: when the learner
                    # stops consuming with the queue full, the thread
                    # must exit rather than park in put() forever (and
                    # pin device-resident staged batches).  A None item is
                    # the end-of-stream sentinel — delivered through the
                    # queue, so a supervised restart after a crash can
                    # never fabricate one.
                    while not done.is_set():
                        try:
                            staged.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if batch is None:
                        return

            pf_sup = Supervisor(max_restarts=2, backoff=0.1)
            pf_thread = pf_sup.start("learner_prefetch", prefetch)

            def next_item():
                # timeout + liveness check: a producer that exhausted its
                # restart budget with the queue empty can never enqueue
                # its sentinel — only then give up (between a crash and
                # its supervised restart the thread is briefly not alive,
                # which must NOT end the stream)
                while True:
                    try:
                        return staged.get(timeout=0.5)
                    except queue.Empty:
                        if pf_sup.any_failed or (not pf_thread.alive
                                                 and done.is_set()):
                            return None
        else:
            done = threading.Event()

            def next_item():
                batch = batch_source()
                return None if batch is None else self._stage(batch)

        # multi-host: stop decisions (wall-clock deadlines, fabric
        # failures) are host-local, but leaving the step loop early on one
        # host would deadlock the others' collectives — sync the flag so
        # all hosts break at the same step boundary
        if jax.process_count() > 1:
            from r2d2_tpu.parallel.distributed import sync_counter

            def any_host(flag: bool) -> bool:
                """True iff the condition holds on any host (collective —
                every host must call it once per loop iteration)."""
                return sync_counter(int(flag), reduce="max") > 0
        else:
            def any_host(flag: bool) -> bool:
                return flag

        def should_stop() -> bool:
            return any_host(bool(stop()) if stop is not None else False)

        # bounded to exactly the reported window: an unbounded list grows
        # ~1 MB/min at fabric rates (measured on a 30-min soak)
        losses: deque = deque(maxlen=100)

        def harvest(pending_item) -> None:
            """Fetch one in-flight step's results and feed them back.
            The copies were started at dispatch time, so behind a nonzero
            pipeline the fetch usually finds host-resident bytes instead
            of paying a fresh interconnect round trip."""
            host, loss, priorities = pending_item
            with tracer.span("learner.result_sync"), \
                    TRANSFER_GUARD.disallow("learner.harvest"), \
                    HOST_TRANSFERS.allowed("learner.result_fetch"):
                if self._lh:
                    # the learnhealth diag rides the same flat fetch
                    flat = np.asarray(jax.device_get(loss))
                    loss, diag = float(flat[0]), flat[1:]
                else:
                    loss, diag = float(jax.device_get(loss)), None
                # loss is replicated (addressable everywhere); priorities
                # are dp-sharded, so under a mesh read back only this
                # host's rows — they pair with the idxes this host sampled
                if self.mesh is not None:
                    from r2d2_tpu.parallel.distributed import local_rows

                    priorities = local_rows(priorities)
                else:
                    priorities = np.asarray(jax.device_get(priorities))
            self._note_results(np.asarray([loss]), diag, strict=False)
            losses.append(loss)
            self.env_steps = int(host.get("env_steps", self.env_steps))
            if priority_sink is not None:
                priority_sink(host["idxes"], priorities,
                              host["block_ptr"], loss)

        # track the update count host-side: self.num_updates is a device
        # fetch of state.step — one interconnect round trip per read, so
        # reading it every iteration would serialise the loop on latency
        updates = self.num_updates
        # NOTE: this pending/harvest/drain shape mirrors _superstep_loop
        # (the device-replay driver) deliberately rather than sharing it:
        # this loop is queue-fed with per-item host metadata and a
        # collective batch-exhaustion break, which don't fit the
        # gate/sample contract there.  A pipeline-logic fix in one loop
        # likely applies to the other — check both.
        pending: deque = deque()
        try:
            while updates < target:
                if should_stop():
                    break
                with tracer.span("learner.batch_wait"):
                    item = next_item()
                # batch exhaustion is also a host-local condition (the
                # host-local stop() can fire between the synced
                # should_stop() and the queue read) — sync it too, or one
                # host breaks out while its peers block in the collective
                # step / the _save allgather
                if any_host(item is None):
                    break
                dev_batch, host = item
                with tracer.span("learner.step_dispatch"), \
                        TRANSFER_GUARD.disallow("learner.dispatch"):
                    if self._lh:
                        (self.state, loss, priorities,
                         diag) = self._step_fn(self.state, dev_batch)
                        # fold loss + diag into ONE flat replicated
                        # vector so the harvest's result fetch count is
                        # unchanged by the diagnostics
                        loss = jnp.concatenate(
                            [jnp.reshape(loss, (1,)), diag])
                    else:
                        self.state, loss, priorities = self._step_fn(
                            self.state, dev_batch)
                    for arr in (loss, priorities):
                        try:
                            arr.copy_to_host_async()  # explicit: exempt
                        except Exception:
                            pass  # prefetch failure: harvest pays the trip
                pending.append((host, loss, priorities))
                while len(pending) > cfg.superstep_pipeline:
                    harvest(pending.popleft())

                updates += 1
                if (self.param_store is not None
                        and updates % cfg.weight_publish_interval == 0):
                    # spanned: cadence work is the classic source of
                    # learner hiccups, and an armed trace capture should
                    # show a publish/save slice, not an unexplained gap
                    with tracer.span("learner.publish"):
                        self._publish()
                if (self.checkpointer is not None
                        and updates % cfg.save_interval == 0):
                    with tracer.span("learner.checkpoint_save"):
                        self._save(updates, t0)
            while pending:
                harvest(pending.popleft())
        finally:
            done.set()
            if pf_sup is not None:
                # stop supervision (cancels any pending backoff timer) and
                # reap the prefetch thread; it exits at its next done poll
                pf_sup.join_all(timeout=2.0)

        if self.checkpointer is not None:
            self._save(self.num_updates, t0)
        mins = self.start_minutes + (time.time() - t0) / 60.0
        if jax.process_count() > 1:
            from r2d2_tpu.parallel.distributed import sync_counter

            self.env_steps = sync_counter(self.env_steps, reduce="sum")
        return dict(
            num_updates=self.num_updates,
            env_steps=self.env_steps,
            minutes=mins,
            mean_loss=float(np.mean(losses)) if losses else float("nan"),
        )

    def run_device(self, buffer: Any, ring: Any,
                   priority_sink: Optional[PrioritySink] = None,
                   max_steps: Optional[int] = None,
                   stop: Optional[Callable[[], bool]] = None,
                   tracer: Optional[Any] = None) -> Dict[str, float]:
        """Drive training from the device-resident replay ring
        (replay/device_ring.py): ``superstep_k`` optimizer steps per
        dispatch, batches gathered in-graph, one small H2D (index bundles)
        and one small D2H (stacked losses+priorities) per super-step.

        Replaces the queued host staging of :meth:`run` when
        ``cfg.device_replay`` — batch bytes never cross the host↔device
        boundary, so throughput is immune to interconnect latency (the
        reference's `.to(device)` per step, worker.py:330-342, is the cost
        this removes).

        The update counter advances by k per dispatch, so the loop may
        overshoot ``training_steps`` by up to k-1 updates.

        Under a mesh (single process): the ring is mesh-replicated (or
        dp-sharded, ``ring.layout``) and the super-step is the table-driven
        pjit program (parallel/sharding.pjit_super_step) — index bundles
        shard their batch axis over dp, grads psum over ICI.

        Multi-host: dispatches to :meth:`_run_device_multihost` — each
        host owns the slot slabs of its dp groups (a dp-layout ring over
        its *local* submesh) and the global ring view is stitched from
        the per-host device shards with zero data movement.
        """
        cfg = self.cfg
        if jax.process_count() > 1 and not cfg.in_graph_per:
            return self._run_device_multihost(buffer, ring, priority_sink,
                                              max_steps, stop, tracer)
        if tracer is None:
            from r2d2_tpu.utils.trace import Tracer
            tracer = Tracer()
        from r2d2_tpu.parallel.sharding import pjit_super_step

        k = cfg.superstep_k
        t0 = time.time()
        updates = self.num_updates
        target = cfg.training_steps if max_steps is None else updates + max_steps
        if cfg.in_graph_per:
            # single-process (any ring layout) AND multi-host (dp slabs):
            # the drivetrain handles both — see its docstring
            return self._run_device_in_graph_per(buffer, ring, k, target,
                                                 t0, stop, tracer)
        # AOT-compile outside the buffer lock: the first dispatch happens
        # under it (sample_meta couples sampling + dispatch), and tracing a
        # fresh jit there would stall actor add()s for the whole compile
        super_fn = pjit_super_step(
            cfg, self.net, self.table, k, state_template=self.state,
            layout=getattr(ring, "layout", "replicated"))
        B = cfg.batch_size
        # Lower from avals, not live ring handles: actor commits donate
        # the ring arrays (DeviceRing._write_slot), so a concurrent
        # commit could delete a handle mid-lowering.  Metadata is
        # snapshotted under the buffer lock; lowering touches no device
        # memory (same discipline as _run_device_in_graph_per).
        with buffer.lock:
            snap_avals = _aval_tree((self.state, ring.snapshot()))
        try:
            super_fn = super_fn.lower(
                *snap_avals,
                np.zeros((k, B, 6), np.int32),
                np.zeros((k, B), np.float32)).compile()
        except Exception:
            # some plugin backends lack the AOT API; the jit wrapper
            # compiles at first call instead (stalling the lock once)
            pass
        compiled = super_fn

        losses_hist: deque = deque(maxlen=100)  # bounded, see run()

        def prepare(item):
            """Called at enqueue time: dispatch the (tiny) result flatten
            and start its device→host copy NOW, so by harvest time —
            ``superstep_pipeline`` dispatches later — the bytes are already
            host-resident and the blocking fetch is cheap.  Without this
            the transfer would only start inside harvest, putting one full
            interconnect round trip on the loop per dispatch regardless of
            pipeline depth."""
            meta, losses, priorities = item
            if self._lh:
                # the learnhealth diag rows ride the SAME flat result
                # vector — one fetch per dispatch, unchanged
                (losses, diags) = losses
                flat = jnp.concatenate([losses, priorities.reshape(-1),
                                        diags.reshape(-1)])
            else:
                flat = jnp.concatenate([losses, priorities.reshape(-1)])
            try:
                flat.copy_to_host_async()
            except Exception:
                pass  # any prefetch failure: harvest pays the round trip
            return (meta, flat)

        def harvest(item) -> None:
            """Fetch a finished super-step's results and feed them back."""
            meta, flat = item
            with tracer.span("learner.result_sync"), \
                    TRANSFER_GUARD.disallow("learner.harvest"):
                # one D2H fetch for everything the host needs (usually
                # already prefetched by prepare())
                with HOST_TRANSFERS.allowed("learner.result_fetch"):
                    flat = np.asarray(jax.device_get(flat))
            diags = (flat[k + k * B:].reshape(k, -1) if self._lh else None)
            self._feed_back(meta, flat[:k], flat[k:k + k * B].reshape(k, B),
                            priority_sink, losses_hist, diags)

        def dispatch(ints, weights):
            with tracer.span("learner.step_dispatch"), \
                    TRANSFER_GUARD.disallow("learner.dispatch"):
                # the dispatch's declared H2D: the sampled idx/weight rows
                with HOST_TRANSFERS.allowed("learner.dispatch_put"):
                    d_ints = jnp.asarray(ints)
                    d_w = jnp.asarray(weights)
                out = compiled(self.state, ring.snapshot(), d_ints, d_w)
                if self._lh:
                    st, losses, priorities, diags = out
                    return st, (losses, diags), priorities
                return out

        def sample():
            with tracer.span("learner.sample_meta"):
                return buffer.sample_meta(k, dispatch=dispatch)

        self._superstep_loop(k, target, t0, self._ready_gate(buffer, stop),
                             sample, harvest, prepare=prepare,
                             tracer=tracer)
        return self._finish_device_run(losses_hist, t0)

    def _ready_gate(self, buffer, stop):
        """The device drivetrains' shared gate(): stop-aware, waits for
        ``learning_starts``."""
        def gate() -> str:
            if stop is not None and stop():
                return "break"
            return "go" if buffer.ready else "wait"
        return gate

    def _collective_gate(self, buffer, stop):
        """Multi-host gate(): the dispatch is a lockstep SPMD launch, so
        the decision to make it must be collective.  One allgather
        carries both flags (min-reduced, so "stop" travels inverted)."""
        from r2d2_tpu.parallel.distributed import sync_min_array

        def gate() -> str:
            flags = sync_min_array(np.array([
                0.0 if (stop is not None and stop()) else 1.0,
                1.0 if buffer.ready else 0.0,
            ]))
            if flags[0] == 0.0:   # some host wants to stop
                return "break"
            if flags[1] == 0.0:   # some host's buffer not ready
                return "wait"
            return "go"
        return gate

    def _finish_device_run(self, losses_hist, t0: float) -> Dict[str, float]:
        """Shared epilogue of the device drivetrains: final save + summary."""
        if self.checkpointer is not None:
            self._save(self.num_updates, t0)
        mins = self.start_minutes + (time.time() - t0) / 60.0
        if jax.process_count() > 1:
            from r2d2_tpu.parallel.distributed import sync_counter

            self.env_steps = sync_counter(self.env_steps, reduce="sum")
        return dict(
            num_updates=self.num_updates,
            env_steps=self.env_steps,
            minutes=mins,
            mean_loss=(float(np.mean(losses_hist))
                       if losses_hist else float("nan")),
        )

    def _run_device_in_graph_per(self, buffer, ring, k: int, target: int,
                                 t0: float, stop, tracer
                                 ) -> Dict[str, float]:
        """Device-PER drivetrain (``cfg.in_graph_per``): sampling, IS
        weights, and priority feedback all execute inside the super-step
        (learner/step.py:make_in_graph_per_super_step), so each dispatch
        is ONE H2D scalar (the seed) and ONE small D2H (the losses, for
        logging) — the ``learner.result_sync`` priority round trip of
        :meth:`run_device` (~99 ms/harvest on the tunneled chip,
        MEASURE_TPU_r04.md) leaves the training path entirely, and the k
        inner steps sample from priorities the previous inner step wrote
        (tighter feedback than the reference's 8+4-batch queue lag,
        worker.py:300-316).

        The priorities array is a donated carry: the dispatch consumes
        the ring's current handle and the returned one is stored back
        before the buffer lock is released, so actor block commits
        (``DeviceRing.commit_per``, same lock) always target the newest
        generation.  Any mesh layout runs the SAME table-driven pjit step
        (parallel/sharding.pjit_in_graph_per_super_step): the stratified
        draw is global regardless of layout — under a dp-sharded ring the
        PER leaves shard with the slabs and GSPMD inserts the collectives,
        so over the same ring content a dp-sharded run draws the same
        strata as a single-device one (pinned by
        test_in_graph_per_dp_layout_matches_single_device).

        Multi-host (ring layout "dp" over each host's local submesh, as
        built by train.py): per dispatch the global ring + PER views are
        stitched from the per-host device shards with zero data movement
        (``assemble_global``), every process launches the same SPMD
        super-step in lockstep (collective gate), and the returned
        global priorities array — whose addressable shards are exactly
        this host's slabs, updated in place — is relabelled back to the
        local view and stored, so the host's actor commits keep writing
        the newest generation.  The reference's priority feedback
        (worker.py:242-276) at pod scale, with zero host round trips."""
        cfg = self.cfg
        multihost = jax.process_count() > 1
        layout = getattr(ring, "layout", "replicated")
        from r2d2_tpu.parallel.sharding import pjit_in_graph_per_super_step

        super_fn = pjit_in_graph_per_super_step(
            cfg, self.net, self.table, k, state_template=self.state,
            layout=layout)

        if multihost:
            from r2d2_tpu.parallel.distributed import assemble_global

            if layout != "dp":
                raise RuntimeError(
                    "multi-host in_graph_per needs a dp-layout ring "
                    "(train.py builds one per host over its local "
                    "submesh)")
            K = cfg.seqs_per_block
            bpg = ring.blocks_per_group
            GB = self.mesh.shape["dp"] * bpg       # global slot count
            gsh_ring = self.table.ring_shardings("dp")
            gsh_per = self.table.per_shardings("dp")
            # the ring's own table IS the local-submesh table train._build
            # gave it — resolve the local prios layout through it rather
            # than rebuilding one that could drift from the ring's
            lsh_prios = ring.table.per_shardings("dp")["prios"]
            local_leaves = cfg.num_blocks * K

            def ring_args():
                """Global views of the per-host shards (metadata-only
                stitch; caller holds the buffer lock)."""
                meta = ring.per_meta()
                per = assemble_global(
                    {"seq_meta": gsh_per["seq_meta"],
                     "first": gsh_per["first"]},
                    {"seq_meta": meta["seq_meta"], "first": meta["first"]},
                    GB)
                prios_v = assemble_global(
                    {"prios": gsh_per["prios"]},
                    {"prios": ring.take_prios()}, GB * K)["prios"]
                return (assemble_global(gsh_ring, ring.snapshot(), GB),
                        prios_v, per["seq_meta"], per["first"])

            def store_prios(new_global):
                """Relabel the returned global priorities to this host's
                local view — same device buffers, local coordinates —
                so commit_per targets the newest generation."""
                ring.put_prios(jax.make_array_from_single_device_arrays(
                    (local_leaves,), lsh_prios,
                    [s.data for s in new_global.addressable_shards]))

            gate = self._collective_gate(buffer, stop)
        else:
            def ring_args():
                meta = ring.per_meta()
                return (ring.snapshot(), ring.take_prios(),
                        meta["seq_meta"], meta["first"])

            store_prios = ring.put_prios
            gate = self._ready_gate(buffer, stop)

        seed0 = jnp.asarray(0, jnp.uint32)
        # AOT-compile from avals, not live ring handles: actor threads
        # are already committing blocks, and a concurrent commit_per
        # donates the priorities handle — lowering from the live array
        # could read a deleted buffer.  Metadata (shape/dtype/sharding)
        # is snapshotted under the buffer lock; the lowering itself then
        # touches no device memory.
        with buffer.lock:
            avals = _aval_tree((self.state, *ring_args(), seed0))
        try:
            super_fn = super_fn.lower(*avals).compile()
        except Exception:
            pass  # no AOT API: the jit wrapper compiles at first call
        compiled = super_fn
        losses_hist: deque = deque(maxlen=100)
        dispatch_no = [0]

        def sample():
            with tracer.span("learner.step_dispatch"), \
                    TRANSFER_GUARD.disallow("learner.dispatch"):
                with buffer.lock:
                    # fold_in(PRNGKey(cfg.seed), idx) happens in-graph;
                    # the u32 counter wraps harmlessly after 2^32.
                    # Multi-host: every process dispatches in lockstep
                    # (collective gate), so the counters — and with them
                    # the in-graph sampling streams — stay identical.
                    # ONE declared H2D per dispatch: the index scalar
                    with HOST_TRANSFERS.allowed("learner.dispatch_put"):
                        idx = jnp.asarray(
                            dispatch_no[0] & 0xFFFFFFFF, jnp.uint32)
                    dispatch_no[0] += 1
                    out = compiled(self.state, *ring_args(), idx)
                    if self._lh:
                        st, new_prios, losses, diags = out
                        losses = (losses, diags)
                    else:
                        st, new_prios, losses = out
                    store_prios(new_prios)
                    env_steps = buffer.env_steps
            # losses ride the pipeline; priorities never leave the device
            return dict(dispatched=(st, losses, None),
                        env_steps=env_steps)

        def prepare(item):
            meta, losses, _ = item
            if self._lh:
                # fold losses + diag rows into the dispatch's ONE D2H
                losses, diags = losses
                losses = jnp.concatenate([losses, diags.reshape(-1)])
            try:
                losses.copy_to_host_async()
            except Exception:
                pass  # prefetch failure: harvest pays the round trip
            return (meta, losses)

        def harvest(item) -> None:
            meta, losses = item
            with tracer.span("learner.result_sync"), \
                    TRANSFER_GUARD.disallow("learner.harvest"):
                with HOST_TRANSFERS.allowed("learner.result_fetch"):
                    flat = np.asarray(jax.device_get(losses))
            losses_np = flat[:k]
            diags = flat[k:].reshape(k, -1) if self._lh else None
            self._note_results(losses_np, diags)
            self.env_steps = int(meta["env_steps"])
            buffer.note_updates(losses_np.shape[0], losses_np.sum())
            losses_hist.extend(losses_np.tolist())

        self._superstep_loop(k, target, t0, gate, sample, harvest,
                             prepare=prepare, tracer=tracer)
        return self._finish_device_run(losses_hist, t0)

    def _superstep_loop(self, k: int, target: int, t0: float,
                        gate: Callable[[], str],
                        sample: Callable[[], Dict[str, Any]],
                        harvest: Callable[[Any], None],
                        prepare: Optional[Callable[[Any], Any]] = None,
                        tracer: Optional[Any] = None) -> None:
        """The pipelined super-step driver shared by the single-process
        and multi-host device-replay paths: keep up to
        ``cfg.superstep_pipeline`` dispatches in flight beyond the one
        being harvested.  ``prepare`` runs at enqueue time and starts the
        result D2H transfer immediately (copy_to_host_async), so a
        harvest ``superstep_pipeline`` dispatches later finds the bytes
        host-resident — the dispatch cadence is then bounded by device
        compute, not by the interconnect round trip (~100 ms on a
        tunneled chip, worse when the host core is contended).  On a
        backend without async host copies the harvest degrades to one
        blocking round trip per dispatch.  Priority feedback lags
        ≤ (pipeline+1)·k updates — at the defaults, comparable to the
        reference's 8-batch queue + 4-batch staging lag
        (worker.py:300-316).  Cadences fire on interval crossings
        (updates advance by k per dispatch).

        ``gate()`` → "break" | "wait" | "go" decides each iteration;
        ``sample()`` must return a meta dict whose ``dispatched`` holds
        the in-flight (state, losses, priorities).
        """
        cfg = self.cfg
        updates = self.num_updates
        pending: deque = deque()
        while updates < target:
            g = gate()
            if g == "break":
                break
            if g == "wait":
                time.sleep(0.02)
                continue
            meta = sample()
            self.state, losses, priorities = meta["dispatched"]
            item = (meta, losses, priorities)
            pending.append(prepare(item) if prepare is not None else item)
            while len(pending) > cfg.superstep_pipeline:
                harvest(pending.popleft())

            prev, updates = updates, updates + k
            span = (tracer.span if tracer is not None
                    else contextlib.nullcontext)
            if (self.param_store is not None
                    and updates // cfg.weight_publish_interval
                    > prev // cfg.weight_publish_interval):
                with span("learner.publish"):
                    self._publish()
            if (self.checkpointer is not None
                    and updates // cfg.save_interval
                    > prev // cfg.save_interval):
                with span("learner.checkpoint_save"):
                    self._save(updates, t0)
        while pending:
            harvest(pending.popleft())

    def _feed_back(self, meta, losses_np: np.ndarray, prios_np: np.ndarray,
                   priority_sink: Optional[PrioritySink],
                   losses_hist: deque,
                   diags_np: Optional[np.ndarray] = None) -> None:
        """Route one harvested super-step's results to the host side."""
        self._note_results(losses_np, diags_np)
        self.env_steps = int(meta["env_steps"])
        if priority_sink is not None:
            for j in range(losses_np.shape[0]):
                priority_sink(meta["idxes"][j], prios_np[j],
                              meta["block_ptr"], float(losses_np[j]))
        losses_hist.extend(losses_np.tolist())

    def _run_device_multihost(self, buffer: Any, ring: Any,
                              priority_sink: Optional[PrioritySink],
                              max_steps: Optional[int],
                              stop: Optional[Callable[[], bool]],
                              tracer: Optional[Any]) -> Dict[str, float]:
        """Device-resident replay across hosts — the pod-scale data plane.

        Layout: the global ring's slot axis is the concatenation of every
        host's slabs.  Host h's ReplayBuffer/DeviceRing (built over its
        *local* submesh, layout="dp") owns the dp groups its devices hold;
        its writes and sampling are process-local.  Per super-step, every
        host:

        1. agrees the fleet is ready / not stopped (sync_counter — the
           dispatch below is a lockstep SPMD launch, so the decision to
           make it must be collective);
        2. samples its rows (raw per-group inclusion densities), agrees
           the global min density (sync_min_array) so IS weights keep the
           reference's min-of-the-whole-batch normalisation across the
           pod, offsets its slot indices into global coordinates, and
           uploads its rows of the (k, B, 6) bundle;
        3. stitches the global ring view from the per-host device shards
           (assemble_global — metadata only, no data movement) and
           dispatches the SAME sharded super-step as the single-process
           dp layout;
        4. harvests its dp rows of the priorities (local_rows axis=1) and
           feeds its own buffer — feedback never crosses hosts.

        Batch bytes never touch host RAM, and never cross DCN: the sampled
        rows reference only their own host's slabs (sample_meta's
        per-group quotas), so GSPMD's partitioned gather stays local in
        practice; only grad psums (ICI/DCN) and the tiny index/min-density
        collectives leave the host.  Steps 2-3 run under the buffer lock
        (the device_ring concurrency contract: a ring write donates the
        buffers a pending dispatch would read).
        """
        import jax.numpy as _jnp

        from jax.sharding import NamedSharding, PartitionSpec as P

        from r2d2_tpu.parallel.distributed import (
            assemble_global, global_from_local_rows, host_batch_size,
            local_rows, owned_dp_groups, sync_min_array)
        from r2d2_tpu.parallel.sharding import pjit_super_step

        cfg = self.cfg
        assert self.mesh is not None, "multi-host device replay needs a mesh"
        if tracer is None:
            from r2d2_tpu.utils.trace import Tracer
            tracer = Tracer()

        k = cfg.superstep_k
        t0 = time.time()
        updates = self.num_updates
        target = (cfg.training_steps if max_steps is None
                  else updates + max_steps)

        dp_local = ring.num_groups
        bpg = ring.blocks_per_group
        owned = owned_dp_groups(self.mesh)
        if owned.stop - owned.start != dp_local:
            raise RuntimeError(
                f"ring has {dp_local} local groups but this process owns "
                f"{owned.stop - owned.start} dp groups of the global mesh")
        slot_offset = owned.start * bpg
        global_blocks = self.mesh.shape["dp"] * bpg
        B, B_host = cfg.batch_size, host_batch_size(cfg, self.mesh)
        beta = cfg.importance_sampling_exponent

        super_fn = pjit_super_step(cfg, self.net, self.table, k,
                                   state_template=self.state, layout="dp")
        ring_sh = self.table.ring_shardings("dp")
        dp_b = NamedSharding(self.mesh, P(None, "dp"))
        try:
            # AOT with shape specs — the global ring is far too big to
            # zero-fill host-side just to trace
            ring_spec = {
                kk: jax.ShapeDtypeStruct((global_blocks, *v.shape[1:]),
                                         v.dtype, sharding=ring_sh[kk])
                for kk, v in ring.snapshot().items()}
            super_fn = super_fn.lower(
                self.state, ring_spec,
                jax.ShapeDtypeStruct((k, B, 6), _jnp.int32, sharding=dp_b),
                jax.ShapeDtypeStruct((k, B), _jnp.float32, sharding=dp_b),
            ).compile()
        except Exception:
            pass  # backend without AOT: first dispatch compiles
        compiled = super_fn

        losses_hist: deque = deque(maxlen=100)  # bounded, see run()

        def prepare(item):
            """Start the result D2H copies at enqueue time (addressable
            shards only) so the later harvest finds them host-resident —
            see :meth:`_superstep_loop`."""
            _, losses, priorities = item
            for arr in (losses, priorities):
                try:
                    arr.copy_to_host_async()
                except Exception:
                    pass  # any prefetch failure: harvest pays the trip
            return item

        def harvest(item) -> None:
            # dispatch() folded losses (+ learnhealth diag rows) into
            # one flat replicated vector — ONE fetch either way
            meta, flat, priorities = item
            with tracer.span("learner.result_sync"):
                flat_np = np.asarray(jax.device_get(flat))
                prios_np = local_rows(priorities, axis=1)       # (k, B_host)
            losses_np = flat_np[:k]
            diags_np = flat_np[k:].reshape(k, -1) if self._lh else None
            self._feed_back(meta, losses_np, prios_np, priority_sink,
                            losses_hist, diags_np)

        gate = self._collective_gate(buffer, stop)

        def dispatch(ints, q):
            """Runs under the buffer lock (sample_meta couples sampling
            with dispatch).  All hosts execute this in lockstep."""
            with tracer.span("learner.step_dispatch"):
                gmin = sync_min_array(q.min(axis=1))           # (k,)
                w = (q / gmin[:, None]) ** (-beta)
                g_ints = ints.astype(np.int32, copy=True)
                g_ints[:, :, 0] += slot_offset
                g_ints = global_from_local_rows(
                    dp_b, g_ints, (k, B, 6), axis=1,
                    offset=owned.start * (B // self.mesh.shape["dp"]))
                g_w = global_from_local_rows(
                    dp_b, w.astype(np.float32), (k, B), axis=1,
                    offset=owned.start * (B // self.mesh.shape["dp"]))
                ring_view = assemble_global(ring_sh, ring.snapshot(),
                                            global_blocks)
                out = compiled(self.state, ring_view, g_ints, g_w)
                if self._lh:
                    # fold losses + diag rows into ONE flat replicated
                    # vector so the harvest's result sync stays a
                    # single fetch with diagnostics armed
                    st, losses, priorities, diags = out
                    return (st,
                            jnp.concatenate([losses, diags.reshape(-1)]),
                            priorities)
                return out

        def sample():
            with tracer.span("learner.sample_meta"):
                return buffer.sample_meta(k, batch_size=B_host,
                                          dispatch=dispatch,
                                          raw_densities=True)

        self._superstep_loop(k, target, t0, gate, sample, harvest,
                             prepare=prepare, tracer=tracer)
        return self._finish_device_run(losses_hist, t0)

    def _save(self, updates: int, t0: float) -> None:
        if updates in self._saved_steps:
            # THIS RUN already saved this step completely (the epilogue
            # save lands on the same step as the last cadence save
            # whenever training_steps % save_interval == 0).  Re-saving
            # would have orbax delete-and-rewrite the payload under a
            # sidecar that still marks it complete — a follow-mode
            # evaluator restoring that step mid-rewrite sees a torn
            # checkpoint.  Tracked per-run (not via has_meta): a fresh
            # run reusing an old checkpoint dir must still overwrite the
            # previous run's steps, and every pod process makes the same
            # local decision so orbax's save barriers stay in sync.
            return
        minutes = self.start_minutes + (time.time() - t0) / 60.0
        if jax.process_count() > 1:
            # Gather mp-sharded leaves that may live on other hosts by
            # resharding the state to fully-replicated IN-GRAPH (XLA
            # allgathers over ICI) — the host-side process_allgather can't
            # express arbitrary shardings (it only tiles along axis 0).
            # Every process then calls checkpointer.save: orbax is
            # multihost-aware (internal sync barriers, primary-host-only
            # file writes), so skipping non-zero processes here would
            # desync its barriers.  The meta sidecar is process-0-gated
            # inside Checkpointer.save.
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self.mesh, PartitionSpec())
            state = jax.device_get(
                jax.jit(lambda s: s, out_shardings=rep)(self.state))
        else:
            state = jax.device_get(self.state)
        from r2d2_tpu.checkpoint import arch_meta

        self.checkpointer.save(updates, state,
                               meta=dict(env_steps=self.env_steps,
                                         minutes=minutes,
                                         game=self.cfg.game_name,
                                         **arch_meta(self.cfg)))
        self._saved_steps.add(updates)
