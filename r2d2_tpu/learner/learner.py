"""Learner host loop: the drivetrain around the jitted train step.

Capability-parity with the reference learner's ``run`` (worker.py:300-381):
staged batch prefetch, periodic weight publication, periodic checkpointing.
Target-net sync is already *inside* the jitted step (in-graph select), so
the host loop only drives data and cadences.

TPU-first redesign:
- The prefetch thread moves batches host→device (``jax.device_put`` with
  the mesh sharding) **ahead of** the compute stream, so H2D overlaps the
  previous step — the async analogue of the reference's host-side staging
  list (worker.py:309-316).
- Weight publication is a versioned immutable snapshot (ParamStore), not a
  shared-memory mutation (worker.py:306-307).
- Multi-device: pass a Mesh and the same loop drives the GSPMD-sharded
  step; the loop code is identical.
- Checkpointing saves the full TrainState with resume (checkpoint.py),
  beating the reference's save-only ``torch.save`` (worker.py:380-381).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.checkpoint import Checkpointer
from r2d2_tpu.config import Config
from r2d2_tpu.learner.step import TrainState, jit_train_step
from r2d2_tpu.models.network import R2D2Network
from r2d2_tpu.parallel.mesh import (
    DEVICE_BATCH_KEYS,
    batch_sharding,
    replicate_state,
    sharded_train_step,
)
from r2d2_tpu.utils.store import ParamStore

# batch_source() -> host batch dict (blocking); returns None to stop early.
BatchSource = Callable[[], Optional[Dict[str, np.ndarray]]]
# priority_sink(idxes, priorities, old_ptr, loss)
PrioritySink = Callable[[np.ndarray, np.ndarray, int, float], None]


class Learner:
    def __init__(self, cfg: Config, net: R2D2Network, state: TrainState,
                 mesh: Optional[Any] = None,
                 param_store: Optional[ParamStore] = None,
                 checkpointer: Optional[Checkpointer] = None,
                 start_env_steps: int = 0, start_minutes: float = 0.0):
        self.cfg = cfg
        self.net = net
        self.mesh = mesh
        self.param_store = param_store
        self.checkpointer = checkpointer
        self.env_steps = start_env_steps
        self.start_minutes = start_minutes

        if mesh is not None:
            self._step_fn = sharded_train_step(cfg, net, mesh,
                                               state_template=state)
            self._shardings = batch_sharding(mesh)
            self.state = replicate_state(mesh, state)
        else:
            self._step_fn = jit_train_step(cfg, net)
            self._shardings = None
            self.state = state

        if self.param_store is not None:
            self._publish()

    def _publish(self) -> None:
        # deep-copy: the jitted step donates the state, so a published
        # snapshot must not alias state buffers or the next update would
        # delete it out from under the actors
        self.param_store.publish(
            jax.tree.map(jnp.copy, self.state.params))

    @property
    def num_updates(self) -> int:
        return int(jax.device_get(self.state.step))

    def _stage(self, batch: Dict[str, np.ndarray]
               ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Split host bookkeeping from device fields and start the H2D copy.

        Multi-host: each process's batch holds only its dp rows, assembled
        into one global sharded array (parallel/distributed.py) — batch
        data never crosses DCN."""
        host = {k: batch[k] for k in batch if k not in DEVICE_BATCH_KEYS}
        if self._shardings is not None:
            if jax.process_count() > 1:
                from r2d2_tpu.parallel.distributed import host_local_batch

                dev = host_local_batch(
                    self.mesh, {k: batch[k] for k in DEVICE_BATCH_KEYS},
                    shardings=self._shardings)
            else:
                dev = {k: jax.device_put(batch[k], self._shardings[k])
                       for k in DEVICE_BATCH_KEYS}
        else:
            dev = {k: jax.device_put(batch[k]) for k in DEVICE_BATCH_KEYS}
        return dev, host

    def run(self, batch_source: BatchSource,
            priority_sink: Optional[PrioritySink] = None,
            max_steps: Optional[int] = None,
            stop: Optional[Callable[[], bool]] = None,
            tracer: Optional[Any] = None) -> Dict[str, float]:
        """Drive training until ``cfg.training_steps`` (or ``max_steps`` more
        updates, or ``stop()``).  Returns summary metrics.

        ``tracer`` (utils/trace.Tracer) records per-stage spans: batch wait,
        jitted step dispatch, and the device→host result sync."""
        cfg = self.cfg
        if tracer is None:
            from r2d2_tpu.utils.trace import Tracer
            tracer = Tracer()
        t0 = time.time()
        target = cfg.training_steps if max_steps is None else (
            self.num_updates + max_steps)

        # prefetch_batches == 0 → fully synchronous staging (deterministic;
        # used by train_sync and tests).  Otherwise a daemon thread keeps up
        # to ``prefetch_batches`` device-resident batches ahead of compute.
        if cfg.prefetch_batches > 0:
            staged: "queue.Queue" = queue.Queue(maxsize=cfg.prefetch_batches)
            done = threading.Event()

            def prefetch():
                try:
                    while not done.is_set():
                        batch = batch_source()
                        item = None if batch is None else self._stage(batch)
                        # bounded put that re-checks done: when the learner
                        # stops consuming with the queue full, the thread
                        # must exit rather than park in put() forever (and
                        # pin device-resident staged batches)
                        while not done.is_set():
                            try:
                                staged.put(item, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if batch is None:
                            return
                finally:
                    # exception-safe end-of-stream sentinel so the consumer
                    # can never block on a dead producer
                    try:
                        staged.put_nowait(None)
                    except queue.Full:
                        pass

            pf = threading.Thread(target=prefetch, daemon=True,
                                  name="prefetch")
            pf.start()

            def next_item():
                # timeout + liveness check: a producer that died with the
                # queue full could not even enqueue its sentinel
                while True:
                    try:
                        return staged.get(timeout=0.5)
                    except queue.Empty:
                        if not pf.is_alive():
                            return None
        else:
            done = threading.Event()

            def next_item():
                batch = batch_source()
                return None if batch is None else self._stage(batch)

        # multi-host: stop decisions (wall-clock deadlines, fabric
        # failures) are host-local, but leaving the step loop early on one
        # host would deadlock the others' collectives — sync the flag so
        # all hosts break at the same step boundary
        if jax.process_count() > 1:
            from r2d2_tpu.parallel.distributed import sync_counter

            def any_host(flag: bool) -> bool:
                """True iff the condition holds on any host (collective —
                every host must call it once per loop iteration)."""
                return sync_counter(int(flag), reduce="max") > 0
        else:
            def any_host(flag: bool) -> bool:
                return flag

        def should_stop() -> bool:
            return any_host(bool(stop()) if stop is not None else False)

        losses = []
        try:
            while self.num_updates < target:
                if should_stop():
                    break
                with tracer.span("learner.batch_wait"):
                    item = next_item()
                # batch exhaustion is also a host-local condition (the
                # host-local stop() can fire between the synced
                # should_stop() and the queue read) — sync it too, or one
                # host breaks out while its peers block in the collective
                # step / the _save allgather
                if any_host(item is None):
                    break
                dev_batch, host = item
                with tracer.span("learner.step_dispatch"):
                    self.state, loss, priorities = self._step_fn(self.state,
                                                                 dev_batch)
                # one device→host sync per step: loss + priorities together.
                # loss is replicated (addressable everywhere); priorities
                # are dp-sharded, so under a mesh read back only this
                # host's rows — they pair with the idxes this host sampled
                with tracer.span("learner.result_sync"):
                    loss = float(jax.device_get(loss))
                    if self.mesh is not None:
                        from r2d2_tpu.parallel.distributed import local_rows

                        priorities = local_rows(priorities)
                    else:
                        priorities = np.asarray(jax.device_get(priorities))
                losses.append(loss)
                self.env_steps = int(host.get("env_steps", self.env_steps))

                if priority_sink is not None:
                    priority_sink(host["idxes"], priorities,
                                  host["block_ptr"], loss)

                updates = self.num_updates
                if (self.param_store is not None
                        and updates % cfg.weight_publish_interval == 0):
                    self._publish()
                if (self.checkpointer is not None
                        and updates % cfg.save_interval == 0):
                    self._save(updates, t0)
        finally:
            done.set()

        if self.checkpointer is not None:
            self._save(self.num_updates, t0)
        mins = self.start_minutes + (time.time() - t0) / 60.0
        if jax.process_count() > 1:
            from r2d2_tpu.parallel.distributed import sync_counter

            self.env_steps = sync_counter(self.env_steps, reduce="sum")
        return dict(
            num_updates=self.num_updates,
            env_steps=self.env_steps,
            minutes=mins,
            mean_loss=float(np.mean(losses[-100:])) if losses else float("nan"),
        )

    def run_device(self, buffer: Any, ring: Any,
                   priority_sink: Optional[PrioritySink] = None,
                   max_steps: Optional[int] = None,
                   stop: Optional[Callable[[], bool]] = None,
                   tracer: Optional[Any] = None) -> Dict[str, float]:
        """Drive training from the device-resident replay ring
        (replay/device_ring.py): ``superstep_k`` optimizer steps per
        dispatch, batches gathered in-graph, one small H2D (index bundles)
        and one small D2H (stacked losses+priorities) per super-step.

        Replaces the queued host staging of :meth:`run` when
        ``cfg.device_replay`` — batch bytes never cross the host↔device
        boundary, so throughput is immune to interconnect latency (the
        reference's `.to(device)` per step, worker.py:330-342, is the cost
        this removes).  Single-process only; multi-host runs use
        :meth:`run` (each host's ring would hold different data).

        The update counter advances by k per dispatch, so the loop may
        overshoot ``training_steps`` by up to k-1 updates.

        Under a mesh (single process): the ring is mesh-replicated and the
        super-step is GSPMD-sharded (parallel.mesh.sharded_super_step) —
        index bundles shard their batch axis over dp, grads psum over ICI.
        """
        cfg = self.cfg
        assert jax.process_count() == 1, (
            "device_replay is per-process; multi-host runs use host "
            "staging (Learner.run)")
        if tracer is None:
            from r2d2_tpu.utils.trace import Tracer
            tracer = Tracer()
        from r2d2_tpu.learner.step import make_super_step

        k = cfg.superstep_k
        t0 = time.time()
        updates = self.num_updates
        target = cfg.training_steps if max_steps is None else updates + max_steps
        # AOT-compile outside the buffer lock: the first dispatch happens
        # under it (sample_meta couples sampling + dispatch), and tracing a
        # fresh jit there would stall actor add()s for the whole compile
        if self.mesh is not None:
            from r2d2_tpu.parallel.mesh import sharded_super_step

            super_fn = sharded_super_step(
                cfg, self.net, self.mesh, k, state_template=self.state,
                layout=getattr(ring, "layout", "replicated"))
        else:
            super_fn = make_super_step(cfg, self.net, k)
        B = cfg.batch_size
        try:
            super_fn = super_fn.lower(
                self.state, ring.snapshot(),
                np.zeros((k, B, 6), np.int32),
                np.zeros((k, B), np.float32)).compile()
        except Exception:
            # some plugin backends lack the AOT API; the jit wrapper
            # compiles at first call instead (stalling the lock once)
            pass
        compiled = super_fn

        losses_hist = []

        def harvest(item) -> None:
            """Fetch a finished super-step's results and feed them back."""
            meta, losses, priorities = item
            with tracer.span("learner.result_sync"):
                # one D2H round trip for everything the host needs
                flat = np.asarray(jax.device_get(
                    jnp.concatenate([losses, priorities.reshape(-1)])))
            losses_np, prios_np = flat[:k], flat[k:].reshape(k, B)
            assert np.isfinite(losses_np).all(), (
                f"non-finite loss in super-step: {losses_np}")
            self.env_steps = int(meta["env_steps"])
            if priority_sink is not None:
                for j in range(k):
                    priority_sink(meta["idxes"][j], prios_np[j],
                                  meta["block_ptr"], float(losses_np[j]))
            losses_hist.extend(losses_np.tolist())

        # depth-1 pipeline: dispatch super-step t+1 before syncing t's
        # results, so the D2H round trip rides under the device compute.
        # Priority feedback lags ≤ 2k updates — comparable to the
        # reference's 8-batch queue + 4-batch staging lag.
        pending = None
        while updates < target:
            if stop is not None and stop():
                break
            if not buffer.ready:
                time.sleep(0.02)
                continue

            def dispatch(ints, weights):
                with tracer.span("learner.step_dispatch"):
                    return compiled(self.state, ring.snapshot(),
                                    jnp.asarray(ints), jnp.asarray(weights))

            with tracer.span("learner.sample_meta"):
                meta = buffer.sample_meta(k, dispatch=dispatch)
            self.state, losses, priorities = meta["dispatched"]
            if pending is not None:
                harvest(pending)
            pending = (meta, losses, priorities)

            prev, updates = updates, updates + k
            # cadences fire on interval crossings (updates advances by k)
            if (self.param_store is not None
                    and updates // cfg.weight_publish_interval
                    > prev // cfg.weight_publish_interval):
                self._publish()
            if (self.checkpointer is not None
                    and updates // cfg.save_interval
                    > prev // cfg.save_interval):
                self._save(updates, t0)
        if pending is not None:
            harvest(pending)

        if self.checkpointer is not None:
            self._save(self.num_updates, t0)
        mins = self.start_minutes + (time.time() - t0) / 60.0
        return dict(
            num_updates=self.num_updates,
            env_steps=self.env_steps,
            minutes=mins,
            mean_loss=(float(np.mean(losses_hist[-100:]))
                       if losses_hist else float("nan")),
        )

    def _save(self, updates: int, t0: float) -> None:
        minutes = self.start_minutes + (time.time() - t0) / 60.0
        if jax.process_count() > 1:
            # Gather mp-sharded leaves that may live on other hosts by
            # resharding the state to fully-replicated IN-GRAPH (XLA
            # allgathers over ICI) — the host-side process_allgather can't
            # express arbitrary shardings (it only tiles along axis 0).
            # Every process then calls checkpointer.save: orbax is
            # multihost-aware (internal sync barriers, primary-host-only
            # file writes), so skipping non-zero processes here would
            # desync its barriers.  The meta sidecar is process-0-gated
            # inside Checkpointer.save.
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self.mesh, PartitionSpec())
            state = jax.device_get(
                jax.jit(lambda s: s, out_shardings=rep)(self.state))
        else:
            state = jax.device_get(self.state)
        from r2d2_tpu.checkpoint import arch_meta

        self.checkpointer.save(updates, state,
                               meta=dict(env_steps=self.env_steps,
                                         minutes=minutes,
                                         game=self.cfg.game_name,
                                         **arch_meta(self.cfg)))
