"""Session-tier wire format: length-framed CRC'd messages over a local
socket.

The session-serving frontier (``serving/server.py``) faces *external*
episodic clients, so its transport cannot be the training fabric's
preallocated shared-memory slabs — a client is any process that can open
a loopback TCP connection.  What DOES carry over from the fabric is the
integrity discipline every shm channel already shares
(``replay/block.py``): a message is header int64 words followed by fixed
-shape payload arrays, hashed by :func:`~r2d2_tpu.replay.block.
payload_crc32` with the CRC written LAST — a torn or garbled frame shows
up as a mismatch at the receiver, which drops it (counted) instead of
acting on garbage.  The payload layout itself is described by the same
``(name, shape, dtype)`` spec tuples the slab channels use and laid out
by :func:`~r2d2_tpu.replay.block.slot_layout`, so one vocabulary covers
every transport in the tree (the ``wire-format`` graftlint rule extends
to these names — a module speaking this protocol must import them from
here, never restate them).

Frame grammar (all little-endian):

- ``u32 length`` — byte length of the body that follows.
- body: ``HEADER_WORDS`` int64 words ``(kind, session_id, seq, aux)``,
  then the payload arrays of the kind's spec (8-byte aligned,
  ``slot_layout`` packing), then the ``u32`` CRC over header + arrays.

Kinds and their payloads:

- ``MSG_OPEN``   (client → server): admit ``session_id``.  No payload.
  ``aux`` unused.
- ``MSG_ACT``    (client → server): one env-step act request —
  ``session_request_spec`` payload (obs, last_action one-hot,
  last_reward).  ``aux`` bit 0 = episode reset (zero the
  session-resident hidden before acting: a session may span many
  episodes).
- ``MSG_CLOSE``  (client → server): episode/session complete.  No
  payload.
- ``MSG_RSP``    (server → client): the reply to any of the above.
  ``aux`` carries the status; an OK act reply carries the
  ``session_response_spec`` payload (the q row — greedy action is
  ``argmax``; ε-greedy stays client-side exactly as it stays fleet-side
  in the training serve plane), all other replies are payload-free.

Statuses (HTTP-flavoured so operators can read a client log cold):
``STATUS_OK`` 0, ``STATUS_SHED`` 429 (admission rejected — bounded
pending queue full, breaker open, or no evictable session slot),
``STATUS_GONE`` 410 (unknown / evicted session — the client must
re-open, never assume server state), ``STATUS_EXPIRED`` 408 (the
request sat past its deadline and was shed instead of served stale).
"""
from __future__ import annotations

import socket
import struct
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.replay.block import payload_crc32, slot_layout, slot_views

# message kinds (header word 0)
MSG_OPEN = 1
MSG_ACT = 2
MSG_CLOSE = 3
MSG_RSP = 4

# response statuses (header word 3 of a MSG_RSP)
STATUS_OK = 0
STATUS_EXPIRED = 408
STATUS_GONE = 410
STATUS_SHED = 429

# act-request aux bits
FLAG_RESET = 1

# body header: (kind, session_id, seq, aux) as int64 words
HEADER_WORDS = 4
_HEADER_BYTES = HEADER_WORDS * 8

# framing: u32 body length; a sanity bound so a desynced/garbled length
# word cannot make a reader allocate gigabytes
_LEN = struct.Struct("<I")
MAX_FRAME_BYTES = 16 * 1024 * 1024


class WireGarbled(Exception):
    """A frame arrived but failed its CRC32 integrity check."""


class WireClosed(Exception):
    """The peer closed the connection (EOF mid-stream included)."""


def session_request_spec(cfg: Config, action_dim: int):
    """(name, shape, dtype) of one act request's payload — the batched
    AgentState row the act fn consumes, minus hidden (session-resident,
    the whole point of the tier)."""
    return (
        ("obs", tuple(cfg.stored_obs_shape), np.uint8),
        ("last_action", (action_dim,), np.float32),
        ("last_reward", (1,), np.float32),
    )


def session_response_spec(cfg: Config, action_dim: int):
    """(name, shape, dtype) of one OK act reply's payload: the q row
    (greedy action = argmax; exploration stays client-side)."""
    return (("q", (action_dim,), np.float32),)


EMPTY_SPEC: Tuple = ()


def encode_frame(spec, header: Sequence[int],
                 fields: Optional[Dict[str, np.ndarray]] = None) -> bytes:
    """One wire frame (length word included): header words, the spec's
    payload arrays, CRC last — the replay/block.py convention."""
    if len(header) != HEADER_WORDS:
        raise ValueError(f"header must be {HEADER_WORDS} words")
    nbytes, offsets = slot_layout(spec) if spec else (0, {})
    body = bytearray(_HEADER_BYTES + nbytes + 4)
    np.frombuffer(body, np.int64, HEADER_WORDS)[:] = header
    arrays = []
    if spec:
        views = slot_views(memoryview(body)[_HEADER_BYTES:
                                            _HEADER_BYTES + nbytes],
                           spec, offsets, nbytes, 0)
        for name, _, _ in spec:
            views[name][...] = fields[name]
        arrays = [views[name] for name, _, _ in spec]
    crc = payload_crc32(header, arrays)
    body[-4:] = np.uint32(crc).tobytes()
    return _LEN.pack(len(body)) + bytes(body)


def peek_kind(body: bytes) -> int:
    """The message kind of a framed body, read before the payload spec is
    known (the spec to decode with depends on it)."""
    if len(body) < _HEADER_BYTES + 4:
        raise WireGarbled(f"frame body too short ({len(body)} bytes)")
    return int(np.frombuffer(body, np.int64, 1)[0])


def decode_frame(spec, body: bytes) -> Tuple[Tuple[int, ...], dict]:
    """``(header words, payload views)`` of a frame body, CRC-verified.
    The views alias ``body`` — copy anything that must outlive it.
    Raises :class:`WireGarbled` on a size or CRC mismatch."""
    nbytes, offsets = slot_layout(spec) if spec else (0, {})
    want = _HEADER_BYTES + nbytes + 4
    if len(body) != want:
        raise WireGarbled(
            f"frame body is {len(body)} bytes, spec says {want}")
    header = tuple(int(w) for w in np.frombuffer(body, np.int64,
                                                 HEADER_WORDS))
    views = {}
    arrays = []
    if spec:
        views = slot_views(memoryview(body)[_HEADER_BYTES:
                                            _HEADER_BYTES + nbytes],
                           spec, offsets, nbytes, 0)
        arrays = [views[name] for name, _, _ in spec]
    crc = int(np.frombuffer(body, np.uint32, 1, len(body) - 4)[0])
    if crc != payload_crc32(header, arrays):
        raise WireGarbled(f"frame kind {header[0]} seq {header[2]} failed "
                          "CRC32")
    return header, views


def send_frame(sock: socket.socket, frame: bytes) -> None:
    """Blocking whole-frame send (``frame`` already carries its length
    word).  Callers serialise concurrent writers with their own lock."""
    sock.sendall(frame)


class FrameReader:
    """Incremental frame parser over a non-blocking-ish socket.

    ``poll()`` does one bounded ``recv`` (the socket's timeout governs
    the wait) and returns every COMPLETE frame body that has arrived —
    zero on a quiet poll, several under pipelining.  Raises
    :class:`WireClosed` on EOF, so a reader loop stays a simple
    poll-with-timeout / check-stop cycle (the ``bounded-wait``
    discipline).

    ``max_frame`` is the desync sanity bound: session traffic keeps the
    default; transports with bigger legitimate frames (the cross-host
    replay fabric's preassembled batch responses, replay/netwire.py)
    pass their layout-derived bound so the check stays tight."""

    def __init__(self, sock: socket.socket,
                 max_frame: int = MAX_FRAME_BYTES):
        self.sock = sock
        self.max_frame = int(max_frame)
        # bytes the LAST poll() recv'd (0 = quiet): drain loops use it
        # to tell "socket idle" from "mid-frame, keep pulling" — a poll
        # returns no frames in both cases
        self.last_chunk = 0
        self._buf = bytearray()

    def poll(self) -> list:
        try:
            chunk = self.sock.recv(1 << 16)
        except socket.timeout:
            self.last_chunk = 0
            return []
        except OSError:
            raise WireClosed("connection reset")
        if not chunk:
            raise WireClosed("peer closed")
        self.last_chunk = len(chunk)
        self._buf.extend(chunk)
        out = []
        while True:
            if len(self._buf) < _LEN.size:
                return out
            (n,) = _LEN.unpack_from(self._buf)
            if n > self.max_frame:
                raise WireGarbled(f"frame length {n} exceeds the "
                                  f"{self.max_frame}-byte bound — "
                                  "desynced stream")
            if len(self._buf) < _LEN.size + n:
                return out
            out.append(bytes(self._buf[_LEN.size:_LEN.size + n]))
            del self._buf[:_LEN.size + n]
