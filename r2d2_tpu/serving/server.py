"""The session-serving frontier: external episodic traffic over the
trained Q-network.

``InferenceService`` (parallel/inference_service.py) serves exactly N
training fleets in a fixed lockstep window; this module generalizes that
act path to *thousands of concurrent episodic sessions* from external
processes — the "millions of users" half of the ROADMAP north star.  One
:class:`SessionServer` composes the three new pieces:

- :class:`~r2d2_tpu.serving.store.SessionStore` — session-keyed
  server-resident LSTM state under the ``cfg.serve_max_sessions`` LRU
  budget, idle-reaped, snapshot/restorable through the run's
  ``Checkpointer`` (a restart resumes live episodes bit-exact).
- :class:`~r2d2_tpu.serving.admission.AdmissionController` — bounded
  pending queue, per-request deadlines, the act circuit breaker: every
  overload answer is an immediate 429/408-style reply, never an
  unbounded wait (the ``bounded-wait`` lint applies to every loop here).
- :class:`~r2d2_tpu.serving.batcher.ContinuousBatcher` — drains whatever
  is pending (up to ``cfg.serve_max_batch``), bucket-pads into one of a
  small set of pre-compiled jitted act entry points, gathers each
  request's hidden from the store and scatters results back — so one
  slow client never stalls the batch (there is no lockstep window to
  hold hostage).

Transport: length-framed CRC'd messages (``serving/wire.py`` — the
replay/block.py integrity conventions over a loopback TCP socket), so
clients can be external processes; per-connection reader threads decode
and enqueue, the batch loop serves, replies go back tagged
``(session_id, seq)`` so clients may pipeline freely.  All threads run
under the :class:`~r2d2_tpu.utils.supervisor.Supervisor`.

Telemetry: the ``serving.*`` namespace in the shared registry
(counters for the session lifecycle + sheds, the
``serving.act_latency_s`` / ``serving.batch_size`` histograms on
``/metrics``, p50/p95/p99 latency gauges), ``serving.gather/act/
scatter`` tracer spans (they ride the span→event bridge onto the
cross-process trace timeline when a capture window is armed), and the
three-state ``/healthz`` verdict (``ok`` / ``degraded`` HTTP 200 —
shedding or breaker-open is the tier degrading BY DESIGN, a load
balancer must not evict it for that / ``failing`` 503 — the serve loop
itself is dead).
"""
from __future__ import annotations

import logging
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.serving.admission import AdmissionController, Request
from r2d2_tpu.serving.batcher import ContinuousBatcher
from r2d2_tpu.serving.store import SessionStore
from r2d2_tpu.serving.wire import (
    EMPTY_SPEC,
    FLAG_RESET,
    MSG_ACT,
    MSG_CLOSE,
    MSG_OPEN,
    MSG_RSP,
    STATUS_EXPIRED,
    STATUS_GONE,
    STATUS_OK,
    STATUS_SHED,
    FrameReader,
    WireClosed,
    WireGarbled,
    decode_frame,
    encode_frame,
    peek_kind,
    send_frame,
    session_request_spec,
    session_response_spec,
)
from r2d2_tpu.telemetry.registry import MetricsRegistry
from r2d2_tpu.utils.resilience import CLOSED, Deadline
from r2d2_tpu.utils.supervisor import Supervisor
from r2d2_tpu.utils.trace import Tracer

log = logging.getLogger(__name__)

# act-latency histogram bounds (seconds): finer than the registry default
# at the low end — a CPU act is single-digit milliseconds and the p99
# story lives there
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0)

# cadence for the cheap periodic work folded into the batch loop: idle
# reaping, store-counter absorption, latency percentile gauges
_HOUSEKEEPING_S = 0.25


class _Conn:
    """One client connection: socket + write lock + its frame reader."""

    __slots__ = ("cid", "sock", "wlock", "reader")

    def __init__(self, cid: int, sock: socket.socket):
        self.cid = cid
        self.sock = sock
        self.wlock = threading.Lock()
        self.reader = FrameReader(sock)


class SessionServer:
    """Continuous-batching session tier over one published param set."""

    def __init__(self, cfg: Config, action_dim: int,
                 registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1"):
        self.cfg = cfg
        self.action_dim = action_dim
        self.registry = registry if registry is not None else (
            MetricsRegistry())
        self.registry.declare_histogram("serving.act_latency_s",
                                        LATENCY_BUCKETS)
        self.tracer = Tracer()
        self.store = SessionStore(cfg)
        self.admission = AdmissionController(
            cfg, on_transition=self._on_breaker)
        self.batcher = ContinuousBatcher(cfg, action_dim)
        self.registry.declare_histogram(
            "serving.batch_size", [float(b) for b in self.batcher.buckets])
        self._req_spec = session_request_spec(cfg, action_dim)
        self._rsp_spec = session_response_spec(cfg, action_dim)

        port = 0 if cfg.serve_port < 0 else cfg.serve_port
        self._listener = socket.create_server((host, port))
        self._listener.settimeout(0.2)
        self.host = host
        self.port = int(self._listener.getsockname()[1])

        self.supervisor = Supervisor(
            max_restarts=3,
            on_giveup=lambda name: self.registry.inc("supervisor.gaveup",
                                                     thread=name))
        self.stop_event = threading.Event()
        self._started = False
        self._conns: Dict[int, _Conn] = {}
        self._conns_lock = threading.Lock()
        self._next_cid = 0
        # request latencies for the percentile gauges (the histogram on
        # /metrics is the durable record; this bounded tail feeds the
        # p50/p95/p99 gauges without per-sample registry storage)
        self._lat = deque(maxlen=4096)
        self._lat_lock = threading.Lock()
        self._last_housekeeping = 0.0
        self.batches = 0
        self.requests = 0
        self.requests_corrupt = 0
        self.gone = 0
        self.act_failures = 0

    # ------------------------------------------------------------- breaker
    def _on_breaker(self, name: str, old: int, new: int) -> None:
        self.registry.set_gauge("serving.circuit_state", float(new))
        if new != CLOSED:
            log.warning("serving: act circuit %s -> %s — shedding act "
                        "requests until a probe batch succeeds", old, new)

    # -------------------------------------------------------------- params
    def publish_params(self, params) -> int:
        version = self.batcher.publish(params)
        self.registry.set_gauge("serving.param_version", version)
        return version

    def warmup(self) -> None:
        self.batcher.warmup()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """Launch the supervised fabric: the accept loop and the batch
        loop.  Reader loops join per connection."""
        if self._started:
            raise RuntimeError("server already started")
        self._started = True
        self.supervisor.start("session_accept", self._accept_loop)
        self.supervisor.start("session_batch", self._batch_loop)

    def _stop(self) -> bool:
        return self.stop_event.is_set() or self.supervisor.any_failed

    def stop(self) -> None:
        self.stop_event.set()

    def close(self) -> None:
        self.stop_event.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.supervisor.join_all(timeout=5.0)
        with self._conns_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for st in conns:
            try:
                st.sock.close()
            except OSError:
                pass

    # --------------------------------------------------------------- accept
    def _accept_loop(self) -> None:
        while not self._stop():
            try:
                sock, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return   # listener closed: shutdown path
            sock.settimeout(0.2)
            with self._conns_lock:
                cid = self._next_cid
                self._next_cid += 1
                st = _Conn(cid, sock)
                self._conns[cid] = st
            self.registry.inc("serving.connections")
            # readers exit (normally) when their peer disconnects; the
            # restart budget only matters for a genuinely crashed reader
            self.supervisor.start(f"session_conn_{cid}",
                                  lambda st=st: self._conn_loop(st))

    # --------------------------------------------------------------- reader
    def _conn_loop(self, st: _Conn) -> None:
        while not self._stop():
            try:
                frames = st.reader.poll()
            except WireClosed:
                break
            except WireGarbled as e:
                # a desynced LENGTH stream is unrecoverable: drop the
                # connection (its sessions reap below, slots never leak)
                log.warning("serving: conn%d stream desync (%s) — "
                            "closing", st.cid, e)
                self.requests_corrupt += 1
                self.registry.inc("serving.requests_corrupt")
                break
            for body in frames:
                self._handle_frame(st, body)
        self._drop_conn(st)

    def _drop_conn(self, st: _Conn) -> None:
        with self._conns_lock:
            self._conns.pop(st.cid, None)
        try:
            st.sock.close()
        except OSError:
            pass
        if self.stop_event.is_set():
            # server shutdown, not a client abandon: the sessions must
            # SURVIVE into the shutdown snapshot (save_sessions runs
            # after the loops drain) so --resume-sessions can restore
            # them — reaping here would race the snapshot's state()
            return
        reaped = self.store.reap_owner(st.cid)
        if reaped:
            # mid-episode disconnect: the owned sessions reap NOW — an
            # abandoned client must never pin hidden-state slots until
            # the idle timeout crawls by
            self.admission.note_degrade()
            log.info("serving: conn%d disconnected — reaped %d live "
                     "session(s)", st.cid, len(reaped))

    def _handle_frame(self, st: _Conn, body: bytes) -> None:
        try:
            kind = peek_kind(body)
            spec = self._req_spec if kind == MSG_ACT else EMPTY_SPEC
            header, views = decode_frame(spec, body)
        except WireGarbled:
            # a torn/garbled frame is dropped, never served: acting on it
            # would return a well-formed reply derived from garbage.  The
            # client's bounded per-request deadline owns recovery
            self.requests_corrupt += 1
            self.registry.inc("serving.requests_corrupt")
            return
        _, sid, seq, aux = header
        if kind == MSG_OPEN:
            # the lifecycle quadruple (admitted/completed/reaped/evicted)
            # reaches the registry ONLY via housekeeping's counter_max
            # absorption of the store counts — an event-site inc here
            # would race it upward (e.g. a retried open of a live
            # session) and break the conservation identity on /metrics
            verdict, evicted = self.store.admit(sid, owner=st.cid)
            if verdict == "exists":
                self.store.adopt(sid, st.cid)
            if evicted is not None:
                self.admission.note_degrade()
            ok = verdict in ("ok", "exists")
            if not ok:
                self.registry.inc("serving.rejected")
            self._reply(st, sid, seq, STATUS_OK if ok else STATUS_SHED)
        elif kind == MSG_CLOSE:
            ok = self.store.release(sid, "completed")
            self._reply(st, sid, seq, STATUS_OK if ok else STATUS_GONE)
        elif kind == MSG_ACT:
            self.store.adopt(sid, st.cid)   # restored sessions re-bind
            if not self.store.mark_pending(sid):
                # unknown or evicted: never act on a zeroed slot — the
                # client re-opens and restarts its episode
                self.gone += 1
                self.registry.inc("serving.gone")
                self._reply(st, sid, seq, STATUS_GONE)
                return
            # zero-copy views: the frame body is per-frame immutable
            # bytes (FrameReader.poll), so the request can alias it for
            # its queued lifetime — the batch path copies exactly once,
            # into the batcher's padded scratch (audit r19: np.array
            # here double-materialized every obs on the ingest path)
            req = Request(st.cid, sid, seq, bool(aux & FLAG_RESET),
                          np.asarray(views["obs"]),
                          np.asarray(views["last_action"]),
                          float(views["last_reward"][0]))
            if not self.admission.submit(req):
                self.store.clear_pending(sid)
                self.registry.inc("serving.rejected")
                self._reply(st, sid, seq, STATUS_SHED)
        else:
            self.requests_corrupt += 1
            self.registry.inc("serving.requests_corrupt")

    # ---------------------------------------------------------------- reply
    def _reply(self, st: _Conn, sid: int, seq: int, status: int,
               q: Optional[np.ndarray] = None) -> None:
        if q is None:
            frame = encode_frame(EMPTY_SPEC, (MSG_RSP, sid, seq, status))
        else:
            frame = encode_frame(self._rsp_spec,
                                 (MSG_RSP, sid, seq, status), {"q": q})
        try:
            with st.wlock:
                send_frame(st.sock, frame)
        except OSError:
            # a dead peer OR a send timeout (a stuck client whose TCP
            # buffer filled mid-frame).  Either way the reply stream may
            # now hold a TORN frame — every later frame would desync the
            # client's reader — so the connection is unusable: close it
            # and let the reader loop observe the EOF and reap
            try:
                st.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                st.sock.close()
            except OSError:
                pass

    def _reply_to(self, req: Request, status: int,
                  q: Optional[np.ndarray] = None) -> None:
        with self._conns_lock:
            st = self._conns.get(req.conn_id)
        if st is not None:
            self._reply(st, req.sid, req.seq, status, q)

    # ---------------------------------------------------------------- serve
    def _batch_loop(self) -> None:
        while not self._stop():
            self.serve_once()

    def serve_once(self, idle_sleep: float = 0.002) -> int:
        """One continuous-batch turn: housekeeping, drain, act, scatter.
        Returns the number of requests served (0 when idle)."""
        now = time.monotonic()
        if now - self._last_housekeeping > _HOUSEKEEPING_S:
            self._last_housekeeping = now
            self._housekeeping(now)
        ready, expired = self.admission.drain(self.cfg.serve_max_batch,
                                              now=now)
        for r in expired:
            # the client's deadline passed while the request queued:
            # answering 408 now beats serving a reply nobody awaits
            self.store.clear_pending(r.sid)
            self.registry.inc("serving.expired")
            self._reply_to(r, STATUS_EXPIRED)
        if not ready:
            if idle_sleep > 0:
                time.sleep(idle_sleep)
            return 0
        # one request per session per batch: a pipelined second step must
        # observe the first's hidden, so it waits for the next turn
        # (arrival order within the session is preserved)
        batch: List[Request] = []
        seen = set()
        later: List[Request] = []
        for r in ready:
            if r.sid in seen:
                later.append(r)
            else:
                seen.add(r.sid)
                batch.append(r)
        if later:
            self.admission.requeue_front(later)

        br = self.admission.breaker
        if br.state != CLOSED and not br.allow_attempt():
            # circuit open: shed fast — queueing behind a broken act
            # path would turn into the unbounded wait this tier bans
            for r in batch:
                self.store.clear_pending(r.sid)
                self.registry.inc("serving.rejected")
                self._reply_to(r, STATUS_SHED)
            return 0

        tr = self.tracer
        with tr.span("serving.gather"):
            sids = [r.sid for r in batch]
            reset = np.fromiter((r.reset for r in batch), bool,
                                len(batch))
            kept, hidden = self.store.gather(sids, reset, now=now)
            if len(kept) < len(batch):
                kept_set = set(kept)
                for i, r in enumerate(batch):
                    if i not in kept_set:
                        # reaped between submit and dispatch (owner
                        # disconnect): nothing to act on
                        self.gone += 1
                        self.registry.inc("serving.gone")
                        self._reply_to(r, STATUS_GONE)
                batch = [batch[i] for i in kept]
            if not batch:
                return 0
            obs = np.stack([r.obs for r in batch])
            last_action = np.stack([r.last_action for r in batch])
            last_reward = np.fromiter((r.last_reward for r in batch),
                                      np.float32, len(batch))
        try:
            with tr.span("serving.act"):
                q, new_hidden = self.batcher.act(obs, last_action,
                                                 last_reward, hidden)
        except Exception as e:  # noqa: BLE001 — breaker boundary
            self.act_failures += 1
            self.registry.inc("serving.act_failures")
            br.record_failure()
            self.admission.note_degrade()
            log.error("serving: act batch failed (%s) — circuit %s, "
                      "shedding the batch", e, br.state_name)
            for r in batch:
                self.store.clear_pending(r.sid)
                self.registry.inc("serving.rejected")
                self._reply_to(r, STATUS_SHED)
            return 0
        br.record_success()
        with tr.span("serving.scatter"):
            self.store.scatter([r.sid for r in batch], new_hidden)
            done = time.monotonic()
            lats = [done - r.recv_ts for r in batch]
            for i, r in enumerate(batch):
                self.store.clear_pending(r.sid)
                self._reply_to(r, STATUS_OK, q[i])
        self.registry.observe_many("serving.act_latency_s", lats)
        self.registry.observe("serving.batch_size", len(batch))
        self.registry.inc("serving.requests", len(batch))
        self.registry.inc("serving.batches")
        with self._lat_lock:
            self._lat.extend(lats)
        self.batches += 1
        self.requests += len(batch)
        return len(batch)

    # ---------------------------------------------------------- housekeeping
    def _housekeeping(self, now: float) -> None:
        reaped = self.store.reap_idle(self.cfg.serve_session_idle_s,
                                      now=now)
        if reaped:
            self.admission.note_degrade()
            log.info("serving: idle-reaped %d session(s)", len(reaped))
        c = self.store.counts()
        reg = self.registry
        reg.counter_max("serving.admitted", c["admitted"])
        reg.counter_max("serving.completed", c["completed"])
        reg.counter_max("serving.reaped", c["reaped"])
        reg.counter_max("serving.evicted", c["evicted"])
        reg.set_gauge("serving.live_sessions", c["live"])
        reg.set_gauge("serving.pending", self.admission.depth())
        with self._lat_lock:
            lats = list(self._lat)
        if lats:
            p50, p95, p99 = np.percentile(lats, [50, 95, 99])
            reg.set_gauge("serving.act_latency_p50_s", float(p50))
            reg.set_gauge("serving.act_latency_p95_s", float(p95))
            reg.set_gauge("serving.act_latency_p99_s", float(p99))

    # ---------------------------------------------------------------- state
    def healthz(self) -> Dict[str, Any]:
        """Three-state verdict through the existing /healthz contract:
        ``failing`` (503) only when the serve fabric itself is down;
        shedding / evicting / an open act circuit is ``degraded`` —
        HTTP 200, because a tier that is successfully degrading must not
        be evicted by its load balancer (docs/OBSERVABILITY.md)."""
        ok = not (self.supervisor.any_failed
                  or (self._started and self.stop_event.is_set()))
        degraded = self.admission.degraded()
        out = dict(ok=ok, degraded=degraded and ok,
                   status=("failing" if not ok
                           else "degraded" if degraded else "ok"),
                   sessions=self.store.counts(),
                   admission=self.admission.stats(),
                   threads=self.supervisor.health())
        return out

    def stats(self) -> Dict[str, Any]:
        c = self.store.counts()
        a = self.admission.stats()
        assert (c["admitted"]
                == c["completed"] + c["reaped"] + c["evicted"] + c["live"])
        return dict(
            port=self.port, batches=self.batches, requests=self.requests,
            requests_corrupt=self.requests_corrupt, gone=self.gone,
            act_failures=self.act_failures,
            mean_batch=round(self.requests / self.batches, 2)
            if self.batches else 0.0,
            param_version=self.batcher.version, **c, **a)

    # ------------------------------------------------------------- snapshot
    def save_sessions(self, ckpt) -> Dict[str, Any]:
        """Persist the live-session store through the Checkpointer's
        atomic snapshot discipline — a restart (:meth:`restore_sessions`)
        resumes every live episode bit-exact."""
        state = self.store.state()

        def writer(path: str) -> Dict[str, Any]:
            with open(path, "wb") as f:
                np.savez(f, sids=state["sids"], steps=state["steps"],
                         hidden=state["hidden"])
            return dict(counters=state["counters"],
                        live=int(len(state["sids"])),
                        param_version=self.batcher.version)

        return ckpt.save_sessions(writer)

    def restore_sessions(self, ckpt) -> bool:
        """Load the latest session snapshot into the (empty) store.
        False when none exists — the server starts cold."""
        snap = ckpt.restore_sessions()
        if snap is None:
            return False
        meta, payload_path = snap
        with np.load(payload_path) as z:
            self.store.load_state(dict(
                sids=z["sids"], steps=z["steps"], hidden=z["hidden"],
                counters=meta["counters"]))
        log.info("serving: restored %d live session(s) from the snapshot",
                 self.store.live())
        return True

    # ------------------------------------------------------------- exporter
    def exporter_loops(self, metrics_port: int):
        """``[(name, loop)]`` for an HTTP scrape endpoint over this
        server's registry/health — same close-driven discipline as the
        trainer's (telemetry/exporter.py).  Empty when disabled (0)."""
        from r2d2_tpu.telemetry.exporter import TelemetryExporter

        if metrics_port == 0:
            return []
        exporter = TelemetryExporter(
            self.registry, self.healthz,
            status_fn=lambda: dict(serving=self.stats()),
            port=max(0, metrics_port))
        self.exporter = exporter

        def serving_telemetry_loop():
            while not exporter.closed:
                try:
                    exporter.handle_once()
                except (OSError, ValueError):
                    return
        return [("serving_telemetry", serving_telemetry_loop)]


# --------------------------------------------------------------------------
# standalone entry point (the `r2d2_tpu serve` CLI)
# --------------------------------------------------------------------------

def follow_params_once(server: SessionServer, ckpt, cfg: Config,
                       followed: Dict[str, int]) -> bool:
    """One poll of follow-mode serving: adjudicate the newest COMPLETE
    checkpoint past ``followed["step"]`` — arch-compat-check, restore,
    re-run the bf16 greedy-parity gate, republish through the batcher.
    A failing gate or a torn/arch-drifted step is SKIPPED (serving stays
    on the last good params; deterministic verdicts are never retried).
    Returns True when a republish happened.  ``followed`` carries
    ``step`` / ``republishes`` / ``parity_failures`` across polls."""
    from r2d2_tpu.checkpoint import check_arch_compat

    s = ckpt.latest_step()
    if s is None or s <= followed["step"]:
        return False
    try:
        check_arch_compat(cfg, ckpt.peek_meta(s))
        raw, _ = ckpt.restore(None, step=s)
    except Exception as e:  # arch drift / GC'd or torn under us
        log.warning("serving: follow skipped step %d (%s)", s, e)
        followed["step"] = s
        return False
    new_params = raw["params"]
    if not server.batcher.greedy_parity_ok(new_params):
        followed["parity_failures"] += 1
        followed["step"] = s
        server.registry.inc("serving.follow_parity_failures")
        log.error("serving: bf16 greedy-parity gate FAILED for step %d "
                  "— serving stays on the last good params (version "
                  "%d)", s, server.batcher.version)
        return False
    server.publish_params(new_params)
    followed["step"] = s
    followed["republishes"] += 1
    server.registry.inc("serving.republishes")
    server.registry.set_gauge("serving.followed_step", float(s))
    log.info("serving: republished step %d (param version %d)", s,
             server.batcher.version)
    return True


def run_server(cfg: Config, checkpoint_dir: str,
               action_dim: Optional[int] = None,
               resume_sessions: bool = False,
               max_wall_seconds: Optional[float] = None,
               verbose: bool = True,
               follow: bool = False,
               follow_poll: float = 2.0) -> Dict[str, Any]:
    """Serve the newest complete checkpoint in ``checkpoint_dir`` until
    SIGTERM/SIGINT (drain, snapshot the live sessions, exit) or the wall
    budget.  Returns the final :meth:`SessionServer.stats` plus the
    bound ports — the CLI prints it as the run's machine-readable
    summary.

    ``follow=True`` is follow-mode serving (the league eval sidecar's
    checkpoint-follow loop on the serving tier): a supervised
    ``param_follow`` loop polls the Checkpointer every ``follow_poll``
    seconds and republishes each new COMPLETE step's params through the
    ContinuousBatcher — arch-compat-checked, and under
    ``serve_dtype="bfloat16"`` the greedy-parity gate re-runs per
    republish (:meth:`ContinuousBatcher.greedy_parity_ok`; a failing
    step is skipped and serving stays on the last good params).  With no
    checkpoint on disk yet, follow mode waits for the first one instead
    of failing — `r2d2_tpu serve --follow` can start before its
    trainer."""
    import signal

    from r2d2_tpu.checkpoint import Checkpointer, check_arch_compat

    ckpt = Checkpointer(checkpoint_dir)
    step = ckpt.latest_step()
    if step is None and not follow:
        raise FileNotFoundError(
            f"no complete checkpoint under {checkpoint_dir} — train "
            "first, then serve (or --follow a live trainer)")
    # follow-mode cold start: the trainer may not have saved yet.  The
    # wait gets its OWN bound — the serving wall budget starts after
    # warmup (below), exactly as in non-follow mode, so restore/compile
    # time never eats a short --max-wall-seconds serving window
    wait = Deadline(max_wall_seconds if max_wall_seconds else 0.0)
    while step is None:
        if wait.expired:
            raise FileNotFoundError(
                f"no complete checkpoint appeared under {checkpoint_dir} "
                "within the wall budget (--follow waits for a live "
                "trainer's first save)")
        time.sleep(0.5)
        step = ckpt.latest_step()

    meta = ckpt.peek_meta(step)
    check_arch_compat(cfg, meta)   # fail with a field list, not an orbax
    raw, _ = ckpt.restore(None, step=step)  # shape error mid-restore
    params = raw["params"]
    if action_dim is None:
        from r2d2_tpu.envs import create_env

        env = create_env(cfg)
        action_dim = int(env.action_space.n)
        close = getattr(env, "close", None)
        if callable(close):
            close()

    server = SessionServer(cfg, action_dim)
    stop = threading.Event()
    prev = {}
    if threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            log.warning("signal %d: draining the session tier, then "
                        "snapshotting live sessions", signum)
            stop.set()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):
                pass
    # follow-mode state: the last step adjudicated (published OR skipped
    # by a parity failure — a deterministic gate is never retried)
    followed = dict(step=int(step), republishes=0, parity_failures=0)

    def param_follow():
        while not (stop.is_set() or server._stop()):
            time.sleep(follow_poll)
            follow_params_once(server, ckpt, cfg, followed)

    try:
        server.publish_params(params)
        server.warmup()
        if resume_sessions:
            server.restore_sessions(ckpt)
        for name, loop in server.exporter_loops(cfg.telemetry_port):
            server.supervisor.start(name, loop)
        if follow:
            server.supervisor.start("param_follow", param_follow)
        server.start()
        if verbose:
            print(f"serving step_{step} on {server.host}:{server.port} "
                  f"(dtype={cfg.serve_dtype}, "
                  f"max_sessions={cfg.serve_max_sessions}, "
                  f"max_batch={cfg.serve_max_batch}"
                  + (", follow" if follow else "") + ")", flush=True)
        deadline = (time.monotonic() + max_wall_seconds
                    if max_wall_seconds else None)
        last_line = 0.0
        final_health = "failing"
        while not (stop.is_set() or server.supervisor.any_failed):
            # sampled pre-teardown: the summary must report the verdict
            # the tier actually served with, not the stopped state
            final_health = server.healthz()["status"]
            if deadline is not None and time.monotonic() > deadline:
                break
            time.sleep(0.2)
            if verbose and time.monotonic() - last_line > cfg.log_interval:
                last_line = time.monotonic()
                s = server.stats()
                print(f"sessions live={s['live']} admitted={s['admitted']}"
                      f" completed={s['completed']} reaped={s['reaped']}"
                      f" evicted={s['evicted']} rejected={s['rejected']}"
                      f" batches={s['batches']} status="
                      f"{server.healthz()['status']}", flush=True)
    finally:
        # drain first (stop + join every loop), snapshot second: an
        # in-flight batch that scattered AFTER the snapshot would leave
        # the client one reply ahead of the restored hidden
        server.stop()
        server.close()
        try:
            server.save_sessions(ckpt)
        except Exception:
            log.exception("session snapshot failed at shutdown")
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass
    out = dict(server.stats(), step=int(step), port=server.port,
               health=final_health)
    if follow:
        out.update(followed_step=followed["step"],
                   republishes=followed["republishes"],
                   follow_parity_failures=followed["parity_failures"])
    return out
