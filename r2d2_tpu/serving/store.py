"""Session-resident recurrent state under an LRU budget.

The R2D2 policy is recurrent: serving it to episodic clients means the
server must carry each live episode's LSTM state ``(2, layers, H)``
between that client's requests — the client only ever ships one step's
``(obs, last_action, last_reward)``.  The :class:`SessionStore` owns
that state for up to ``cfg.serve_max_sessions`` concurrent sessions:

- **one preallocated pool** ``(max_sessions, 2, layers, H) float32`` —
  a session holds a slot; gather/scatter for a batch is one fancy-indexed
  read/write, never per-session allocation.
- **LRU eviction**: admitting past the budget evicts the least-recently-
  used session *that has no request in flight* (evicting under a pending
  request would serve the request on a zeroed slot — the one corruption
  this tier can never emit; if every session is in flight the admit is
  shed instead).  An evicted session's next request answers
  ``STATUS_GONE``: the client re-opens and restarts its episode.
- **idle reaping**: sessions untouched for ``cfg.serve_session_idle_s``
  are reaped (abandoned clients must never pin hidden-state slots), and
  a disconnect reaps every session the connection owned immediately.
- **snapshot/restore**: the full store (pool rows + per-session meta +
  the accounting counters) round-trips through ``Checkpointer
  .save_sessions`` so a server restart resumes live episodes bit-exact.

Accounting invariant (asserted by the acceptance e2e and the chaos
soak): ``admitted == completed + reaped + evicted + live`` — every
admitted session leaves the store through exactly one of the three
exits or is still live.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from r2d2_tpu.config import Config


class _Session:
    __slots__ = ("sid", "slot", "owner", "steps", "last_used", "pending")

    def __init__(self, sid: int, slot: int, owner: Optional[int],
                 now: float):
        self.sid = sid
        self.slot = slot
        self.owner = owner          # connection id; None after a restore
        self.steps = 0              # served act steps (telemetry only)
        self.last_used = now        # monotonic; idle-reap clock
        self.pending = 0            # requests in flight (eviction guard)


class SessionStore:
    """Session-keyed server-resident hidden state (module docstring).

    Thread-safe: the reader threads admit/complete/mark-pending while
    the batch loop gathers/scatters/reaps — one lock, scalar work plus
    the batch-sized pool reads/writes inside it."""

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.max_sessions = int(cfg.serve_max_sessions)
        self.hidden = np.zeros(
            (self.max_sessions, 2, cfg.lstm_layers, cfg.hidden_dim),
            np.float32)
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[int, _Session]" = OrderedDict()
        self._free: List[int] = list(range(self.max_sessions - 1, -1, -1))
        # lifetime accounting (the invariant in the module docstring)
        self.admitted = 0
        self.completed = 0
        self.reaped = 0
        self.evicted = 0

    # ------------------------------------------------------------ admission
    def admit(self, sid: int, owner: Optional[int] = None,
              now: Optional[float] = None) -> Tuple[str, Optional[int]]:
        """Admit session ``sid``.  Returns ``(verdict, evicted_sid)``:
        ``("ok", None)`` on a free slot, ``("ok", victim)`` when the LRU
        victim was evicted to make room, ``("exists", None)`` for a
        re-open of a live session (its state is kept — the client is
        retrying an open whose ack it lost), and ``("shed", None)`` when
        the store is full of in-flight sessions (nothing is safely
        evictable)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if sid in self._sessions:
                return "exists", None
            victim = None
            if not self._free:
                for cand_id, cand in self._sessions.items():
                    if cand.pending == 0:
                        victim = cand_id
                        break
                if victim is None:
                    return "shed", None
                v = self._sessions.pop(victim)
                self.hidden[v.slot] = 0.0   # no state leaks across owners
                self._free.append(v.slot)
                self.evicted += 1
            slot = self._free.pop()
            self.hidden[slot] = 0.0
            self._sessions[sid] = _Session(sid, slot, owner, now)
            self.admitted += 1
            return "ok", victim

    def release(self, sid: int, reason: str) -> bool:
        """Remove ``sid`` and free its slot.  ``reason`` picks the
        accounting exit: ``"completed"`` (client closed), ``"reaped"``
        (idle timeout / disconnect), ``"evicted"`` is admit()'s business
        and not accepted here."""
        if reason not in ("completed", "reaped"):
            raise ValueError(f"unknown release reason {reason!r}")
        with self._lock:
            return self._release_locked(sid, reason)

    def _release_locked(self, sid: int, reason: str) -> bool:
        s = self._sessions.pop(sid, None)
        if s is None:
            return False
        self.hidden[s.slot] = 0.0
        self._free.append(s.slot)
        if reason == "completed":
            self.completed += 1
        else:
            self.reaped += 1
        return True

    # ---------------------------------------------------------- in-flight
    def mark_pending(self, sid: int) -> bool:
        """A request for ``sid`` entered the pending queue: pin it
        against eviction until the reply is written.  False = unknown
        session (evicted/never admitted — answer ``STATUS_GONE``)."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                return False
            s.pending += 1
            return True

    def clear_pending(self, sid: int) -> None:
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None and s.pending > 0:
                s.pending -= 1

    # ------------------------------------------------------ gather/scatter
    def gather(self, sids: List[int], reset_mask: np.ndarray,
               now: Optional[float] = None
               ) -> Tuple[List[int], np.ndarray]:
        """Batch-read the hidden rows for ``sids`` (applying each row's
        episode-reset zero first), marking every session used-now (LRU
        touch).  Returns ``(kept_indices, hidden_batch)`` — a session
        that vanished between submit and dispatch (owner disconnect
        reaped it) is skipped, and its request answers ``STATUS_GONE``.
        """
        now = time.monotonic() if now is None else now
        kept: List[int] = []
        slots: List[int] = []
        with self._lock:
            for i, sid in enumerate(sids):
                s = self._sessions.get(sid)
                if s is None:
                    continue
                if reset_mask[i]:
                    self.hidden[s.slot] = 0.0
                s.last_used = now
                self._sessions.move_to_end(sid)
                kept.append(i)
                slots.append(s.slot)
            # fancy indexing already materialises a fresh array — no
            # extra copy on the hot path
            batch = self.hidden[slots] if slots else np.zeros(
                (0, *self.hidden.shape[1:]), np.float32)
        return kept, batch

    def scatter(self, sids: List[int], new_hidden: np.ndarray) -> None:
        """Write the post-step hidden rows back (skipping sessions that
        vanished mid-act) and count the served step."""
        with self._lock:
            for i, sid in enumerate(sids):
                s = self._sessions.get(sid)
                if s is None:
                    continue   # reaped mid-act: its slot may be reused
                self.hidden[s.slot] = new_hidden[i]
                s.steps += 1

    # -------------------------------------------------------------- reaping
    def reap_idle(self, idle_s: float,
                  now: Optional[float] = None) -> List[int]:
        """Release every session idle past ``idle_s`` with no request in
        flight (an in-flight straggler is the batcher's to answer — the
        race goes to the active side)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            # one atomic pass: a request that lands between the staleness
            # check and the release would reap an ACTIVE session — the
            # pending pin decides the race in the active side's favour
            stale = [sid for sid, s in self._sessions.items()
                     if s.pending == 0 and now - s.last_used > idle_s]
            return [sid for sid in stale
                    if self._release_locked(sid, "reaped")]

    def reap_owner(self, owner: int) -> List[int]:
        """A connection died: release every session it owned (mid-episode
        disconnects must never leak hidden-state slots).  In-flight
        requests of a reaped session resolve as skips at gather/scatter
        time — the reply had nowhere to go anyway."""
        with self._lock:
            mine = [sid for sid, s in self._sessions.items()
                    if s.owner == owner]
            return [sid for sid in mine
                    if self._release_locked(sid, "reaped")]

    def adopt(self, sid: int, owner: int) -> None:
        """Bind a restored (owner-less) session to the connection now
        driving it, so a later disconnect reaps it normally."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None and s.owner is None:
                s.owner = owner

    # ------------------------------------------------------------- introspect
    def live(self) -> int:
        with self._lock:
            return len(self._sessions)

    def session_steps(self, sid: int) -> Optional[int]:
        with self._lock:
            s = self._sessions.get(sid)
            return None if s is None else s.steps

    def counts(self) -> Dict[str, int]:
        """The accounting quadruple plus ``live`` — the invariant
        ``admitted == completed + reaped + evicted + live`` holds at any
        quiescent point (and at every point: each transition moves one
        session between exactly two terms under the lock)."""
        with self._lock:
            return dict(admitted=self.admitted, completed=self.completed,
                        reaped=self.reaped, evicted=self.evicted,
                        live=len(self._sessions))

    # ------------------------------------------------------------- snapshot
    def state(self) -> Dict[str, object]:
        """Everything a restart needs to resume live episodes bit-exact:
        per-session (sid, steps) in LRU order, the hidden rows packed
        densely in that order, and the lifetime counters (so the
        accounting invariant survives the restart)."""
        with self._lock:
            sids = np.asarray(list(self._sessions), np.int64)
            steps = np.asarray([s.steps for s in self._sessions.values()],
                               np.int64)
            slots = [s.slot for s in self._sessions.values()]
            return dict(
                sids=sids, steps=steps,
                hidden=self.hidden[slots] if slots else
                np.zeros((0, *self.hidden.shape[1:]), np.float32),
                counters=dict(admitted=self.admitted,
                              completed=self.completed,
                              reaped=self.reaped, evicted=self.evicted))

    def load_state(self, state: Dict[str, object]) -> None:
        """Restore a :meth:`state` snapshot into an EMPTY store of the
        same geometry.  Sessions come back owner-less (the connections
        died with the old server) with a fresh idle clock — the first
        act re-binds them (:meth:`adopt`); hidden rows are bit-exact."""
        hidden = np.asarray(state["hidden"], np.float32)
        if hidden.shape[1:] != self.hidden.shape[1:]:
            raise ValueError(
                f"session snapshot hidden {hidden.shape[1:]} does not "
                f"match this store's {self.hidden.shape[1:]}")
        now = time.monotonic()
        with self._lock:
            if self._sessions:
                raise RuntimeError("load_state into a non-empty store")
            if len(state["sids"]) > self.max_sessions:
                raise ValueError(
                    f"snapshot has {len(state['sids'])} sessions, budget "
                    f"is {self.max_sessions}")
            for sid, steps, row in zip(state["sids"], state["steps"],
                                       hidden):
                slot = self._free.pop()
                self.hidden[slot] = row
                s = _Session(int(sid), slot, None, now)
                s.steps = int(steps)
                self._sessions[int(sid)] = s
            c = state["counters"]
            self.admitted = int(c["admitted"])
            self.completed = int(c["completed"])
            self.reaped = int(c["reaped"])
            self.evicted = int(c["evicted"])
