"""Continuous batching over a small set of pre-compiled act entry points.

The training serve plane batches a FIXED window — ``num_actors`` lanes,
every fleet posting in lockstep — so one compiled executable covers every
batch.  External sessions have no lockstep: whatever requests are pending
when the batch loop turns is the batch, and its size is ragged from 1 to
``cfg.serve_max_batch``.  Compiling an executable per observed size would
retrace unboundedly (exactly what the RETRACES guard exists to catch);
padding everything to ``serve_max_batch`` wastes most of the batch at low
load.  The standard middle path is **bucket shaping**: round the ragged
size up to the next power of two, pad the tail rows with zeros (their
outputs are discarded, and pad rows never touch session state), and run
one of ``log2(serve_max_batch)+1`` pre-compiled entry points.  The
RETRACES budget is exactly the bucket count — a trace beyond it means
shape drift, not load.

Quantized serving (``cfg.serve_dtype``, QuaRL): ``"bfloat16"`` quantizes
the published params at publish time — each float32 leaf is rounded
through bfloat16 (the mantissa truncation IS the quantization) and
widened back so the same executable serves both dtypes bit-comparably.
This is the ``param_pump_dtype`` pattern lifted from the pump wire to the
serving tier, and the greedy-action-parity test
(tests/test_serving.py) gates it the same way.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.utils.trace import HOST_TRANSFERS, TRANSFER_GUARD


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """The pre-compiled batch shapes: powers of two below ``max_batch``,
    then ``max_batch`` itself (so the largest bucket is exactly the
    configured cap, power of two or not)."""
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(int(max_batch))
    return tuple(sizes)


class ContinuousBatcher:
    """Ragged-batch act over bucket-shaped jitted entry points."""

    def __init__(self, cfg: Config, action_dim: int):
        from r2d2_tpu.actor import make_act_fn
        from r2d2_tpu.models.network import create_network

        self.cfg = cfg
        self.action_dim = action_dim
        self.buckets = bucket_sizes(cfg.serve_max_batch)
        net = create_network(cfg, action_dim)
        # one jitted instance; each bucket shape is one deliberate trace
        # (+1 slack for a weak-type wobble on the very first call)
        self._act = make_act_fn(cfg, net, retrace_name="serving.act",
                                retrace_budget=len(self.buckets) + 1)
        self._params = None
        self.version = 0
        # per-bucket padded scratch, allocated on first use of each size
        self._scratch: dict = {}

    # ------------------------------------------------------------- params
    @staticmethod
    def _quantize(params):
        """The bf16 weights-only round-trip (mantissa truncation IS the
        quantization) — shared by :meth:`publish` and the re-runnable
        :meth:`greedy_parity_ok` gate so the gate tests exactly what
        publish ships."""
        import jax
        import jax.numpy as jnp

        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16).astype(jnp.float32)
            if hasattr(x, "dtype") and x.dtype == jnp.float32 else x,
            params)

    def publish(self, params) -> int:
        """Adopt a new param snapshot for serving.  ``serve_dtype=
        "bfloat16"`` quantizes every float32 leaf through bfloat16 at
        publish (weights-only post-training quantization; the act math
        stays the executable's own compute dtype), exactly like
        ``param_pump_dtype`` narrows the pump wire."""
        import jax

        if self.cfg.serve_dtype == "bfloat16":
            params = self._quantize(params)
        # host trees (a checkpoint restore) commit to a local device once
        # per publish, the VectorActor._refresh_params rule
        if isinstance(jax.tree.leaves(params)[0], np.ndarray):
            params = jax.device_put(params, jax.local_devices()[0])
        self._params = params
        self.version += 1
        return self.version

    def greedy_parity_ok(self, params, probe: int = 32,
                         seed: int = 0) -> bool:
        """The greedy-action-parity gate, re-runnable per publish: on a
        seeded probe batch, the bf16-quantized params must pick the same
        greedy actions as the full-precision ones.  Follow-mode serving
        runs this before EVERY republish (a trained policy can drift
        into bf16-sensitive logit margins long after the initial gate
        passed); trivially True when ``serve_dtype`` is float32.  The
        probe batch is bucket-shaped so the gate never costs an extra
        trace."""
        if self.cfg.serve_dtype != "bfloat16":
            return True
        import jax

        cfg = self.cfg
        n = self.bucket(min(probe, self.buckets[-1]))
        rng = np.random.default_rng(seed)
        obs = rng.integers(0, 256, (n, *cfg.stored_obs_shape), np.uint8)
        la = np.zeros((n, self.action_dim), np.float32)
        la[np.arange(n), rng.integers(self.action_dim, size=n)] = 1.0
        lr = rng.normal(size=n).astype(np.float32)
        hid = (rng.normal(size=(n, 2, cfg.lstm_layers, cfg.hidden_dim))
               .astype(np.float32) * 0.1)
        if isinstance(jax.tree.leaves(params)[0], np.ndarray):
            params = jax.device_put(params, jax.local_devices()[0])
        q_ref, _ = self._act(params, obs, la, lr, hid)
        q_bf16, _ = self._act(self._quantize(params), obs, la, lr, hid)
        return bool((np.asarray(q_ref).argmax(axis=1)
                     == np.asarray(q_bf16).argmax(axis=1)).all())

    @property
    def ready(self) -> bool:
        return self._params is not None

    # ---------------------------------------------------------------- act
    def bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} exceeds serve_max_batch="
                         f"{self.buckets[-1]}")

    def _pad(self, b: int):
        s = self._scratch.get(b)
        if s is None:
            cfg = self.cfg
            s = self._scratch[b] = dict(
                obs=np.zeros((b, *cfg.stored_obs_shape), np.uint8),
                last_action=np.zeros((b, self.action_dim), np.float32),
                last_reward=np.zeros(b, np.float32),
                hidden=np.zeros((b, 2, cfg.lstm_layers, cfg.hidden_dim),
                                np.float32))
        return s

    def act(self, obs: np.ndarray, last_action: np.ndarray,
            last_reward: np.ndarray, hidden: np.ndarray
            ) -> Tuple[np.ndarray, np.ndarray]:
        """One continuous batch: ``n`` ragged rows in, ``(q, new_hidden)``
        rows out.  Pads to the covering bucket (pad rows carry zeros —
        stale garbage would still be discarded, zeros keep the scratch
        deterministic) and pays ONE device→host fetch per batch
        regardless of size, the serve plane's own invariant."""
        if self._params is None:
            raise RuntimeError("no params published yet")
        import jax

        n = len(obs)
        b = self.bucket(n)
        s = self._pad(b)
        s["obs"][:n] = obs
        s["last_action"][:n] = last_action
        s["last_reward"][:n] = last_reward
        s["hidden"][:n] = hidden
        if n < b:
            s["obs"][n:] = 0
            s["last_action"][n:] = 0.0
            s["last_reward"][n:] = 0.0
            s["hidden"][n:] = 0.0
        with TRANSFER_GUARD.disallow("serving.act"):
            # the batch's declared H2D: the padded scratch rows ride the
            # dispatch as implicit transfers of numpy args
            with HOST_TRANSFERS.allowed("serving.act_put"):
                q, new_hidden = self._act(self._params, s["obs"],
                                          s["last_action"],
                                          s["last_reward"], s["hidden"])
            # ONE explicit D2H for both outputs (audit r19: was two
            # implicit np.asarray syncs — same values, one blocking
            # fetch, and explicit transfers stay guard-exempt)
            with HOST_TRANSFERS.allowed("serving.act_fetch"):
                q, new_hidden = jax.device_get((q, new_hidden))
        return q[:n], new_hidden[:n]

    def warmup(self) -> None:
        """Pre-compile every bucket entry point (server start-up, before
        traffic): the first real request must not eat a multi-second XLA
        compile inside its deadline."""
        cfg = self.cfg
        for b in self.buckets:
            s = self._pad(b)
            self._act(self._params, s["obs"], s["last_action"],
                      s["last_reward"], s["hidden"])
