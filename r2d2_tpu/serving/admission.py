"""Admission control for the session tier: bounded queue, deadlines,
breaker.

The resilience layer's lesson (utils/resilience.py, PR 7) applied to
external traffic: overload and partial failure are normal, and the
correct response is never an unbounded wait — it is *bounded queueing*
(a full pending queue sheds with ``STATUS_SHED``/429, counted in
``serving.rejected``), *per-request deadlines* (a request that sat past
``cfg.serve_request_deadline`` is answered ``STATUS_EXPIRED``/408
instead of served stale — the client already gave up on it), and a
*circuit breaker* around the act path itself (an act executable that
starts failing opens the circuit; while open every act request sheds
fast instead of queueing behind a broken device, and one half-open
probe batch per cooldown re-closes it).

Health is three-state through the existing ``/healthz`` contract
(docs/OBSERVABILITY.md): ``ok``; ``degraded`` (HTTP 200 — the tier is
shedding, evicting or running an open circuit, i.e. degrading by
design, and must NOT be evicted by a load balancer for it); ``failing``
(HTTP 503 — the serve loop itself is dead).  The server composes the
final verdict; this module contributes the admission-side signals.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.utils.resilience import CLOSED, CircuitBreaker

# how long a degrade signal (a shed, an eviction, a reap burst) keeps
# /healthz reporting "degraded" after the event — long enough for a
# scrape cadence to observe it, short enough to recover the "ok" verdict
# once the pressure passes
DEGRADE_WINDOW_S = 15.0


class Request:
    """One queued act request: the decoded payload (copied out of the
    frame — the frame buffer is the reader's), its provenance, and its
    admission clock."""

    __slots__ = ("conn_id", "sid", "seq", "reset", "obs", "last_action",
                 "last_reward", "recv_ts")

    def __init__(self, conn_id: int, sid: int, seq: int, reset: bool,
                 obs: np.ndarray, last_action: np.ndarray,
                 last_reward: float, recv_ts: Optional[float] = None):
        self.conn_id = conn_id
        self.sid = sid
        self.seq = seq
        self.reset = reset
        self.obs = obs
        self.last_action = last_action
        self.last_reward = last_reward
        self.recv_ts = time.monotonic() if recv_ts is None else recv_ts


class AdmissionController:
    """Bounded pending queue + request deadlines + the act breaker."""

    def __init__(self, cfg: Config,
                 breaker: Optional[CircuitBreaker] = None,
                 on_transition=None):
        self.cfg = cfg
        self.limit = int(cfg.serve_pending_max)
        self.deadline_s = float(cfg.serve_request_deadline)
        self._lock = threading.Lock()
        self._pending: deque = deque()
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            name="serving.act", cooldown=2.0, on_transition=on_transition)
        self.rejected = 0          # 429 sheds (queue full / breaker open)
        self.expired = 0           # 408 deadline drops
        self._last_degrade = 0.0   # monotonic ts of the last shed/derate

    # ------------------------------------------------------------- enqueue
    def submit(self, req: Request) -> bool:
        """Admit one act request into the pending queue.  False = shed
        (queue at its bound, or the act circuit is open) — the caller
        replies ``STATUS_SHED`` NOW; the client never waits on a queue
        that cannot drain.  A HALF_OPEN circuit admits normally: the
        batch loop's ``allow_attempt`` turns the next batch into the
        probe."""
        from r2d2_tpu.utils.resilience import OPEN

        if self.breaker.state == OPEN:
            with self._lock:
                self.rejected += 1
                self._last_degrade = time.monotonic()
            return False
        with self._lock:
            if len(self._pending) >= self.limit:
                self.rejected += 1
                self._last_degrade = time.monotonic()
                return False
            self._pending.append(req)
            return True

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def requeue_front(self, reqs: List[Request]) -> None:
        """Return drained-but-unserved requests to the FRONT of the queue
        in their original order (the batcher serves one request per
        session per turn — a pipelined second step waits one turn, and
        its deadline still runs from its original arrival)."""
        with self._lock:
            for req in reversed(reqs):
                self._pending.appendleft(req)

    # --------------------------------------------------------------- drain
    def drain(self, max_n: int, now: Optional[float] = None
              ) -> Tuple[List[Request], List[Request]]:
        """Pop up to ``max_n`` serviceable requests: ``(ready, expired)``.
        Expired requests (older than the per-request deadline) never
        reach the act path — they are answered ``STATUS_EXPIRED`` and
        counted; serving them would burn batch capacity on replies the
        client has already written off."""
        now = time.monotonic() if now is None else now
        ready: List[Request] = []
        expired: List[Request] = []
        with self._lock:
            while self._pending and len(ready) < max_n:
                req = self._pending.popleft()
                if now - req.recv_ts > self.deadline_s:
                    expired.append(req)
                    self.expired += 1
                    self._last_degrade = now
                else:
                    ready.append(req)
        return ready, expired

    # -------------------------------------------------------------- health
    def note_degrade(self) -> None:
        """An eviction / reap burst / act failure happened: hold the
        ``degraded`` verdict for the observation window."""
        with self._lock:
            self._last_degrade = time.monotonic()

    def degraded(self) -> bool:
        with self._lock:
            recent = (time.monotonic() - self._last_degrade
                      < DEGRADE_WINDOW_S and self._last_degrade > 0)
        return recent or self.breaker.state != CLOSED

    def stats(self) -> dict:
        with self._lock:
            return dict(pending=len(self._pending), rejected=self.rejected,
                        expired=self.expired,
                        circuit=self.breaker.state_name)
