"""Session-serving tier: the trained Q-network as a product.

``r2d2_tpu serve --ckpt-dir ...`` runs a :class:`SessionServer` —
thousands of concurrent episodic sessions with session-resident
recurrent state, continuous batching, admission control and a
``serving.*`` telemetry namespace — over a training run's checkpoints.
See docs/SERVING.md for the architecture and ``serving/server.py`` for
the composition.
"""
from r2d2_tpu.serving.admission import AdmissionController, Request
from r2d2_tpu.serving.batcher import ContinuousBatcher, bucket_sizes
from r2d2_tpu.serving.client import SessionClient, SessionClientError
from r2d2_tpu.serving.server import SessionServer, run_server
from r2d2_tpu.serving.store import SessionStore

__all__ = [
    "AdmissionController",
    "ContinuousBatcher",
    "Request",
    "SessionClient",
    "SessionClientError",
    "SessionServer",
    "SessionStore",
    "bucket_sizes",
    "run_server",
]
