"""Client side of the session tier's wire protocol.

A :class:`SessionClient` is what an external frontend embeds (and what
``tools/session_load_gen.py`` drives by the hundred): one loopback TCP
connection multiplexing any number of episodic sessions, requests tagged
``(session_id, seq)`` so acts may be pipelined across sessions and
matched to replies out of order.  Every wait is bounded (a per-call
deadline; the server's own per-request deadline means a late reply was
already written off server-side too) and every frame is CRC-verified on
receipt — a garbled reply is dropped and surfaces as a timeout, never as
a consumed q-row of garbage.

One instance is single-threaded by design: the load generator gives each
worker thread its own client, which also makes a worker's disconnect
(the ``kill_session_client`` chaos site) reap exactly that worker's
sessions server-side.
"""
from __future__ import annotations

import socket
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.serving.wire import (
    EMPTY_SPEC,
    FLAG_RESET,
    MSG_ACT,
    MSG_CLOSE,
    MSG_OPEN,
    MSG_RSP,
    FrameReader,
    WireClosed,
    WireGarbled,
    decode_frame,
    encode_frame,
    send_frame,
    session_request_spec,
    session_response_spec,
)
from r2d2_tpu.utils.resilience import Deadline


class SessionClientError(Exception):
    """A client-side protocol failure (timeout / closed connection)."""


class SessionClient:
    """One connection to a :class:`~r2d2_tpu.serving.server.
    SessionServer`, multiplexing many sessions (module docstring)."""

    def __init__(self, cfg: Config, action_dim: int, host: str, port: int,
                 timeout: float = 30.0):
        self.cfg = cfg
        self.action_dim = action_dim
        self.timeout = float(timeout)
        self.sock = socket.create_connection((host, port))
        self.sock.settimeout(0.05)
        self.reader = FrameReader(self.sock)
        self._wlock = threading.Lock()
        self._req_spec = session_request_spec(cfg, action_dim)
        self._rsp_spec = session_response_spec(cfg, action_dim)
        self._seq = 0
        # (sid, seq) -> (status, q or None): replies already pumped in
        self._inbox: Dict[Tuple[int, int], Tuple[int,
                                                 Optional[np.ndarray]]] = {}

    # ----------------------------------------------------------------- io
    def _send(self, frame: bytes) -> None:
        try:
            with self._wlock:
                send_frame(self.sock, frame)
        except OSError as e:
            raise SessionClientError(f"send failed: {e}")

    def _pump(self) -> None:
        """Drain every complete reply frame into the inbox (one bounded
        recv — the socket timeout is the poll step)."""
        try:
            frames = self.reader.poll()
        except WireClosed as e:
            raise SessionClientError(f"server closed the connection: {e}")
        for body in frames:
            # an OK act reply carries the q payload; every other reply is
            # payload-free — the body length picks the spec
            for spec in (self._rsp_spec, EMPTY_SPEC):
                try:
                    header, views = decode_frame(spec, body)
                except WireGarbled:
                    continue
                kind, sid, seq, status = header
                if kind == MSG_RSP:
                    q = (np.array(views["q"]) if "q" in views else None)
                    self._inbox[(sid, seq)] = (int(status), q)
                break
            # both specs failing CRC = a genuinely garbled reply: drop it
            # (the pending call times out, the server already moved on)

    def _await(self, sid: int, seq: int,
               timeout: Optional[float] = None
               ) -> Tuple[int, Optional[np.ndarray]]:
        deadline = Deadline(self.timeout if timeout is None else timeout)
        while True:
            hit = self._inbox.pop((sid, seq), None)
            if hit is not None:
                return hit
            if deadline.expired:
                raise SessionClientError(
                    f"no reply for session {sid} seq {seq} within "
                    f"{deadline.budget:.1f}s")
            self._pump()

    # ------------------------------------------------------------ protocol
    def next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def open_session(self, sid: int,
                     timeout: Optional[float] = None) -> int:
        seq = self.next_seq()
        self._send(encode_frame(EMPTY_SPEC, (MSG_OPEN, sid, seq, 0)))
        status, _ = self._await(sid, seq, timeout)
        return status

    def close_session(self, sid: int,
                      timeout: Optional[float] = None) -> int:
        seq = self.next_seq()
        self._send(encode_frame(EMPTY_SPEC, (MSG_CLOSE, sid, seq, 0)))
        status, _ = self._await(sid, seq, timeout)
        return status

    def send_act(self, sid: int, obs: np.ndarray, last_action: np.ndarray,
                 last_reward: float, reset: bool = False) -> int:
        """Fire one act request WITHOUT waiting (pipelining across
        sessions); returns the seq to :meth:`recv` on."""
        seq = self.next_seq()
        self._send(encode_frame(
            self._req_spec, (MSG_ACT, sid, seq,
                             FLAG_RESET if reset else 0),
            dict(obs=obs, last_action=last_action,
                 last_reward=np.asarray([last_reward], np.float32))))
        return seq

    def recv(self, sid: int, seq: int, timeout: Optional[float] = None
             ) -> Tuple[int, Optional[np.ndarray]]:
        """``(status, q or None)`` for a pipelined :meth:`send_act`."""
        return self._await(sid, seq, timeout)

    def poll_reply(self, sid: int, seq: int
                   ) -> Optional[Tuple[int, Optional[np.ndarray]]]:
        """Non-blocking :meth:`recv`: one bounded pump, then ``(status,
        q)`` if the reply is in, else None — the load generator's
        event-loop primitive (hundreds of sessions per worker thread
        without a thread per session)."""
        self._pump()
        return self._inbox.pop((sid, seq), None)

    def act(self, sid: int, obs: np.ndarray, last_action: np.ndarray,
            last_reward: float, reset: bool = False,
            timeout: Optional[float] = None
            ) -> Tuple[int, Optional[np.ndarray]]:
        """One synchronous act round-trip: ``(status, q or None)``."""
        seq = self.send_act(sid, obs, last_action, last_reward, reset)
        return self._await(sid, seq, timeout)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def abandon(self) -> None:
        """Drop the connection abruptly — the ``kill_session_client``
        chaos shape: no CLOSE for any live session; the server must reap
        them on the disconnect, never leak their hidden slots."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.close()
