"""Cross-process structured event tracing (docs/OBSERVABILITY.md §Tracing).

The metrics plane (ISSUE 5) reports *rates*; this module answers *where
a microsecond went*: a low-overhead, preallocated ring-buffered event
tracer usable from every process of the fabric — trainer, fleet
subprocesses, the inference service, replay shard owners — whose rings
merge into ONE Chrome-trace-event JSON viewable in Perfetto, with one
process track per ring and correct relative timestamps.

Design points:

- **Preallocated ring, near-zero disarmed cost.**  Each process owns one
  fixed-capacity ring of fixed-size records (:data:`EVENT_DTYPE`); the
  fast path is one attribute check (``self.armed``) when disarmed, and
  one locked structured-row write when armed.  Nothing allocates per
  event and nothing is recorded outside a capture window.
- **Shared-memory slots, stats-slab conventions.**  Subprocess rings
  live in a :class:`TraceSlab` — one shm segment, one slot per process,
  laid out by :func:`~r2d2_tpu.replay.block.slot_layout` with a
  ``(seq, count, crc32)`` publish header exactly like the telemetry
  stats slab: the writer publishes its header CRC-last, and a torn or
  garbled slot (writer SIGKILLed mid-publish, corrupted slab) fails CRC
  at harvest and is **dropped and counted**, never mis-merged.
- **Clock model.**  Every writer records events against its own
  ``time.perf_counter()`` and publishes a spawn-time clock pair
  ``(t0_perf, t0_wall)`` in its slot header — the clock-offset
  handshake.  The merger maps each event to the shared wall clock as
  ``t0_wall + (ts - t0_perf)``; per-writer mapping is affine and
  increasing, so each track stays monotone, and all processes of one
  host share ``time.time()`` so cross-track ordering is correct to NTP
  noise (sub-ms on one host — far below the hop latencies traced).
- **Capture windows.**  The slab header carries ``(capture_id, armed)``
  control words the trainer writes and every writer polls at its
  existing publish cadence (fleet burst / shard loop) — arming is
  fabric-wide without a new channel.  A bumped ``capture_id`` resets
  the writer's ring so each capture is self-contained.
- **Flow (block-lineage) events.**  A record may carry a ``flow`` id
  plus a flow phase (``s``/``t``/``f``); the merger emits the matching
  Chrome flow events so one block's life — env steps → cut → fleet
  slab → ingest → route → shard add → sample → priority feedback —
  renders as a single arrow chain across the process tracks.  Trace
  ids are **incarnation-tagged** (:meth:`EventTracer.next_trace_id`)
  so a respawned fleet's flows can never alias its dead predecessor's.

The process-wide :data:`EVENTS` singleton is what instrumented code
records against (``EVENTS.complete("ingest.block", ...)``); ``train()``
attaches it to slot 0 of the run's slab and subprocess workers attach
to the slot their plane assigned.  The graftlint
``telemetry-discipline`` rule extends to this API: event names must be
string literals — variable parts go in ``flow``/``arg``.
"""
from __future__ import annotations

import json
import os
import threading
import time
from multiprocessing import shared_memory
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from r2d2_tpu.replay.block import payload_crc32, slot_layout, slot_views

# One trace record.  ``name`` is a fixed-size byte string (no pickling,
# no string table to keep coherent across processes); ``ph`` is the
# Chrome phase (X complete / i instant); ``fph`` an optional flow phase
# (s start / t step / f end) bound to ``flow``; ``ts`` is the writer's
# LOCAL perf_counter seconds, ``dur`` seconds.
EVENT_DTYPE = np.dtype([
    ("name", "S48"), ("ph", "S1"), ("fph", "S1"),
    ("ts", np.float64), ("dur", np.float64),
    ("flow", np.int64), ("arg", np.int64),
], align=True)

# control words at the head of the slab, written by the trainer and
# polled by every writer (at publish cadence — never per event)
_CTRL_SPEC = (("capture_id", (1,), np.int64),
              ("armed", (1,), np.int64))


def _slot_spec(capacity: int):
    """One writer slot: publish header + clock pair + identity + the
    event ring + CRC (written LAST — the stats-slab discipline)."""
    return (("seq", (1,), np.int64),
            ("count", (1,), np.int64),        # total events ever written
            ("clock", (2,), np.float64),      # (t0_perf, t0_wall)
            ("incarnation", (1,), np.int64),
            ("name", (1,), "S32"),            # track name, e.g. b"fleet0"
            ("events", (capacity,), EVENT_DTYPE),
            ("crc32", (1,), np.uint32))


def _slot_crc(v: dict) -> int:
    """CRC over the publish header + clock + the WHOLE event region
    (unused slots are deterministic bytes, so covering them is free of
    used-length bookkeeping)."""
    return payload_crc32(
        (int(v["seq"][0]), int(v["count"][0]), int(v["incarnation"][0])),
        [v["clock"], v["events"].view(np.uint8)])


class TraceSlab:
    """Trainer-side owner of the shared-memory trace segment: the two
    control words plus ``num_slots`` writer slots."""

    def __init__(self, num_slots: int, capacity: int):
        self.num_slots = num_slots
        self.capacity = capacity
        self.ctrl_nbytes, self.ctrl_offsets = slot_layout(_CTRL_SPEC)
        self.spec = _slot_spec(capacity)
        self.slot_nbytes, self.offsets = slot_layout(self.spec)
        self.shm = shared_memory.SharedMemory(
            create=True,
            size=self.ctrl_nbytes + num_slots * self.slot_nbytes)
        self._ctrl = slot_views(self.shm.buf, _CTRL_SPEC,
                                self.ctrl_offsets, self.ctrl_nbytes, 0)
        self._closed = False

    # ------------------------------------------------------------- control
    def set_armed(self, armed: bool, capture_id: Optional[int] = None
                  ) -> None:
        if capture_id is not None:
            self._ctrl["capture_id"][0] = capture_id
        self._ctrl["armed"][0] = 1 if armed else 0

    def writer_info(self, slot: int, incarnation: int, name: str
                    ) -> Tuple[str, int, int, int, str]:
        """Picklable attach handle for a subprocess writer."""
        return (self.shm.name, slot, self.capacity, incarnation, name)

    # ------------------------------------------------------------- harvest
    def _slot_views(self, slot: int) -> dict:
        return slot_views(self.shm.buf[self.ctrl_nbytes:], self.spec,
                          self.offsets, self.slot_nbytes, slot)

    def harvest(self) -> Tuple[List[Dict[str, Any]], int]:
        """Read every published slot.  Returns ``(tracks, dropped)`` —
        a torn/garbled slot (CRC mismatch: writer SIGKILLed mid-publish
        or corrupted slab) is dropped and counted, never mis-merged;
        never-published slots (seq == 0) are skipped silently."""
        tracks: List[Dict[str, Any]] = []
        dropped = 0
        for s in range(self.num_slots):
            v = self._slot_views(s)
            seq = int(v["seq"][0])
            if seq <= 0:
                continue
            # raw-byte copy before the CRC check: a field-wise structured
            # copy would leave the dtype's alignment padding
            # uninitialised and the CRC could never match
            events = np.array(v["events"].view(np.uint8)).view(EVENT_DTYPE)
            snap = dict(seq=v["seq"].copy(), count=v["count"].copy(),
                        clock=v["clock"].copy(),
                        incarnation=v["incarnation"].copy(),
                        name=v["name"].copy(), events=events)
            if int(v["crc32"][0]) != _slot_crc(snap):
                dropped += 1
                continue
            count = int(snap["count"][0])
            used = min(count, self.capacity)
            # ring order: oldest surviving event first
            order = (np.arange(count - used, count) % self.capacity
                     if count > self.capacity else np.arange(used))
            tracks.append(dict(
                slot=s,
                name=snap["name"][0].decode("utf-8", "replace"),
                incarnation=int(snap["incarnation"][0]),
                t0_perf=float(snap["clock"][0]),
                t0_wall=float(snap["clock"][1]),
                overflow=max(0, count - self.capacity),
                events=events[order]))
        return tracks, dropped

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._ctrl = None
        try:
            self.shm.close()
        except BufferError:
            pass          # a late view holds the mapping; unlink frees it
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


class EventTracer:
    """One process's event recorder (module docstring).

    Constructed local (private ring, disarmed) so the process-wide
    :data:`EVENTS` singleton is always safe to record against;
    :meth:`attach` re-backs the SAME object with a shm slab slot so
    every module-level reference picks up the run's slab without
    rebinding.
    """

    def __init__(self, capacity: int = 1024, name: str = "local"):
        self._lock = threading.Lock()
        self.armed = False
        self._capacity = int(capacity)
        self._events = np.zeros(self._capacity, EVENT_DTYPE)
        self._n = 0
        self._flushed = -1
        self._seq = 0
        self._capture_seen = -1
        self._trace_counter = 0
        self._slot = 0
        self._incarnation = 0
        self._name = name
        self._shm: Optional[shared_memory.SharedMemory] = None
        self._views: Optional[dict] = None
        self._ctrl: Optional[dict] = None
        self.t0_perf = time.perf_counter()
        self.t0_wall = time.time()

    # ------------------------------------------------------------ backing
    def attach(self, info: Tuple[str, int, int, int, str]) -> None:
        """Back this tracer with slab slot ``info`` (writer side of
        :meth:`TraceSlab.writer_info`); stamps the clock handshake."""
        shm_name, slot, capacity, incarnation, name = info
        self.detach()
        with self._lock:
            self._shm = shared_memory.SharedMemory(name=shm_name)
            spec = _slot_spec(capacity)
            slot_nbytes, offsets = slot_layout(spec)
            ctrl_nbytes, ctrl_offsets = slot_layout(_CTRL_SPEC)
            self._views = slot_views(self._shm.buf[ctrl_nbytes:], spec,
                                     offsets, slot_nbytes, slot)
            self._ctrl = slot_views(self._shm.buf, _CTRL_SPEC,
                                    ctrl_offsets, ctrl_nbytes, 0)
            self._capacity = int(capacity)
            self._events = self._views["events"]
            self._n = 0
            self._flushed = -1
            self._seq = 0
            self._capture_seen = -1
            self._slot = int(slot)
            self._incarnation = int(incarnation)
            self._name = name
            self.t0_perf = time.perf_counter()
            self.t0_wall = time.time()
            self._views["clock"][0] = self.t0_perf
            self._views["clock"][1] = self.t0_wall
            self._views["incarnation"][0] = self._incarnation
            self._views["name"][0] = name.encode("utf-8")[:32]
        self.poll()

    def detach(self) -> None:
        with self._lock:
            self.armed = False
            self._views = None
            self._ctrl = None
            self._events = np.zeros(0, EVENT_DTYPE)
            self._capacity = 0
            if self._shm is not None:
                try:
                    self._shm.close()
                except Exception:
                    pass
                self._shm = None

    # ------------------------------------------------------------ control
    def poll(self) -> None:
        """Refresh ``armed`` from the slab control words (called at the
        owning loop's publish cadence — never per event).  A bumped
        capture id resets the ring so each capture is self-contained."""
        ctrl = self._ctrl
        if ctrl is None:
            return
        try:
            capture = int(ctrl["capture_id"][0])
            armed = bool(ctrl["armed"][0])
        except (ValueError, TypeError):     # slab closed under us
            return
        with self._lock:
            if capture != self._capture_seen:
                self._capture_seen = capture
                self._n = 0
                self._flushed = -1
            self.armed = armed

    def arm_local(self, capture_id: int) -> None:
        """Direct arming for the in-process (trainer) tracer — the slab
        control words cover subprocess writers; the trainer's own ring
        arms synchronously so no events at the window edges are lost."""
        with self._lock:
            if capture_id != self._capture_seen:
                self._capture_seen = capture_id
                self._n = 0
                self._flushed = -1
            self.armed = True

    def disarm_local(self) -> None:
        self.armed = False

    # ------------------------------------------------------------- record
    def instant(self, name: str, flow: int = 0, fph: str = "",
                arg: int = 0) -> None:
        """One instant event ``now`` (armed fast path: a single attribute
        check when disarmed)."""
        if not self.armed:
            return
        self._record(name, b"i", time.perf_counter(), 0.0, flow, fph, arg)

    def complete(self, name: str, ts: float, dur: float, flow: int = 0,
                 fph: str = "", arg: int = 0) -> None:
        """One complete (``X``) event: ``ts`` is the span start from
        ``time.perf_counter()``, ``dur`` seconds."""
        if not self.armed:
            return
        self._record(name, b"X", ts, dur, flow, fph, arg)

    def _record(self, name, ph, ts, dur, flow, fph, arg) -> None:
        with self._lock:
            if not self.armed or self._capacity <= 0:
                return
            i = self._n % self._capacity
            ev = self._events[i]
            ev["name"] = name.encode("utf-8")[:48]
            ev["ph"] = ph
            ev["fph"] = fph.encode("ascii")[:1] if fph else b""
            ev["ts"] = ts
            ev["dur"] = dur
            ev["flow"] = flow
            ev["arg"] = arg
            self._n += 1

    def next_trace_id(self) -> int:
        """A fabric-unique flow id: slot- and incarnation-tagged so a
        respawned fleet's ids can never alias its dead predecessor's
        (the merger would otherwise stitch two different blocks' hops
        into one arrow chain)."""
        with self._lock:
            self._trace_counter += 1
            return (((self._slot + 1) & 0x7FFF) << 48
                    | (self._incarnation & 0xFFFF) << 32
                    | (self._trace_counter & ((1 << 32) - 1)))

    # -------------------------------------------------------------- flush
    def flush(self) -> None:
        """Publish the ring header (count, seq, CRC last) so the trainer
        can harvest a consistent snapshot.  Cheap no-op when nothing new
        was recorded; shm-backed writers call it at their loop's publish
        cadence."""
        if self._views is None:
            return
        with self._lock:
            if self._n == self._flushed:
                return
            v = self._views
            self._seq += 1
            v["seq"][0] = self._seq
            v["count"][0] = self._n
            v["crc32"][0] = _slot_crc(v)
            self._flushed = self._n

    def local_events(self) -> np.ndarray:
        """The used ring contents in order (oldest first) — the harvest
        path for a local (non-shm) tracer, e.g. unit tests."""
        with self._lock:
            used = min(self._n, self._capacity)
            if self._n > self._capacity:
                order = (np.arange(self._n - used, self._n)
                         % self._capacity)
                return np.array(self._events[order])
            return np.array(self._events[:used])


# the process-wide recorder every instrumented call site uses; train()
# attaches it to the run's slab (slot 0), subprocess workers attach to
# the slot their plane assigned — always safe to record against
EVENTS = EventTracer(capacity=0, name="detached")


# --------------------------------------------------------------------------
# merge: rings -> Chrome trace event JSON (Perfetto-loadable)
# --------------------------------------------------------------------------

def merge_tracks(tracks: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge harvested rings into one Chrome-trace-event object.

    Per-track mapping to the shared wall clock is affine and increasing
    (``t0_wall + (ts - t0_perf)``), so each track's events stay monotone;
    timestamps are microseconds relative to the earliest event.  Each
    ring becomes a process track (``pid`` = slot, ``tid`` =
    incarnation) with ``process_name`` metadata; records carrying a
    flow id additionally emit the matching Chrome flow event
    (``s``/``t``/``f``) so block lineage renders as one arrow chain."""
    walls: List[float] = []
    for t in tracks:
        ev = t["events"]
        if len(ev):
            walls.append(t["t0_wall"]
                         + float(ev["ts"].min()) - t["t0_perf"])
    base = min(walls) if walls else 0.0
    out: List[Dict[str, Any]] = []
    for t in tracks:
        pid, tid = int(t["slot"]), int(t["incarnation"])
        out.append(dict(ph="M", name="process_name", pid=pid, tid=tid,
                        args=dict(name=t["name"])))
        out.append(dict(ph="M", name="thread_name", pid=pid, tid=tid,
                        args=dict(name=f"inc{tid}")))
        offset = t["t0_wall"] - t["t0_perf"] - base
        for ev in t["events"]:
            ts_us = (float(ev["ts"]) + offset) * 1e6
            name = ev["name"].decode("utf-8", "replace")
            ph = ev["ph"].decode("ascii", "replace") or "i"
            rec: Dict[str, Any] = dict(name=name, cat="r2d2", ph=ph,
                                       ts=ts_us, pid=pid, tid=tid)
            if ph == "X":
                rec["dur"] = float(ev["dur"]) * 1e6
            if ph == "i":
                rec["s"] = "t"
            args = {}
            if int(ev["flow"]):
                args["trace_id"] = int(ev["flow"])
            if int(ev["arg"]):
                args["arg"] = int(ev["arg"])
            if args:
                rec["args"] = args
            out.append(rec)
            fph = ev["fph"].decode("ascii", "replace")
            if fph in ("s", "t", "f") and int(ev["flow"]):
                flow: Dict[str, Any] = dict(
                    name="block", cat="block", ph=fph,
                    id=int(ev["flow"]), pid=pid, tid=tid,
                    # just inside the slice so the arrow binds to it
                    ts=ts_us + min(1.0, float(ev["dur"]) * 1e6 / 2))
                if fph == "f":
                    flow["bp"] = "e"
                out.append(flow)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# --------------------------------------------------------------------------
# capture controllers (the /tracez and /profilez machinery)
# --------------------------------------------------------------------------

class TraceController:
    """Arms bounded fabric-wide capture windows and dumps the merged
    trace (``/tracez`` and ``--trace-steps``).

    ``step_fn`` reads the run's train-step counter; a capture armed for
    N steps disarms once the counter advances by N (or after a
    wall-clock backstop, so a stalled learner cannot pin a window open
    forever).  :meth:`poll` drives the state machine from a supervised
    fabric loop."""

    GRACE_SECONDS = 0.6       # post-disarm window for writers to notice
                              # the control word and flush their final CRC
    MAX_CAPTURE_SECONDS = 120.0

    def __init__(self, slab: TraceSlab, step_fn: Callable[[], int],
                 out_dir: str, tracer: Optional[EventTracer] = None):
        self.slab = slab
        self.step_fn = step_fn
        self.out_dir = out_dir
        self.tracer = tracer if tracer is not None else EVENTS
        self._lock = threading.Lock()
        self._capture_id = 0
        self._armed = False
        self._closing = False     # a window past its target, mid-harvest
        self._target_step = 0
        self._deadline = 0.0
        # dumps number on from whatever already exists in out_dir: a
        # resumed run (or a later chaos_soak round reusing the ckpt dir)
        # must not overwrite earlier captures — and a soak's per-round
        # dump check must never false-pass on a stale trace_1.json
        self._dump_n = 0
        try:
            for f in os.listdir(out_dir):
                if f.startswith("trace_") and f.endswith(".json"):
                    try:
                        self._dump_n = max(self._dump_n,
                                           int(f[len("trace_"):-5]))
                    except ValueError:
                        pass
        except OSError:
            pass
        self.last: Dict[str, Any] = {}

    def arm(self, steps: int) -> Dict[str, Any]:
        """Open a capture window of ``steps`` train steps.  Returns the
        armed status, or an error dict when a window is already open —
        including one in its close/harvest phase: arming there would
        bump the capture id and make every writer reset its ring while
        the previous capture is still being read out."""
        steps = max(1, int(steps))
        with self._lock:
            if self._armed or self._closing:
                return dict(error="capture already in progress",
                            capture_id=self._capture_id)
            self._capture_id += 1
            self._armed = True
            self._target_step = self.step_fn() + steps
            self._deadline = time.monotonic() + self.MAX_CAPTURE_SECONDS
            self.slab.set_armed(True, capture_id=self._capture_id)
            self.tracer.arm_local(self._capture_id)
            return dict(armed=True, steps=steps,
                        capture_id=self._capture_id)

    def poll(self, force: bool = False) -> Optional[str]:
        """Close the window once the step target (or the wall-clock
        backstop) is reached: disarm fabric-wide, give writers a flush
        grace, harvest, merge, dump.  Returns the dump path when a
        capture completed this poll.  ``force`` closes an open window
        regardless of progress — the shutdown path, so a capture armed
        near the end of a short run still dumps."""
        with self._lock:
            if not self._armed:
                return None
            if (not force and self.step_fn() < self._target_step
                    and time.monotonic() < self._deadline):
                return None
            self._armed = False
            self._closing = True   # arm() refuses until the harvest
            capture_id = self._capture_id       # below has read the slab
        self.slab.set_armed(False)
        self.tracer.disarm_local()
        self.tracer.flush()
        time.sleep(self.GRACE_SECONDS)
        try:
            # a CRC failure here is usually a LIVE writer mid-flush (it
            # has not polled the disarm word yet), not corruption —
            # re-read until the slab settles; only a slot that stays
            # torn is dropped
            for _ in range(4):
                tracks, dropped = self.slab.harvest()
                if dropped == 0:
                    break
                time.sleep(0.3)
            trace = merge_tracks(tracks)
            os.makedirs(self.out_dir, exist_ok=True)
            self._dump_n += 1
            path = os.path.join(self.out_dir,
                                f"trace_{self._dump_n}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(trace, f)
            os.replace(tmp, path)  # a reader never sees a torn dump
            self.last = dict(
                path=path, capture_id=capture_id,
                events=sum(len(t["events"]) for t in tracks),
                tracks=len(tracks), dropped_slabs=dropped,
                overflow=sum(t["overflow"] for t in tracks))
        finally:
            with self._lock:
                self._closing = False
        return path

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return dict(armed=self._armed or self._closing,
                        capture_id=self._capture_id,
                        target_step=self._target_step, last=dict(self.last))

    def close(self) -> None:
        self.slab.close()


class ProfileController:
    """On-demand ``jax.profiler`` device trace (``/profilez``), riding
    the long-dormant :func:`~r2d2_tpu.utils.trace.device_profile`
    context manager.  The capture loop's :meth:`poll` runs the bounded
    window synchronously (profiles are short and rare; the trace poll
    pauses for the duration — documented in docs/OBSERVABILITY.md)."""

    MAX_SECONDS = 60.0

    def __init__(self, out_dir: str):
        self.out_dir = out_dir
        self._lock = threading.Lock()
        self._want: Optional[float] = None
        self._n = 0
        self.last: Dict[str, Any] = {}

    def arm(self, seconds: float) -> Dict[str, Any]:
        seconds = min(max(0.1, float(seconds)), self.MAX_SECONDS)
        with self._lock:
            if self._want is not None:
                return dict(error="profile already in progress")
            self._want = seconds
            return dict(armed=True, seconds=seconds)

    def poll(self) -> Optional[str]:
        with self._lock:
            seconds = self._want
            if seconds is None:
                return None
            self._n += 1
            n = self._n
        from r2d2_tpu.utils.trace import device_profile

        path = os.path.join(self.out_dir, f"profile_{n}")
        try:
            os.makedirs(path, exist_ok=True)
            with device_profile(path):
                time.sleep(seconds)
            self.last = dict(path=path, seconds=seconds)
        except Exception as e:    # backend without profiler support
            self.last = dict(error=str(e))
        finally:
            with self._lock:
                self._want = None
        return path

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return dict(armed=self._want is not None, last=dict(self.last))
