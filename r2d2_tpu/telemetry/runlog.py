"""Persistent JSONL run log: the durable record of a training run.

``train()``'s stats entries previously lived only in an unbounded
in-process ``logs`` list — nothing survived the process, and a
SIGTERM→resume soak produced no continuous curve anywhere.  The
:class:`RunLog` is the source of truth instead:

- One JSON object per line, appended (NEVER truncated) to
  ``<ckpt_dir>/telemetry/run.jsonl`` — a resumed run reopens the same
  file in append mode, so a preempt/resume cycle yields ONE file whose
  ``training_steps`` curve continues monotonically across the restart.
- **Size-capped rotation**: when the active file would exceed
  ``max_bytes``, it is renamed to ``run.jsonl.1`` (older segments shift
  up, the oldest beyond ``keep`` is deleted) and a fresh file starts.
  Rotation preserves every byte ever written (up to the keep budget);
  the cap bounds any single file, not the history.
- Writes are line-atomic under the instance lock and flushed per entry,
  so a ``kill -9`` loses at most the entry being written and a tail
  (tools/r2d2_top.py) sees entries promptly.

:func:`read_entries` is the reader used by tests and tooling: it streams
the rotated segments oldest-first, skipping any torn final line.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional


class RunLog:
    """Append-only, size-rotated JSONL sink (see module docstring)."""

    def __init__(self, directory: str, filename: str = "run.jsonl",
                 max_bytes: int = 64_000_000, keep: int = 3):
        if max_bytes < 1024:
            raise ValueError("max_bytes must be >= 1024")
        self.directory = directory
        self.filename = filename
        self.max_bytes = max_bytes
        self.keep = max(1, keep)
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, filename)
        # append mode IS the resume semantics: a restarted run continues
        # the same file, never truncates it
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = self._fh.tell()

    def append(self, entry: Dict[str, Any]) -> None:
        """Write one entry as a single JSON line (flushed)."""
        line = json.dumps(entry, separators=(",", ":"), default=str) + "\n"
        with self._lock:
            if self._fh is None:
                return
            if self._size + len(line) > self.max_bytes and self._size > 0:
                self._rotate_locked()
            self._fh.write(line)
            self._fh.flush()
            self._size += len(line)

    def _rotate_locked(self) -> None:
        self._fh.close()
        # drop the segment past the keep budget, then shift .(k) → .(k+1)
        oldest = f"{self.path}.{self.keep}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for k in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{k}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{k + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def artifact_log(out: Optional[str], default: str) -> "RunLog":
    """A RunLog placed next to a tool's ``--out`` summary artifact
    (``OUT.json`` → ``OUT.telemetry.jsonl``; no --out → ``default`` in
    the cwd) — the shared path convention of tools/soak.py and
    tools/chaos_soak.py."""
    if out:
        base = out[:-5] if out.endswith(".json") else out
        directory, name = os.path.split(base + ".telemetry.jsonl")
        return RunLog(directory or ".", filename=name)
    return RunLog(".", filename=default)


def segment_paths(path: str) -> List[str]:
    """Every on-disk segment of a run log, oldest first: highest-numbered
    rotation down to the active file."""
    out: List[str] = []
    k = 1
    while os.path.exists(f"{path}.{k}"):
        k += 1
    for i in range(k - 1, 0, -1):
        out.append(f"{path}.{i}")
    if os.path.exists(path):
        out.append(path)
    return out


def read_entries(path: str, include_rotated: bool = True
                 ) -> Iterator[Dict[str, Any]]:
    """Stream entries oldest-first across the rotated segments; a torn
    final line (kill -9 mid-write) is skipped, not fatal."""
    paths = segment_paths(path) if include_rotated else (
        [path] if os.path.exists(path) else [])
    for p in paths:
        with open(p, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue


def tail_entry(path: str) -> Optional[Dict[str, Any]]:
    """The newest complete entry of the ACTIVE file (cheap seek-from-end
    read — what the live terminal view polls)."""
    if not os.path.exists(path):
        return None
    with open(path, "rb") as fh:
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        fh.seek(max(0, size - 65536))
        chunk = fh.read().decode("utf-8", errors="replace")
    for line in reversed(chunk.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None
