"""Cross-plane metrics registry: counters, gauges, histograms.

The fabric's observability before this module was a pile of disjoint
ad-hoc surfaces — ``Tracer`` spans, the ``RETRACES``/``HOST_TRANSFERS``
guard counters, ``ReplayBuffer.stats()``, chaos fire counts, supervisor
health dicts — each with its own shape and none scrapeable.  The
:class:`MetricsRegistry` absorbs all of them into ONE labeled namespace
with exactly three metric kinds (the Prometheus data model):

- **counter** — monotone accumulator.  Two write paths: :meth:`inc`
  (event increments) and :meth:`counter_max` (absorbing an *absolute*
  external counter, e.g. ``buffer.training_steps`` — the registry keeps
  the running max so re-absorbing the same snapshot is idempotent and a
  restarted source can never drag the series backwards).
- **gauge** — instantaneous value (:meth:`set_gauge`), may go down.
- **histogram** — fixed upper-bound buckets, allocation-light: one
  ``bisect`` + three scalar adds per :meth:`observe`, no per-sample
  storage — safe in the ingest hot loop.

Metric names are dotted lowercase (``actor.env_steps``); labels are
keyword arguments (``fleet="0"``).  The telemetry-discipline graftlint
rule (r2d2_tpu/analysis/telemetry_discipline.py) requires the name
argument at every call site to be a string literal — the namespace is a
registry, not a format-string free-for-all, so a grep for a metric name
always finds its producers.

Rendering: :meth:`snapshot` (plain JSON-able dict — the ``/statusz``
payload) and :meth:`render_prometheus` (text exposition format 0.0.4 —
the ``/metrics`` payload).  Prometheus names are sanitized from the
dotted form (``actor.env_steps`` → ``r2d2_actor_env_steps_total``).

Thread-safe throughout: one lock, scalar work inside it.
"""
from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

# seconds-scale latency buckets — the default when a histogram is not
# explicitly declared with its own bounds
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)

LabelKey = Tuple[Tuple[str, str], ...]
MetricKey = Tuple[str, LabelKey]


def _label_key(labels: Mapping[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Histogram:
    __slots__ = ("bounds", "counts", "total", "count")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = [float(b) for b in bounds]   # ascending upper edges
        self.counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.total += value
        self.count += 1

    def to_dict(self) -> dict:
        return dict(buckets=list(self.bounds), counts=list(self.counts),
                    sum=self.total, count=self.count)


class MetricsRegistry:
    """Thread-safe named counters / gauges / histograms (module doc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[MetricKey, float] = {}
        self._gauges: Dict[MetricKey, float] = {}
        self._histograms: Dict[MetricKey, _Histogram] = {}
        self._hist_bounds: Dict[str, Sequence[float]] = {}

    # ------------------------------------------------------------- writes
    def inc(self, name: str, n: float = 1, **labels) -> None:
        """Add ``n`` (>= 0) to a counter."""
        if n < 0:
            raise ValueError(f"counter {name!r}: negative increment {n}")
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def counter_max(self, name: str, value: float, **labels) -> None:
        """Absorb an ABSOLUTE external counter: the stored value becomes
        ``max(current, value)``, so repeated scrapes of the same source
        are idempotent and the series never regresses."""
        key = (name, _label_key(labels))
        with self._lock:
            cur = self._counters.get(key, 0)
            if value > cur:
                self._counters[key] = value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges[key] = float(value)

    def declare_histogram(self, name: str,
                          buckets: Sequence[float]) -> None:
        """Pin a histogram's bucket bounds (ascending upper edges); must
        run before the first :meth:`observe` of that name."""
        with self._lock:
            self._hist_bounds[name] = tuple(float(b) for b in buckets)

    def observe(self, name: str, value: float, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = _Histogram(
                    self._hist_bounds.get(name, DEFAULT_BUCKETS))
            h.observe(float(value))

    def observe_many(self, name: str, values, **labels) -> None:
        """Vectorised :meth:`observe` for a batch of samples (e.g. the
        per-row block ages of one sampled batch): one lock acquisition
        and one ``np.searchsorted`` pass instead of ``len(values)``
        locked bisects."""
        import numpy as np

        values = np.asarray(values, np.float64).ravel()
        if values.size == 0:
            return
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = _Histogram(
                    self._hist_bounds.get(name, DEFAULT_BUCKETS))
            idx = np.searchsorted(h.bounds, values, side="left")
            for i, c in zip(*np.unique(idx, return_counts=True)):
                h.counts[int(i)] += int(c)
            h.total += float(values.sum())
            h.count += int(values.size)

    def absorb_histogram(self, name: str, bounds: Sequence[float],
                         counts: Sequence[float],
                         total: Optional[float] = None, **labels) -> None:
        """Absorb an ABSOLUTE cumulative bucket-count vector from a
        monotone external source (e.g. the learnhealth diag's in-graph
        |TD| histogram): per-bucket max-merge — the :meth:`counter_max`
        idempotence rule applied bucketwise, so re-absorbing the same
        snapshot never double-counts and a restarted scrape never drags
        a bucket backwards.  ``counts`` must align to ``bounds`` plus
        the trailing +Inf bucket; ``total`` is the histogram's running
        value sum (kept monotone the same way)."""
        bounds_f = [float(b) for b in bounds]
        if len(counts) != len(bounds_f) + 1:
            raise ValueError(
                f"histogram {name!r}: {len(counts)} counts for "
                f"{len(bounds_f)} bounds (+Inf bucket expected)")
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None or h.bounds != bounds_f:
                h = self._histograms[key] = _Histogram(bounds_f)
            for i, c in enumerate(counts):
                h.counts[i] = max(h.counts[i], int(c))
            h.count = sum(h.counts)
            if total is not None:
                h.total = max(h.total, float(total))

    # bulk absorption of the pre-existing flat-dict surfaces ---------------
    def absorb_gauges(self, prefix: str,
                      mapping: Mapping[str, float], **labels) -> None:
        """Every numeric entry of ``mapping`` becomes gauge
        ``<prefix>.<key>`` — the Tracer-snapshot / health-dict path."""
        for k, v in mapping.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            self.set_gauge(f"{prefix}.{k}", v, **labels)  # graftlint: disable=telemetry-discipline -- bulk absorption of a fixed upstream surface, not a hot-loop key

    def absorb_counters(self, prefix: str,
                        mapping: Mapping[str, float], **labels) -> None:
        """Every numeric entry becomes counter ``<prefix>.<key>`` via
        :meth:`counter_max` (the entries are absolute totals)."""
        for k, v in mapping.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            self.counter_max(f"{prefix}.{k}", v, **labels)  # graftlint: disable=telemetry-discipline -- bulk absorption of a fixed upstream surface, not a hot-loop key

    # -------------------------------------------------------------- reads
    def get_counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0)

    def get_gauge(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get((name, _label_key(labels)))

    def snapshot(self) -> dict:
        """Plain JSON-able dump — the ``/statusz`` payload.  Keys are
        ``name{k=v,...}`` strings (label-free metrics keep the bare
        name)."""
        def fmt(key: MetricKey) -> str:
            name, labels = key
            if not labels:
                return name
            return name + "{" + ",".join(f"{k}={v}"
                                         for k, v in labels) + "}"

        with self._lock:
            return dict(
                counters={fmt(k): v for k, v in
                          sorted(self._counters.items())},
                gauges={fmt(k): v for k, v in sorted(self._gauges.items())},
                histograms={fmt(k): h.to_dict() for k, h in
                            sorted(self._histograms.items())},
            )

    # -------------------------------------------------- prometheus render
    @staticmethod
    def _prom_name(name: str, kind: str) -> str:
        out = ["r2d2_"]
        for ch in name:
            out.append(ch if ch.isalnum() or ch == "_" else "_")
        base = "".join(out)
        if kind == "counter" and not base.endswith("_total"):
            base += "_total"
        return base

    @staticmethod
    def _prom_labels(labels: LabelKey, extra: str = "") -> str:
        if not labels and not extra:
            return ""
        parts = [f'{k}="' + v.replace("\\", r"\\").replace('"', r'\"')
                 .replace("\n", r"\n") + '"' for k, v in labels]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}"

    @staticmethod
    def _prom_value(v: float) -> str:
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        return repr(float(v)) if isinstance(v, float) and v != int(v) \
            else str(int(v))

    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4 (the ``/metrics`` body): one
        ``# TYPE`` line per metric family, label values escaped, and the
        histogram bucket/sum/count triple per Prometheus convention."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._histograms.items())
        lines: List[str] = []
        typed: set = set()

        for kind, series in (("counter", counters), ("gauge", gauges)):
            for (name, labels), v in series:
                pname = self._prom_name(name, kind)
                if pname not in typed:
                    lines.append(f"# TYPE {pname} {kind}")
                    typed.add(pname)
                lines.append(pname + self._prom_labels(labels) + " "
                             + self._prom_value(v))
        for (name, labels), h in hists:
            base = self._prom_name(name, "histogram")
            if base not in typed:
                lines.append(f"# TYPE {base} histogram")
                typed.add(base)
            cum = 0
            for edge, c in zip(list(h.bounds) + ["+Inf"],
                               h.counts):
                cum += c
                le = ("+Inf" if edge == "+Inf"
                      else self._prom_value(float(edge)))
                lines.append(
                    base + "_bucket"
                    + self._prom_labels(labels, f'le="{le}"') + f" {cum}")
            lines.append(base + "_sum" + self._prom_labels(labels)
                         + " " + self._prom_value(h.total))
            lines.append(base + "_count" + self._prom_labels(labels)
                         + f" {h.count}")
        return "\n".join(lines) + "\n"
