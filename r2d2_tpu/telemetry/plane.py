"""The trainer-side telemetry plane: registry + run log + exporter.

One :class:`Telemetry` object per ``train()`` call, wired by the fabric:

- owns the :class:`~r2d2_tpu.telemetry.registry.MetricsRegistry` every
  plane writes into (``train()`` hands the same instance to the process
  fleet plane so respawn/ingest/serve counters land in the shared
  namespace),
- owns the persistent JSONL :class:`~r2d2_tpu.telemetry.runlog.RunLog`
  under ``<ckpt_dir>/telemetry/`` (absent without a checkpoint dir —
  ephemeral runs still get the registry and exporter),
- optionally owns the HTTP exporter (``cfg.telemetry_port``), whose
  supervised loop ``train()`` registers like any other fabric thread.

:meth:`record` is the single scrape point, called once per log
interval from ``log_loop`` with the assembled stats entry: it absorbs
the entry into the registry (spans → gauges, stats → monotone counters,
supervisor/fleet health → labeled gauges, chaos fires → counters, the
RETRACES / HOST_TRANSFERS guard surfaces), then appends the entry to
the run log.  Everything the registry learns is therefore also in the
durable JSONL record — the exporter and the file never disagree by more
than one interval.
"""
from __future__ import annotations

import math
import os
from typing import Any, Dict, Optional

from r2d2_tpu.telemetry.exporter import TelemetryExporter, make_exporter
from r2d2_tpu.telemetry.registry import MetricsRegistry
from r2d2_tpu.telemetry.runlog import RunLog


class Telemetry:
    """Registry + run log + exporter for one training run."""

    def __init__(self, cfg, checkpoint_dir: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.cfg = cfg
        self.registry = registry if registry is not None else (
            MetricsRegistry())
        self.runlog: Optional[RunLog] = None
        if checkpoint_dir:
            self.runlog = RunLog(
                os.path.join(checkpoint_dir, "telemetry"),
                max_bytes=cfg.telemetry_log_max_bytes)
        self.exporter: Optional[TelemetryExporter] = None
        self._bound_port = 0
        self.last_entry: Dict[str, Any] = {}

    # ------------------------------------------------------------ exporter
    def serve(self, health_fn, routes=None) -> Optional[TelemetryExporter]:
        """Arm the HTTP exporter per ``cfg.telemetry_port`` (None when
        disabled).  ``/statusz`` carries the newest recorded entry;
        ``routes`` adds trigger endpoints (``/tracez``/``/profilez`` —
        exporter module docstring)."""
        self.exporter = make_exporter(
            self.cfg, self.registry, health_fn,
            status_fn=lambda: dict(last_entry=self.last_entry),
            routes=routes)
        if self.exporter is not None:
            self._bound_port = self.exporter.port
        return self.exporter

    @property
    def port(self) -> int:
        """The exporter's bound port (0 = exporter never armed); stays
        readable after close so the run's metrics can report it."""
        return self._bound_port

    # -------------------------------------------------------------- scrape
    def record(self, entry: Dict[str, Any]) -> None:
        """Absorb one ``log_loop`` stats entry into the registry, then
        persist it to the run log (module docstring)."""
        reg = self.registry
        # headline counters (absolute values → monotone absorption)
        reg.counter_max("learner.training_steps",
                        entry.get("training_steps", 0))
        reg.counter_max("replay.env_steps", entry.get("env_steps", 0))
        # headline gauges
        reg.set_gauge("replay.buffer_size", entry.get("buffer_size", 0))
        reg.set_gauge("learner.updates_per_sec",
                      entry.get("updates_per_sec", 0.0))
        reg.set_gauge("learner.mean_loss",
                      entry.get("mean_loss", float("nan")))
        reg.set_gauge("actor.mean_episode_return",
                      entry.get("mean_episode_return", float("nan")))
        reg.set_gauge("learner.heartbeat_age_seconds",
                      entry.get("learner_heartbeat_age", 0.0))
        if "telemetry_port" in entry:
            reg.set_gauge("telemetry.port", entry["telemetry_port"])
        # interval deltas are genuine counter increments
        if entry.get("interval_episodes"):
            reg.inc("actor.episodes_finished", entry["interval_episodes"])
        # tracer spans/gauges/counters ride along as telemetry gauges
        reg.absorb_gauges("trace", entry.get("trace", {}))
        # supervisor thread health, one labeled series per thread
        for name, h in (entry.get("health") or {}).items():
            reg.set_gauge("fabric.thread_alive",
                          1.0 if h.get("alive") else 0.0, thread=name)
            reg.counter_max("fabric.thread_restarts",
                            h.get("restarts", 0), thread=name)
            if h.get("gave_up"):
                # belt over the Supervisor's own on_giveup stamp (the
                # log loop may be the thread that died — then only the
                # callback path records it)
                reg.counter_max("supervisor.gaveup", 1, thread=name)
        # chaos fires
        for kind, n in (entry.get("chaos") or {}).items():
            reg.counter_max("chaos.fires", n, kind=kind)
        # process-fleet plane health (incl. the slab-merged actor stats)
        fleet = entry.get("fleet")
        if fleet:
            reg.set_gauge("fleet.alive", fleet.get("alive", 0))
            reg.set_gauge("fleet.total", fleet.get("fleets", 0))
            reg.counter_max("fleet.restarts",
                            sum(fleet.get("restarts", [])))
            reg.counter_max("ingest.blocks",
                            fleet.get("blocks_ingested", 0))
            reg.counter_max("ingest.frames",
                            fleet.get("frames_ingested", 0))
            reg.counter_max("ingest.blocks_corrupt",
                            fleet.get("blocks_corrupt", 0))
            # slab-merged actor stats: env steps / blocks / episodes are
            # genuine monotone counters; the reward SUM legally decreases
            # (negative rewards) so it must travel as a gauge —
            # counter_max would clamp it at its historical max and never
            # export a negative value at all
            totals = (fleet.get("stats") or {}).get("totals", {})
            reg.counter_max("actor.env_steps",
                            totals.get("env_steps", 0))
            reg.counter_max("actor.blocks_produced",
                            totals.get("blocks_produced", 0))
            reg.counter_max("actor.episodes", totals.get("episodes", 0))
            if "episode_reward_sum" in totals:
                reg.set_gauge("actor.episode_reward_sum",
                              totals["episode_reward_sum"])
            for f, row in enumerate(
                    (fleet.get("stats") or {}).get("per_fleet", [])):
                lbl = str(f)
                reg.counter_max("actor.fleet.env_steps",
                                row.get("env_steps", 0), fleet=lbl)
                reg.counter_max("actor.fleet.blocks_produced",
                                row.get("blocks_produced", 0), fleet=lbl)
                reg.counter_max("actor.fleet.episodes",
                                row.get("episodes", 0), fleet=lbl)
                reg.set_gauge("actor.fleet.episode_reward_sum",
                              row.get("episode_reward_sum", 0.0),
                              fleet=lbl)
                reg.set_gauge("actor.fleet.param_version",
                              row.get("param_version", 0), fleet=lbl)
            svc = fleet.get("service")
            if svc:
                reg.counter_max("serve.batches", svc.get("batches", 0))
                reg.counter_max("serve.lanes_served",
                                svc.get("lanes_served", 0))
                reg.counter_max("serve.requests_corrupt",
                                svc.get("requests_corrupt", 0))
                reg.counter_max("serve.partial_batches",
                                svc.get("partial_batches", 0))
                reg.counter_max("serve.stale_requests",
                                svc.get("stale_requests", 0))
                reg.counter_max("serve.resyncs", svc.get("resyncs", 0))
                reg.set_gauge("serve.last_batch_lanes",
                              svc.get("last_batch_lanes", 0))
                reg.set_gauge("serve.param_version",
                              svc.get("param_version", 0))
            # population plane (league/population.py): per-member rows of
            # the slab-merged fleet counters — fleet f ↔ member f, folded
            # monotone through respawns by the CounterMerger upstream
            pop = fleet.get("population")
            if pop:
                for row in pop.get("members", []):
                    lbl = str(row.get("member", 0))
                    reg.counter_max("population.env_steps",
                                    row.get("env_steps", 0), member=lbl)
                    reg.counter_max("population.blocks",
                                    row.get("blocks", 0), member=lbl)
                    reg.counter_max("population.episodes",
                                    row.get("episodes", 0), member=lbl)
                    # reward sums legally decrease (negative rewards):
                    # gauge, the actor.episode_reward_sum rule
                    reg.set_gauge("population.episode_reward_sum",
                                  row.get("episode_reward_sum", 0.0),
                                  member=lbl)
                    reg.set_gauge("population.lanes",
                                  row.get("lanes", 0), member=lbl)
            # degraded-mode resilience plane (utils/resilience.py): the
            # fleets' act-RPC failover state merged from the stats slab
            # plus the plane's param-staleness watchdog
            res = fleet.get("resilience")
            if res:
                reg.counter_max("resilience.retries",
                                res.get("retries", 0))
                reg.counter_max("resilience.circuit_opens",
                                res.get("circuit_opens", 0))
                reg.counter_max("resilience.local_acts",
                                res.get("local_acts", 0))
                reg.set_gauge("resilience.degraded",
                              1.0 if res.get("degraded") else 0.0)
                reg.set_gauge("fleet.max_stale_params_s",
                              res.get("max_stale_params_s", 0.0))
                for f, st in enumerate(res.get("circuit_states", [])):
                    reg.set_gauge("resilience.circuit_state", st,
                                  fleet=str(f))
        # sharded replay plane (parallel/replay_shards.py): shard health
        # + the coordinator's routing/RPC counters under replay.shard.*.
        # Event counters the plane already writes LIVE with a {shard}
        # label (respawns, dropped_blocks, sample_timeouts, redraws,
        # garbled_responses, stale_feedback) are NOT re-absorbed here
        # unlabeled: two label schemas under one name double-count every
        # event in any sum() over the metric — per-shard series plus
        # label aggregation are the one view
        rs = entry.get("replay_shards")
        if rs:
            reg.set_gauge("replay.shard.total", rs.get("shards", 0))
            reg.set_gauge("replay.shard.alive", rs.get("alive", 0))
            reg.counter_max("replay.shard.blocks_routed",
                            rs.get("blocks_routed", 0))
            reg.counter_max("replay.shard.corrupt_blocks",
                            rs.get("corrupt_blocks", 0))
            reg.counter_max("replay.shard.sample_retries",
                            rs.get("sample_retries", 0))
            for sh, m in enumerate(rs.get("masses", [])):
                reg.set_gauge("replay.shard.mass", m, shard=str(sh))
            for sh, n in enumerate(rs.get("sizes", [])):
                reg.set_gauge("replay.shard.size", n, shard=str(sh))
            for sh, n in enumerate(rs.get("per_shard_corrupt", [])):
                reg.counter_max("replay.shard.shard_corrupt_blocks", n,
                                shard=str(sh))
            # cross-host transport (parallel/replay_net.py): the link
            # table's aggregates — per-link circuit_state / connected /
            # event counters are plane-written LIVE with labels, so only
            # the unlabeled aggregates absorb here (the two-schema
            # double-count rule above)
            net = rs.get("net")
            if net:
                reg.set_gauge("replay.net.links_connected",
                              net.get("connected", 0))
                reg.counter_max("replay.net.shard_epoch_drops",
                                net.get("shard_epoch_drops", 0))
                reg.counter_max("replay.net.shard_garbled",
                                net.get("shard_garbled", 0))
                reg.counter_max("replay.net.prio_batches",
                                net.get("prio_batches", 0))
        # shard-health drive-by on the base stats schema (zero on the
        # in-process path — replay.corrupt_blocks also covers the K=1
        # buffer's wire-format drops); shard_respawns stays entry/console
        # only: as a registry name it would flatten onto the same
        # Prometheus series as the plane's replay.shard.respawns{shard}
        if "corrupt_blocks" in entry:
            reg.counter_max("replay.corrupt_blocks",
                            entry["corrupt_blocks"])
        # league standings (league/eval_service.py): the sidecar's
        # durable record is league.jsonl; these gauges are the scrape
        # view — per-member latest/best scores plus sidecar liveness.
        # sidecar_respawns is inc'd at the respawn event site (the
        # fleet.respawns rule), so it is deliberately NOT re-absorbed
        lg = entry.get("league")
        if lg:
            h = lg.get("health") or {}
            reg.set_gauge("league.sidecar_alive",
                          1.0 if h.get("alive") else 0.0)
            reg.set_gauge("league.sidecar_failed",
                          1.0 if h.get("failed") else 0.0)
            reg.counter_max("league.rows", lg.get("rows", 0))
            reg.counter_max("league.sweeps", lg.get("sweeps", 0))
            reg.set_gauge("league.last_step",
                          max(0, lg.get("last_step", 0)))
            for row in lg.get("table", []):
                lbl = str(row.get("member", 0))
                reg.counter_max("league.evals", row.get("evals", 0),
                                member=lbl)
                reg.set_gauge("league.last_reward",
                              row.get("last_reward", 0.0), member=lbl)
                if row.get("best_reward") is not None:
                    reg.set_gauge("league.best_reward",
                                  row["best_reward"], member=lbl)
        # anakin fused-loop surface (train._train_anakin's log loop): the
        # transport is single-process by construction, so its counters
        # publish straight through the registry — no shm slab involved
        an = entry.get("anakin")
        if an:
            reg.counter_max("anakin.super_steps", an.get("super_steps", 0))
            reg.counter_max("anakin.frames", an.get("frames", 0))
            reg.set_gauge("anakin.frames_per_sec",
                          an.get("frames_per_sec", 0.0))
            reg.counter_max("actor.env_steps", entry.get("env_steps", 0))
            reg.counter_max("actor.blocks_produced", an.get("blocks", 0))
            reg.counter_max("actor.episodes", an.get("episodes_total", 0))
            reg.set_gauge("anakin.ring_fill", entry.get("buffer_size", 0))
            # in-graph greedy eval lane (cfg.anakin_eval_interval): the
            # return gauge stays absent until the first eval dispatch
            # (last_eval_return is NaN before it — a NaN gauge would
            # poison /metrics parsers)
            reg.counter_max("anakin.eval_episodes",
                            an.get("eval_episodes", 0))
            ev = an.get("eval_return")
            if ev is not None and math.isfinite(ev):
                reg.set_gauge("anakin.eval_return", ev)
        # learning-health plane (telemetry/learnhealth.py): the
        # monitor's snapshot — latest armed in-graph diag scalars as
        # gauges, cumulative sentry/spike counters, and the |TD| /
        # IS-weight histograms absorbed bucketwise-monotone.  Alert
        # fires are NOT re-absorbed here: the AlertEngine stamps
        # learnhealth.alert{rule} at the fire site (the fleet.respawns
        # rule — the log loop may never tick again after a trip)
        lh = entry.get("learnhealth")
        if lh:
            reg.absorb_counters("learnhealth", {
                k: lh[k] for k in ("armed_steps", "nonfinite",
                                   "loss_spikes", "loss_count")
                if k in lh})
            reg.absorb_gauges("learnhealth", {
                k: lh[k] for k in ("loss_ewma", "dq_ewma", "dq_mean",
                                   "dq_max", "grad_norm", "update_norm",
                                   "param_norm", "target_lag",
                                   "max_abs_q")
                if isinstance(lh.get(k), (int, float))})
            from r2d2_tpu.telemetry.learnhealth import (
                IS_WEIGHT_EDGES,
                TD_ABS_EDGES,
            )

            if lh.get("td_hist"):
                reg.absorb_histogram("learnhealth.td_abs", TD_ABS_EDGES,
                                     lh["td_hist"],
                                     total=lh.get("td_sum"))
            if lh.get("is_hist"):
                reg.absorb_histogram("learnhealth.is_weight",
                                     IS_WEIGHT_EDGES, lh["is_hist"],
                                     total=lh.get("is_sum"))
        # replay data-health: the PER distribution's ESS + priority
        # histogram (per ring, or per shard on the sharded plane), the
        # replay-ratio gauge, per-member sample fractions
        rh = entry.get("replay_health")
        if rh:
            reg.set_gauge("learnhealth.replay.ratio",
                          rh.get("replay_ratio", 0.0))
            spm = rh.get("samples_per_member") or {}
            total_s = sum(spm.values())
            if total_s:
                for m, c in spm.items():
                    reg.set_gauge("learnhealth.replay.sample_fraction",
                                  c / total_s, member=str(m))

            def _prio_row(row, **lbl):
                reg.set_gauge("learnhealth.replay.ess",
                              row.get("ess", 0.0), **lbl)
                reg.set_gauge("learnhealth.replay.ess_frac",
                              row.get("ess_frac", 0.0), **lbl)
                reg.set_gauge("learnhealth.replay.positive_leaves",
                              row.get("positive_leaves", 0), **lbl)
                edges = list(row.get("edges", rh.get("edges") or []))
                for i, c in enumerate(row.get("hist", [])):
                    le = (str(edges[i]) if i < len(edges) else "+Inf")
                    # snapshot of the CURRENT leaf distribution (not a
                    # cumulative counter): per-bucket gauges, le label
                    reg.set_gauge("learnhealth.replay.priorities", c,
                                  le=le, **lbl)

            if rh.get("shards") is not None:
                for row in rh["shards"]:
                    _prio_row(row, shard=str(row.get("shard", 0)))
            elif rh.get("priorities"):
                _prio_row(rh["priorities"])
        # the runtime guard surfaces (utils/trace.py process-wide views)
        from r2d2_tpu.utils.trace import (
            HOST_TRANSFERS,
            RETRACES,
            TRANSFER_GUARD,
        )

        reg.absorb_counters("host_transfers", HOST_TRANSFERS.snapshot())
        reg.absorb_counters("transfer_guard", TRANSFER_GUARD.snapshot())
        for name, traces in RETRACES.counts().items():
            reg.set_gauge("retraces.max_traces", traces, entry_point=name)

        self.last_entry = entry
        if self.runlog is not None:
            self.runlog.append(entry)

    def close_exporter(self) -> None:
        """Stop serving scrapes (train()'s fabric teardown calls this
        before joining the supervised loops — the loop is close-driven,
        not stop-driven, so a stalled run stays scrapeable until here)."""
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None

    def close(self) -> None:
        self.close_exporter()
        if self.runlog is not None:
            self.runlog.close()
