"""Telemetry plane: metrics registry, persistent run log, HTTP exporter.

The observability subsystem (docs/OBSERVABILITY.md):

- :mod:`~r2d2_tpu.telemetry.registry` — thread-safe counters / gauges /
  fixed-bucket histograms in one labeled namespace; Prometheus rendering.
- :mod:`~r2d2_tpu.telemetry.slab` — cross-process stats: fleet
  subprocesses publish counter vectors through a preallocated
  shared-memory slab (replay/block.py CRC conventions, no pickling);
  the trainer merges them monotone across watchdog respawns.
- :mod:`~r2d2_tpu.telemetry.runlog` — append-only, size-rotated JSONL
  run log under ``<ckpt_dir>/telemetry/`` (the durable stats record; a
  SIGTERM→resume cycle yields one continuous curve).
- :mod:`~r2d2_tpu.telemetry.exporter` — stdlib HTTP endpoint serving
  ``/metrics`` (Prometheus text), ``/healthz``, ``/statusz``
  (``cfg.telemetry_port`` / ``--telemetry-port``).
- :mod:`~r2d2_tpu.telemetry.console` — the one console rendering shared
  by ``train()``'s verbose line and ``tools/r2d2_top.py``.
- :mod:`~r2d2_tpu.telemetry.tracing` — cross-process structured event
  tracing: per-process preallocated shm event rings, fabric-wide
  bounded capture windows (``/tracez`` / ``--trace-steps``), block
  lineage flows, and the merged Chrome-trace (Perfetto) dump.
  Deliberately NOT re-exported here: instrumented code imports the
  module directly so the :data:`~r2d2_tpu.telemetry.tracing.EVENTS`
  singleton's attach-in-place semantics stay unambiguous.
- :mod:`~r2d2_tpu.telemetry.plane` — the per-run orchestrator
  (``Telemetry``) that ``train()`` wires through the fabric.
- :mod:`~r2d2_tpu.telemetry.learnhealth` — the learning-health plane:
  in-graph train-step diagnostics (ΔQ, |TD|/IS histograms, norms, the
  NaN sentry), replay data-health (PER ESS / priority histograms /
  replay ratio / member fractions), and the declarative alert engine
  (``alerts.jsonl`` + ``/alertz`` + ``learnhealth.alert{rule}``).
"""
from r2d2_tpu.telemetry.learnhealth import (  # noqa: F401
    AlertEngine,
    AlertRule,
    LearnHealthMonitor,
)
from r2d2_tpu.telemetry.console import format_entry  # noqa: F401
from r2d2_tpu.telemetry.exporter import (  # noqa: F401
    TelemetryExporter,
    make_exporter,
)
from r2d2_tpu.telemetry.plane import Telemetry  # noqa: F401
from r2d2_tpu.telemetry.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    MetricsRegistry,
)
from r2d2_tpu.telemetry.runlog import (  # noqa: F401
    RunLog,
    read_entries,
    tail_entry,
)
from r2d2_tpu.telemetry.slab import (  # noqa: F401
    FLEET_STAT_FIELDS,
    CounterMerger,
    StatsSlab,
    StatsSlabWriter,
)
