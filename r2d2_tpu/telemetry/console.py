"""The ONE console rendering of a stats entry.

``train()``'s verbose log line and the live terminal view
(tools/r2d2_top.py) previously could not share formatting — the line was
an inline f-string in ``log_loop``.  Both now render through
:func:`format_entry`, so the operator sees the same line whether they
are watching the training process's stdout, tailing the JSONL run log,
or polling the HTTP endpoint.
"""
from __future__ import annotations

from typing import Any, Dict


def format_entry(entry: Dict[str, Any], prefix: str = "[r2d2]") -> str:
    """One status line from a stats entry (the ``log_loop`` schema;
    missing keys render as zeros so partial entries — e.g. an early
    scrape — still format)."""
    ret = entry.get("mean_episode_return", float("nan"))
    line = (f"{prefix} updates={entry.get('training_steps', 0)} "
            f"({entry.get('updates_per_sec', 0.0):.1f}/s) "
            f"buffer={entry.get('buffer_size', 0)} "
            f"env_steps={entry.get('env_steps', 0)} "
            f"return={float(ret):.1f} "
            f"loss={entry.get('mean_loss', float('nan')):.4f}")
    fleet = entry.get("fleet")
    if fleet:
        line += f" fleets={fleet.get('alive', 0)}/{fleet.get('fleets', 0)}"
        stats = fleet.get("stats") or {}
        totals = stats.get("totals") or {}
        if totals.get("env_steps"):
            line += f" fleet_env_steps={int(totals['env_steps'])}"
    trace = entry.get("trace") or {}
    p95 = trace.get("span.learner.step_dispatch.p95_ms")
    if p95 is not None:
        # span-histogram percentiles (utils/trace.Tracer): the learner's
        # dispatch latency tail, visible without a trace dump
        line += f" step_p95={p95:.1f}ms"
        wait95 = trace.get("span.learner.batch_wait.p95_ms")
        if wait95 is not None:
            line += f" wait_p95={wait95:.1f}ms"
    rs = entry.get("replay_shards")
    if rs:
        line += f" shards={rs.get('alive', 0)}/{rs.get('shards', 0)}"
        respawns = sum(rs.get("respawns", []))
        if respawns:
            line += f" shard_respawns={respawns}"
        if rs.get("sample_timeouts"):
            line += f" shard_timeouts={rs['sample_timeouts']}"
        net = rs.get("net")
        if net:
            # cross-host transport: link connectivity at a glance, plus
            # the partition-story counters when they are non-zero
            line += f" net={net.get('connected', 0)}/{rs.get('shards', 0)}"
            if net.get("reconnects"):
                line += f" reconnects={net['reconnects']}"
            if net.get("epoch_drops"):
                line += f" epoch_drops={net['epoch_drops']}"
    if entry.get("corrupt_blocks"):
        line += f" corrupt_blocks={entry['corrupt_blocks']}"
    lh = entry.get("learnhealth") or {}
    if lh.get("armed_steps") and lh.get("dq_mean") is not None:
        # the paper's stored-vs-recomputed-state ΔQ, from the newest
        # armed in-graph diagnostic (telemetry/learnhealth.py)
        line += f" dq={lh['dq_mean']:.4f}"
    alerts = entry.get("alerts") or {}
    fired = {k: v for k, v in alerts.items() if v}
    if fired:
        line += " ALERTS[" + ",".join(
            f"{k}={v}" for k, v in sorted(fired.items())) + "]"
    age = entry.get("learner_heartbeat_age")
    if age is not None and age > 5.0:
        line += f" heartbeat_age={age:.1f}s"
    return line
