"""Cross-process stats slab: fleet subprocesses → trainer registry.

Fleet subprocesses (parallel/actor_procs.py) previously exported nothing
but liveness — the trainer could count ingested blocks but had no view of
actor-side progress (env steps run, episodes finished, weight staleness).
This module is the telemetry wire between the two, built on the same
primitives as the block channel so the conventions cannot fork:

- **Preallocated shared memory, no pickling**: one tiny
  ``multiprocessing.shared_memory`` segment holds ``num_slots`` fixed
  slots (one per fleet), each laid out by
  :func:`~r2d2_tpu.replay.block.slot_layout` as ``(seq, values[K],
  crc32)``.  A fleet publishes by writing its whole float64 value vector
  plus a monotonically increasing sequence number, CRC32 last — the block
  channel's torn-write discipline (:func:`~r2d2_tpu.replay.block.
  payload_crc32` over the ``(slot, seq)`` header + values).  The trainer
  polls each scrape; a CRC mismatch (producer SIGKILLed mid-publish,
  garbled slab) just keeps the previous good reading.
- **Counter monotonicity across respawns**: a respawned fleet's process
  restarts every counter (and its publish sequence) at zero.
  :class:`CounterMerger` detects the new incarnation by the published
  ``incarnation`` field changing (the watchdog bumps it per respawn —
  value regression would be ambiguous: a counter of negative rewards
  legally sums downward, and a young incarnation's seq can collide with
  the dead one's) and folds the dead incarnation's last reading into a
  per-slot base, so the merged series ``base + current`` stays monotone
  through any number of watchdog respawns.  A seq regression without an
  incarnation bump (producer restarted outside the watchdog) folds too.
  Gauge fields skip the fold: latest reading wins.

The field schema is fixed at construction on both ends
(:data:`FLEET_STAT_FIELDS` for the actor plane) — no names travel on the
wire, only the value vector, which is what keeps a publish
allocation-light enough for the fleet's run-burst loop.
"""
from __future__ import annotations

from multiprocessing import shared_memory
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from r2d2_tpu.replay.block import payload_crc32, slot_layout, slot_views

# (name, kind) schema of the actor-fleet stats slab; kind is "counter"
# (merged monotone across respawns) or "gauge" (latest reading wins)
FLEET_STAT_FIELDS: Tuple[Tuple[str, str], ...] = (
    ("env_steps", "counter"),
    ("blocks_produced", "counter"),
    ("episodes", "counter"),
    ("episode_reward_sum", "counter"),
    ("param_version", "gauge"),
    ("incarnation", "gauge"),   # respawn generation — the merger's fold
                                # trigger (module docstring)
    # degraded-mode resilience counters (utils/resilience.py — the serve
    # fleets' act-RPC failover state, exported as resilience.*)
    ("act_retries", "counter"),
    ("circuit_opens", "counter"),
    ("local_acts", "counter"),
    ("circuit_state", "gauge"),  # 0 closed / 1 open / 2 half-open
)


def _slot_spec(num_fields: int):
    return (("seq", (1,), np.int64),
            ("values", (num_fields,), np.float64),
            ("crc32", (1,), np.uint32))


class StatsSlab:
    """Trainer-side owner of the stats shared-memory segment."""

    def __init__(self, num_slots: int,
                 fields: Sequence[Tuple[str, str]] = FLEET_STAT_FIELDS):
        self.fields = tuple(fields)
        self.num_slots = num_slots
        self.spec = _slot_spec(len(self.fields))
        self.slot_nbytes, self.offsets = slot_layout(self.spec)
        self.shm = shared_memory.SharedMemory(
            create=True, size=max(1, num_slots) * self.slot_nbytes)
        self._closed = False

    def writer_info(self, slot: int) -> Tuple[str, int]:
        """Picklable handle for a fleet child: (segment name, slot)."""
        return (self.shm.name, slot)

    def read(self, slot: int) -> Optional[Tuple[int, np.ndarray]]:
        """One consistent ``(seq, values)`` reading of ``slot``, or None
        when the slot was never published / the CRC fails (torn write —
        the caller keeps its previous good reading) / the slab is
        already closed (a late health scrape after shutdown)."""
        if self._closed:
            return None
        try:
            v = slot_views(self.shm.buf, self.spec, self.offsets,
                           self.slot_nbytes, slot)
            seq = int(v["seq"][0])
            if seq <= 0:
                return None
            values = np.array(v["values"])    # copy before the CRC check
            if int(v["crc32"][0]) != payload_crc32((slot, seq), [values]):
                return None
        except (ValueError, TypeError):       # closed under a late reader
            return None
        return seq, values

    def close(self) -> None:
        self._closed = True
        try:
            self.shm.close()
        except BufferError:
            # a late reader still holds slot views; the mapping dies
            # with the process — unlinking below still frees the name
            pass
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


class StatsSlabWriter:
    """Fleet-side publisher (lives in the subprocess)."""

    def __init__(self, info: Tuple[str, int],
                 fields: Sequence[Tuple[str, str]] = FLEET_STAT_FIELDS):
        name, self.slot = info
        self.fields = tuple(fields)
        self.spec = _slot_spec(len(self.fields))
        self.slot_nbytes, self.offsets = slot_layout(self.spec)
        self.shm = shared_memory.SharedMemory(name=name)
        self._views = slot_views(self.shm.buf, self.spec, self.offsets,
                                 self.slot_nbytes, self.slot)
        self._order = [n for n, _ in self.fields]
        self._seq = 0
        self._buf = np.zeros(len(self.fields), np.float64)

    def publish(self, stats: Dict[str, float]) -> None:
        """Write the full value vector + seq, CRC32 last (torn-write
        discipline shared with the block channel)."""
        for i, field in enumerate(self._order):
            self._buf[i] = float(stats.get(field, 0.0))
        self._seq += 1
        v = self._views
        v["seq"][0] = self._seq
        v["values"][:] = self._buf
        v["crc32"][0] = payload_crc32((self.slot, self._seq), [self._buf])

    def close(self) -> None:
        try:
            self._views = None
            self.shm.close()
        except Exception:
            pass


class CounterMerger:
    """Fold per-slot publications into one monotone cross-fleet view.

    ``update(slot, seq, values)`` ingests a slab reading; ``totals()``
    returns ``{name: sum over slots}`` for counter fields (each slot
    contributing ``base + last`` — base absorbs dead incarnations, folded
    on *seq* regression, so the sum is monotone across respawns) and the
    latest per-slot reading for gauge fields under ``per_slot()``.
    """

    INCARNATION_FIELD = "incarnation"

    def __init__(self, num_slots: int,
                 fields: Sequence[Tuple[str, str]] = FLEET_STAT_FIELDS):
        self.fields = tuple(fields)
        self.num_slots = num_slots
        K = len(self.fields)
        self._counter_idx = [i for i, (_, kind) in enumerate(self.fields)
                             if kind == "counter"]
        names = [n for n, _ in self.fields]
        self._inc_idx = (names.index(self.INCARNATION_FIELD)
                         if self.INCARNATION_FIELD in names else None)
        self._base = np.zeros((num_slots, K), np.float64)
        self._last = np.zeros((num_slots, K), np.float64)
        self._seq = np.zeros(num_slots, np.int64)
        self._incarnation = np.full(num_slots, -1, np.int64)
        self._folds = np.zeros(num_slots, np.int64)

    def update(self, slot: int, seq: int, values: np.ndarray) -> bool:
        """Returns True when the reading advanced this slot's view."""
        inc = (int(values[self._inc_idx]) if self._inc_idx is not None
               else self._incarnation[slot])
        # a new stream is an incarnation bump (watchdog respawn) OR a
        # seq regression without one (producer restarted outside the
        # watchdog) — either way the old stream's counters must fold, or
        # totals() would regress when the fresh small values land
        new_stream = (inc != self._incarnation[slot]
                      and self._inc_idx is not None
                      ) or seq < self._seq[slot]
        if not new_stream and seq <= self._seq[slot]:
            return False          # a reading we already merged
        if new_stream:
            # fold the dead stream's final counters into the base (the
            # very first reading folds zeros — harmless)
            if self._incarnation[slot] >= 0:
                self._folds[slot] += 1
            for i in self._counter_idx:
                self._base[slot, i] += self._last[slot, i]
            self._incarnation[slot] = inc
        self._seq[slot] = seq
        self._last[slot] = values
        return True

    def totals(self) -> Dict[str, float]:
        """Counter fields summed across slots (monotone through
        respawns)."""
        merged = self._base + self._last
        return {self.fields[i][0]: float(merged[:, i].sum())
                for i in self._counter_idx}

    def per_slot(self) -> List[Dict[str, float]]:
        """Every field's current per-slot view: counters as
        ``base + last``, gauges as the latest reading."""
        out: List[Dict[str, float]] = []
        counter_set = set(self._counter_idx)
        for s in range(self.num_slots):
            row = {}
            for i, (name, _) in enumerate(self.fields):
                row[name] = float(self._base[s, i] + self._last[s, i]
                                  if i in counter_set else self._last[s, i])
            out.append(row)
        return out

    def incarnations(self) -> List[int]:
        """Respawn folds observed per slot (a telemetry-visible respawn
        count independent of the watchdog's own accounting)."""
        return [int(x) for x in self._folds]
