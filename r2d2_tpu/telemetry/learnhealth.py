"""Learning-health plane: in-graph training diagnostics + alert engine.

The fabric observes itself at the *systems* level (metrics, tracing) but
was blind to *learning* health: the R2D2 paper's central analysis is
exactly such a diagnostic — the ΔQ divergence between Q-values computed
from stored vs. recomputed recurrent states, which motivates burn-in and
stored-state training — and silent learning pathologies (priority-
distribution collapse, stale-state drift, NaN grads, loss spikes) are
the failures systems telemetry cannot see.  This module is that plane:

- **In-graph learner diagnostics** (:func:`make_diag_fn`): a fixed
  ``(DIAG_SIZE,)`` float32 vector computed INSIDE the jitted train step,
  cadence-gated by ``lax.cond`` on ``cfg.learnhealth_interval`` (the
  disarmed branch is a zeros fill — the heavy work, notably the ΔQ
  re-unroll, only executes on armed steps).  Fields: the paper's ΔQ
  stored-vs-recomputed-state divergence (the learning window re-unrolled
  from a ZERO initial state with the same pre-update params, mean/max
  ``|Q_stored − Q_recomputed|`` over the masked window), per-batch
  |TD-error| and IS-weight fixed-bucket histograms, grad/update/param
  global norms, target-network lag (``‖θ − θ⁻‖``), max|Q|, and a NaN/Inf
  sentry over loss + grads.  The vector rides the drivetrains' EXISTING
  per-dispatch D2H result fetch (concatenated into the same flat array),
  so per-dispatch ``HOST_TRANSFERS`` budgets are unchanged.
- **Host-side monitor** (:class:`LearnHealthMonitor`): absorbs harvested
  losses (every dispatch — the host half of the NaN sentry, plus the
  loss-spike EWMA) and armed diag vectors; accumulates the cumulative
  histograms the registry renders.  A non-finite observation trips the
  monitor, which fires the ``nonfinite`` alert immediately and requests
  a clean fabric stop (``_HostScaffold.stop`` polls :attr:`tripped`).
- **Replay data-health** (:func:`priority_health`): effective sample
  size of the PER distribution + a fixed-bucket priority histogram over
  the sum-tree leaves (``ReplayBuffer.data_health`` /
  ``ShardedReplayPlane.data_health`` per shard), the replay-ratio gauge,
  and per-member sample fractions riding the ``member_id`` block stamp.
- **Declarative alert engine** (:class:`AlertEngine`): rules
  (``nonfinite``, ``loss_spike``, ``dq_drift``, ``ess_collapse``,
  ``replay_ratio``) evaluated host-side each log interval over the
  monitor/replay snapshots.  A firing rule increments
  ``learnhealth.alert{rule}``, appends a durable row to
  ``<ckpt_dir>/telemetry/alerts.jsonl`` (RunLog conventions:
  append-on-resume, rotation, torn-line-tolerant readers), and shows up
  on ``/alertz``, ``/statusz`` and ``tools/r2d2_top.py``.  Only the
  ``nonfinite`` rule degrades ``/healthz`` — every other rule is an
  operator signal, not an orchestration verdict.

Rule names must be string literals and rule thresholds must come from
``cfg`` (never inline magic numbers) — enforced by the
``telemetry-discipline`` graftlint rule (docs/ANALYSIS.md).

Module-level code is numpy/stdlib only (replay shard subprocesses import
this for the data-health vocabulary); the in-graph factory imports jax
lazily.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from r2d2_tpu.telemetry.runlog import RunLog

# ---------------------------------------------------------------------------
# the in-graph diagnostic vector layout
# ---------------------------------------------------------------------------

# scalar slots, in wire order.  "armed" is 1.0 on cadence steps and the
# whole vector is zeros otherwise (the lax.cond disarmed branch).
DIAG_SCALARS = (
    "armed",          # 1.0 when this step computed diagnostics
    "loss",           # the step's scalar loss (copy)
    "nonfinite",      # NaN/Inf sentry: non-finite elements in loss+grads
    "grad_norm",      # global L2 norm of the gradients
    "update_norm",    # global L2 norm of the optimizer updates
    "param_norm",     # global L2 norm of the updated params
    "target_lag",     # global L2 norm of (params - target_params)
    "max_abs_q",      # max |Q| over the full online unroll
    "dq_mean",        # ΔQ: masked mean |Q_stored - Q_zero| (paper diag)
    "dq_max",         # ΔQ: masked max
    "td_abs_sum",     # masked sum of |TD| (the histogram's _sum)
    "is_weight_sum",  # sum of IS weights (the histogram's _sum)
)

# fixed bucket upper edges (ascending; +Inf bucket implied) — shared by
# the in-graph bucketize and the registry histograms so the counts land
# in a declared histogram unchanged.  |TD| under value rescaling lives
# in ~[1e-3, 10]; IS weights are min-normalised into (0, 1].
TD_ABS_EDGES = (0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0)
IS_WEIGHT_EDGES = (0.05, 0.1, 0.2, 0.4, 0.6, 0.8, 0.95)

_TD_LO = len(DIAG_SCALARS)
_TD_HI = _TD_LO + len(TD_ABS_EDGES) + 1
_IS_LO = _TD_HI
_IS_HI = _IS_LO + len(IS_WEIGHT_EDGES) + 1
DIAG_SIZE = _IS_HI

_SCALAR_IDX = {name: i for i, name in enumerate(DIAG_SCALARS)}

# fixed bucket upper edges for the replay-side priority-distribution
# histogram (sum-tree leaf masses, i.e. td^alpha)
PRIO_EDGES = (1e-3, 3e-3, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0)


def diag_enabled(cfg) -> bool:
    """Whether the train-step drivetrains carry the diagnostic vector."""
    return getattr(cfg, "learnhealth_interval", 0) > 0


def make_diag_fn(cfg, net) -> Callable[..., Any]:
    """The in-graph diagnostic bundle for one train step.

    Returns ``diag(params, batch, loss, grads, updates, new_params,
    new_target, aux) -> (DIAG_SIZE,) f32`` where ``aux`` is the
    ``loss_and_priorities(..., with_aux=True)`` bundle ``(td, mask,
    q_learn, max_abs_q)`` and ``params`` are the PRE-update params (the
    ones that produced ``q_learn`` — the ΔQ re-unroll must compare like
    with like).  Called only inside the armed branch of the step's
    ``lax.cond``, so the re-unroll costs nothing on disarmed steps.

    ``net`` must be the step's LOSS net (the scan recurrence —
    ``learner.step._loss_net`` builds it).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from r2d2_tpu.learner.step import _gather_time, _window_indices
    from r2d2_tpu.models.network import R2D2Network

    td_edges = jnp.asarray(TD_ABS_EDGES, jnp.float32)
    is_edges = jnp.asarray(IS_WEIGHT_EDGES, jnp.float32)

    def bucketize(values, weights, edges):
        # side="left" == bisect_left — the registry _Histogram's exact
        # bucket rule, so the counts merge into a declared histogram
        # without re-binning (pinned against a numpy oracle in
        # tests/test_learnhealth.py)
        idx = jnp.searchsorted(edges, values.ravel(), side="left")
        return jnp.zeros(edges.shape[0] + 1, jnp.float32).at[idx].add(
            weights.ravel().astype(jnp.float32))

    def nonfinite_count(loss, grads):
        total = (~jnp.isfinite(loss)).astype(jnp.float32)
        for leaf in jax.tree.leaves(grads):
            total = total + (~jnp.isfinite(leaf)).sum().astype(jnp.float32)
        return total

    def diag(params, batch, loss, grads, updates, new_params, new_target,
             aux):
        td, mask, q_learn, max_abs_q = aux
        # the paper's ΔQ: the SAME learning window re-unrolled from a
        # zero initial state (the stored-state-vs-zero-state divergence
        # that motivates burn-in + stored-state training) with the SAME
        # pre-update params, gathered at the same online indices
        q_zero_seq, _ = net.apply(
            params, batch["obs"], batch["last_action"],
            batch["last_reward"], jnp.zeros_like(batch["hidden"]),
            method=R2D2Network.unroll)
        idx_online, _, m = _window_indices(
            cfg, batch["burn_in"], batch["learning"], batch["forward"])
        dq = jnp.abs(q_learn - _gather_time(q_zero_seq, idx_online))
        m3 = m[:, :, None]
        dq_masked = jnp.where(m3, dq, 0.0)
        denom = jnp.maximum(m.sum() * dq.shape[-1], 1)
        dq_mean = dq_masked.sum() / denom
        dq_max = dq_masked.max()

        td_abs = jnp.where(mask, jnp.abs(td), 0.0)
        td_counts = bucketize(jnp.abs(td), mask, td_edges)
        w = batch["is_weights"]
        is_counts = bucketize(w, jnp.ones_like(w), is_edges)

        lag = optax.global_norm(jax.tree.map(lambda p, t: p - t,
                                             new_params, new_target))
        scalars = jnp.stack([
            jnp.float32(1.0),
            loss.astype(jnp.float32),
            nonfinite_count(loss, grads),
            optax.global_norm(grads).astype(jnp.float32),
            optax.global_norm(updates).astype(jnp.float32),
            optax.global_norm(new_params).astype(jnp.float32),
            lag.astype(jnp.float32),
            max_abs_q.astype(jnp.float32),
            dq_mean.astype(jnp.float32),
            dq_max.astype(jnp.float32),
            td_abs.sum().astype(jnp.float32),
            w.sum().astype(jnp.float32),
        ])
        return jnp.concatenate([scalars, td_counts, is_counts])

    return diag


def empty_diag():
    """The disarmed branch's zeros vector (host twin for tests)."""
    return np.zeros(DIAG_SIZE, np.float32)


# ---------------------------------------------------------------------------
# replay data-health math (shared by the in-process buffer and the shard
# owner processes — numpy only)
# ---------------------------------------------------------------------------

def priority_health(leaves) -> Dict[str, Any]:
    """ESS + fixed-bucket histogram of one sum-tree leaf vector.

    ``ess = (Σp)² / Σp²`` over the positive leaves — the effective
    sample size of the PER sampling distribution; ``ess_frac`` is it
    normalised by the positive-leaf count (1.0 = uniform, → 0 as a few
    leaves dominate — the "priority ESS collapse" failure mode the alert
    engine watches)."""
    leaves = np.asarray(leaves, np.float64).ravel()
    pos = leaves[leaves > 0]
    n = int(pos.size)
    if n == 0:
        return dict(ess=0.0, ess_frac=1.0, positive_leaves=0, mass=0.0,
                    hist=[0] * (len(PRIO_EDGES) + 1),
                    edges=list(PRIO_EDGES))
    ess = float(pos.sum() ** 2 / np.square(pos).sum())
    idx = np.searchsorted(np.asarray(PRIO_EDGES), pos, side="left")
    hist = np.bincount(idx, minlength=len(PRIO_EDGES) + 1)
    return dict(ess=ess, ess_frac=ess / n, positive_leaves=n,
                mass=float(pos.sum()), hist=[int(c) for c in hist],
                edges=list(PRIO_EDGES))


def replay_ratio(cfg, training_steps: int, env_steps: int) -> float:
    """Samples consumed per transition inserted: how many times the
    average stored step has been trained on so far (cumulative)."""
    if env_steps <= 0:
        return 0.0
    return (training_steps * cfg.batch_size * cfg.learning_steps
            / float(env_steps))


# ---------------------------------------------------------------------------
# host-side monitor
# ---------------------------------------------------------------------------

# diag scalars surfaced as latest-value gauges (the rest are counters /
# histogram sums handled separately)
_GAUGE_SCALARS = ("grad_norm", "update_norm", "param_norm", "target_lag",
                  "max_abs_q", "dq_mean", "dq_max")


class LearnHealthMonitor:
    """Absorbs harvested losses + armed diag vectors on the learner
    thread; snapshotted by the log loop.  A non-finite observation trips
    :attr:`tripped` (the scaffold's stop predicate polls it) and fires
    the ``nonfinite`` alert immediately through the attached engine —
    the log loop may never tick again once the fabric drains."""

    LOSS_EWMA_ALPHA = 0.02
    LOSS_WARMUP = 20         # samples before the spike rule may fire
    _NONFINITE_CAP = 10 ** 9  # a NaN param tree counts millions of elems

    def __init__(self, cfg, engine: Optional["AlertEngine"] = None):
        self.cfg = cfg
        self.engine = engine
        self.enabled = diag_enabled(cfg)
        self._lock = threading.Lock()
        self._loss_count = 0
        self._loss_ewma = 0.0
        self._last_loss = float("nan")
        self._spikes = 0
        self._nonfinite = 0
        self._tripped = False
        self._armed_steps = 0
        self._scalars: Dict[str, float] = {}
        self._dq_ewma: Optional[float] = None
        self._td_counts = np.zeros(len(TD_ABS_EDGES) + 1, np.int64)
        self._td_sum = 0.0
        self._is_counts = np.zeros(len(IS_WEIGHT_EDGES) + 1, np.int64)
        self._is_sum = 0.0

    @property
    def tripped(self) -> bool:
        """True once a non-finite loss/grad was observed — the fabric
        must stop cleanly (drain-then-save) instead of training on
        through poisoned numerics."""
        return self._tripped

    # ------------------------------------------------------------ writes
    def note_losses(self, losses) -> None:
        """Absorb one harvest's losses (every dispatch — the host half
        of the NaN sentry plus the loss-spike EWMA)."""
        losses = np.asarray(losses, np.float64).ravel()
        factor = self.cfg.alert_loss_spike_factor
        fire_snap = None
        with self._lock:
            for v in losses:
                v = float(v)
                if not np.isfinite(v):
                    self._nonfinite += 1
                    if not self._tripped:
                        self._tripped = True
                        fire_snap = self._snapshot_locked()
                    continue
                self._last_loss = float(v)
                if (self._loss_count >= self.LOSS_WARMUP
                        and self._loss_ewma > 1e-12
                        and v > factor * self._loss_ewma):
                    self._spikes += 1
                self._loss_count += 1
                a = self.LOSS_EWMA_ALPHA
                self._loss_ewma = (v if self._loss_count == 1
                                   else a * v + (1 - a) * self._loss_ewma)
        self._maybe_fire(fire_snap)

    def absorb_diags(self, diags) -> None:
        """Absorb one harvest's diag vectors ((n, DIAG_SIZE) or flat);
        disarmed rows (armed == 0) are skipped."""
        rows = np.asarray(diags, np.float64).reshape(-1, DIAG_SIZE)
        fire_snap = None
        with self._lock:
            for r in rows:
                if r[_SCALAR_IDX["armed"]] < 0.5:
                    continue
                self._armed_steps += 1
                for name in _GAUGE_SCALARS:
                    self._scalars[name] = float(r[_SCALAR_IDX[name]])
                dq = float(r[_SCALAR_IDX["dq_mean"]])
                self._dq_ewma = (dq if self._dq_ewma is None
                                 else 0.1 * dq + 0.9 * self._dq_ewma)
                self._td_counts += r[_TD_LO:_TD_HI].astype(np.int64)
                self._td_sum += float(r[_SCALAR_IDX["td_abs_sum"]])
                self._is_counts += r[_IS_LO:_IS_HI].astype(np.int64)
                self._is_sum += float(r[_SCALAR_IDX["is_weight_sum"]])
                nonfin = r[_SCALAR_IDX["nonfinite"]]
                if nonfin > 0:
                    self._nonfinite += int(min(nonfin,
                                               self._NONFINITE_CAP))
                    if not self._tripped:
                        self._tripped = True
                        fire_snap = self._snapshot_locked()
        self._maybe_fire(fire_snap)

    def _maybe_fire(self, snap) -> None:
        # outside the lock: the engine takes its own lock + file I/O
        if snap is not None and self.engine is not None:
            self.engine.evaluate(dict(learnhealth=snap))

    # ------------------------------------------------------------- reads
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> Dict[str, Any]:
        out: Dict[str, Any] = dict(
            enabled=self.enabled,
            armed_steps=self._armed_steps,
            nonfinite=self._nonfinite,
            loss_spikes=self._spikes,
            loss_count=self._loss_count,
            last_loss=self._last_loss,
            td_hist=[int(c) for c in self._td_counts],
            td_sum=self._td_sum,
            is_hist=[int(c) for c in self._is_counts],
            is_sum=self._is_sum,
        )
        if self._loss_count:
            out["loss_ewma"] = self._loss_ewma
        if self._dq_ewma is not None:
            out["dq_ewma"] = self._dq_ewma
        out.update(self._scalars)
        return out


# ---------------------------------------------------------------------------
# declarative alert engine
# ---------------------------------------------------------------------------

class AlertRule:
    """One declarative learning-health rule.

    ``name`` MUST be a string literal at the construction site and
    ``threshold`` must be a ``cfg``-derived value, never an inline magic
    number — both enforced by the ``telemetry-discipline`` graftlint
    rule.  ``check(rule, ctx)`` returns None (quiet) or a dict with
    ``value``/``detail``; delta rules keep their cursor on
    :attr:`last`, edge rules their level on :attr:`active`."""

    def __init__(self, name: str, check: Callable[["AlertRule", Dict],
                                                  Optional[Dict]],
                 threshold: Optional[float] = None):
        self.name = name
        self.check = check
        self.threshold = threshold
        self.active = False      # edge rules: currently in violation
        self.last = 0.0          # delta rules: last absorbed counter


def _replay_rows(ctx) -> List[Dict[str, Any]]:
    """Per-ring priority-health rows of the ctx's replay view: one row
    for the in-process buffer, one per shard for the sharded plane."""
    replay = ctx.get("replay") or {}
    if replay.get("shards") is not None:
        return [row for row in replay["shards"]]
    pr = replay.get("priorities")
    return [pr] if pr else []


def build_rules(cfg) -> List[AlertRule]:
    """The standing rule set, thresholds drawn from cfg: ``nonfinite``
    and ``loss_spike`` always armed (delta rules over the monitor's
    cumulative counters); ``dq_drift`` / ``ess_collapse`` /
    ``replay_ratio`` armed by their nonzero cfg thresholds (edge rules —
    they fire on the transition into violation, not every interval)."""
    rules: List[AlertRule] = []

    def nonfinite_check(rule, ctx):
        cur = (ctx.get("learnhealth") or {}).get("nonfinite", 0)
        rule.active = cur > 0
        if cur > rule.last:
            rule.last = cur
            return dict(value=cur,
                        detail="non-finite loss/grad elements observed")
        return None

    rules.append(AlertRule("nonfinite", check=nonfinite_check))

    def spike_check(rule, ctx):
        lh = ctx.get("learnhealth") or {}
        cur = lh.get("loss_spikes", 0)
        if cur > rule.last:
            rule.last = cur
            return dict(value=lh.get("last_loss"),
                        detail="loss above %.1fx its EWMA (%.5g)"
                               % (cfg.alert_loss_spike_factor,
                                  lh.get("loss_ewma", float("nan"))))
        return None

    rules.append(AlertRule("loss_spike", check=spike_check,
                           threshold=cfg.alert_loss_spike_factor))

    if cfg.alert_dq_budget > 0:
        def dq_check(rule, ctx):
            dq = (ctx.get("learnhealth") or {}).get("dq_mean")
            if dq is None:
                return None   # no armed diag in this ctx: keep the
                              # edge level latched, never reset it
            over = dq > cfg.alert_dq_budget
            fired = over and not rule.active
            rule.active = over
            if fired:
                return dict(value=dq,
                            detail="stored-vs-recomputed-state ΔQ above "
                                   "budget")
            return None

        rules.append(AlertRule("dq_drift", check=dq_check,
                               threshold=cfg.alert_dq_budget))

    if cfg.alert_ess_min > 0:
        def ess_check(rule, ctx):
            worst = None
            for row in _replay_rows(ctx):
                if row.get("positive_leaves", 0) < cfg.batch_size:
                    continue   # warmup: a near-empty ring is not collapse
                f = row.get("ess_frac")
                if f is not None and (worst is None or f < worst):
                    worst = f
            if worst is None:
                # no replay view in this ctx (partial evaluation — e.g.
                # the monitor's immediate nonfinite path, or a one-off
                # data_health failure): keep the edge level latched —
                # resetting it would re-fire a duplicate alert on the
                # next full evaluation with no actual transition
                return None
            over = worst < cfg.alert_ess_min
            fired = over and not rule.active
            rule.active = over
            if fired:
                return dict(value=worst,
                            detail="PER effective-sample-size fraction "
                                   "collapsed")
            return None

        rules.append(AlertRule("ess_collapse", check=ess_check,
                               threshold=cfg.alert_ess_min))

    if cfg.alert_replay_ratio_max > 0:
        def ratio_check(rule, ctx):
            replay = ctx.get("replay") or {}
            ratio = replay.get("replay_ratio")
            if not ratio or not ctx.get("training_steps"):
                return None    # nothing trained yet: no band to be in
            over = (ratio > cfg.alert_replay_ratio_max
                    or ratio < cfg.alert_replay_ratio_min)
            fired = over and not rule.active
            rule.active = over
            if fired:
                return dict(value=ratio,
                            detail="replay ratio out of the configured "
                                   "band")
            return None

        rules.append(AlertRule("replay_ratio", check=ratio_check,
                               threshold=cfg.alert_replay_ratio_max))
    return rules


class AlertEngine:
    """Evaluates the declarative rule set each log interval (plus the
    monitor's immediate non-finite path) and owns the three alert
    surfaces: ``learnhealth.alert{rule}`` counters, the durable
    ``alerts.jsonl`` row stream, and the ``/alertz`` status payload."""

    def __init__(self, cfg, registry, log_dir: Optional[str] = None):
        self.cfg = cfg
        self.registry = registry
        self.rules = build_rules(cfg)
        self._lock = threading.RLock()
        self._counts: Dict[str, int] = {}
        self._recent: collections.deque = collections.deque(maxlen=64)
        self._log: Optional[RunLog] = None
        if log_dir:
            self._log = RunLog(log_dir, filename="alerts.jsonl",
                               max_bytes=max(1024,
                                             cfg.telemetry_log_max_bytes))

    @property
    def nonfinite_active(self) -> bool:
        """The one rule that degrades /healthz: non-finite numerics mean
        the checkpoint stream is suspect and an operator must look."""
        with self._lock:
            return self._counts.get("nonfinite", 0) > 0

    # ------------------------------------------------------------ engine
    def evaluate(self, ctx: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Run every rule over one context snapshot; returns the fired
        rows (already counted, logged and registry-stamped)."""
        fired: List[Dict[str, Any]] = []
        with self._lock:
            for rule in self.rules:
                try:
                    res = rule.check(rule, ctx)
                except Exception:   # a rule must never kill the log loop
                    continue
                if not res:
                    continue
                fired.append(self._emit(rule.name, rule.threshold, res,
                                        ctx.get("training_steps")))
        return fired

    def fire(self, name: str, value: Optional[float] = None,
             threshold: Optional[float] = None, detail: str = "") -> None:
        """Manual fire path (drills/tests); ``name`` must be a string
        literal at the call site (graftlint telemetry-discipline)."""
        with self._lock:
            self._emit(name, threshold, dict(value=value, detail=detail),
                       None)

    def _emit(self, name, threshold, res, step) -> Dict[str, Any]:
        row = dict(kind="alert", rule=name, time=time.time(), step=step,
                   value=res.get("value"), threshold=threshold,
                   detail=res.get("detail", ""))
        self._counts[name] = self._counts.get(name, 0) + 1
        self._recent.append(row)
        # the rule name is bounded vocabulary, so it travels as a label
        self.registry.inc("learnhealth.alert", rule=name)
        if self._log is not None:
            self._log.append(row)
        return row

    # ------------------------------------------------------------- reads
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def active(self) -> List[str]:
        with self._lock:
            return [r.name for r in self.rules if r.active]

    def status(self) -> Dict[str, Any]:
        """The ``/alertz`` payload: armed rules + thresholds, cumulative
        counts, currently-active edge rules, newest rows."""
        with self._lock:
            return dict(
                rules=[dict(rule=r.name, threshold=r.threshold,
                            active=r.active,
                            fired=self._counts.get(r.name, 0))
                       for r in self.rules],
                counts=dict(self._counts),
                active=[r.name for r in self.rules if r.active],
                recent=list(self._recent),
            )

    def route(self, params: Dict[str, str]):
        """Exporter trigger-route adapter (``GET /alertz``)."""
        return 200, self.status()

    def close(self) -> None:
        if self._log is not None:
            self._log.close()


def read_alerts(checkpoint_dir: str):
    """Stream the durable alert rows of a run (oldest first, rotated
    segments included, torn tail skipped) — tooling/tests twin of the
    engine's writer."""
    import os

    from r2d2_tpu.telemetry.runlog import read_entries

    path = os.path.join(checkpoint_dir, "telemetry", "alerts.jsonl")
    return [e for e in read_entries(path) if e.get("kind") == "alert"]
