"""Stdlib-only HTTP exporter: ``/metrics``, ``/healthz``, ``/statusz``.

Three endpoint contracts, chosen so stock tooling works unmodified:

- ``GET /metrics`` — the registry in Prometheus text exposition format
  0.0.4 (``Content-Type: text/plain; version=0.0.4; charset=utf-8``);
  point a Prometheus scrape job at it.
- ``GET /healthz`` — JSON liveness verdict from the fabric's own
  signals (supervisor failures, learner heartbeat age vs its stall
  budget, fleet/process health).  HTTP 200 when ``ok`` is true, 503
  otherwise — a load balancer or ``curl -f`` needs no JSON parsing.
- ``GET /statusz`` — full JSON snapshot (registry dump + health + the
  newest log entry): the machine-readable twin of the terminal view.

Trigger routes (``routes=``): the caller may register extra GET paths —
``train()`` wires ``/tracez`` (arm a bounded cross-process trace
capture; dump under ``<ckpt_dir>/telemetry/``) and ``/profilez`` (arm a
``jax.profiler`` device trace) through this hook
(docs/OBSERVABILITY.md §Tracing).  A route handler receives the flat
query-param dict and returns ``(status_code, json_payload)``.

Anything else is 404.  The server binds loopback by default and is
driven by the caller's loop (:meth:`handle_once` — a bounded
``handle_request`` with the server timeout set), so in ``train()`` it
runs as a normal supervised fabric thread with the fabric's stop
predicate, not a free-running stdlib thread pool.

Port semantics (``cfg.telemetry_port``): ``0`` disables the exporter
entirely (:func:`make_exporter` returns None — the default), ``> 0``
binds that port, ``-1`` binds an OS-assigned ephemeral port (tests,
multi-run hosts); the bound port is always on :attr:`TelemetryExporter.
port` and surfaced in the run's log entries.
"""
from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qsl

# a trigger route: flat query params in, (status code, JSON payload) out
RouteFn = Callable[[Dict[str, str]], Tuple[int, Dict[str, Any]]]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
JSON_CONTENT_TYPE = "application/json; charset=utf-8"


class TelemetryExporter:
    """One bounded-request-at-a-time HTTP scrape endpoint."""

    def __init__(self, registry, health_fn: Callable[[], Dict[str, Any]],
                 status_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 routes: Optional[Dict[str, RouteFn]] = None):
        self.registry = registry
        self.health_fn = health_fn
        self.status_fn = status_fn
        self.routes = dict(routes or {})
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            # scrapes must not spam stderr
            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def do_GET(self):  # noqa: N802 (stdlib handler convention)
                try:
                    exporter._respond(self)
                except (BrokenPipeError, ConnectionResetError):
                    pass       # scraper went away mid-reply; next scrape

        self.server = HTTPServer((host, port), _Handler)
        self.server.timeout = 0.2      # bounds handle_once for stop polls
        self.port = int(self.server.server_address[1])
        self.closed = False

    # ------------------------------------------------------------ serving
    def _respond(self, handler: BaseHTTPRequestHandler) -> None:
        path, _, query = handler.path.partition("?")
        if path in self.routes:
            try:
                code, payload = self.routes[path](dict(parse_qsl(query)))
            except Exception as e:   # a trigger must never kill the loop
                code, payload = 500, dict(error=str(e))
            self._send(handler, code, JSON_CONTENT_TYPE,
                       json.dumps(payload, default=str).encode("utf-8"))
        elif path == "/metrics":
            body = self.registry.render_prometheus().encode("utf-8")
            self._send(handler, 200, PROM_CONTENT_TYPE, body)
        elif path == "/healthz":
            health = self.health_fn()
            code = 200 if health.get("ok") else 503
            self._send(handler, code, JSON_CONTENT_TYPE,
                       json.dumps(health, default=str).encode("utf-8"))
        elif path == "/statusz":
            status = dict(metrics=self.registry.snapshot(),
                          health=self.health_fn())
            if self.status_fn is not None:
                status.update(self.status_fn())
            self._send(handler, 200, JSON_CONTENT_TYPE,
                       json.dumps(status, default=str).encode("utf-8"))
        else:
            self._send(handler, 404, JSON_CONTENT_TYPE,
                       b'{"error": "unknown path"}')

    @staticmethod
    def _send(handler: BaseHTTPRequestHandler, code: int,
              content_type: str, body: bytes) -> None:
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def handle_once(self) -> None:
        """Serve at most one request, bounded by the server timeout —
        the supervised fabric loop body.  The loop runs until
        :meth:`close` (NOT until the fabric's stop flag): a stalled or
        draining run must stay scrapeable — /healthz going non-OK while
        the learner is wedged is the whole point of the endpoint."""
        self.server.handle_request()

    def close(self) -> None:
        self.closed = True            # flag first: the loop polls it
        self.server.server_close()


def make_exporter(cfg, registry, health_fn, status_fn=None,
                  routes=None) -> Optional[TelemetryExporter]:
    """The config gate: ``telemetry_port == 0`` → disabled (None);
    ``> 0`` → that port; ``-1`` → ephemeral (the bound port is on the
    returned exporter)."""
    if cfg.telemetry_port == 0:
        return None
    return TelemetryExporter(registry, health_fn, status_fn=status_fn,
                             port=max(0, cfg.telemetry_port),
                             routes=routes)
