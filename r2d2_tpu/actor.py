"""Actors: experience generation against the environment.

Capability-parity with the reference actor (worker.py:500-575) and its
``AgentState`` carrier (model.py:9-24): ε-greedy acting on the recurrent
Q-network, LocalBuffer block assembly with bootstrap Q at truncation,
periodic weight refresh, per-actor ε ladder (train.py:15-17).

TPU-first redesign — the **lockstep vector actor**: instead of N CPU
processes each running an unbatched torch forward (worker.py:528-529), one
driver steps N environments in lockstep and issues a single batched
``act`` call per step.  Batched inference amortizes device dispatch and
keeps the MXU busy (N×512 matmuls instead of N separate 1×512), which is
the standard TPU inference-server architecture.  Each env keeps its own
ε, LocalBuffer, and episode lifecycle, so the learning semantics are
unchanged from the reference fleet.

The bootstrap Q at a block boundary (worker.py:550-554 runs a *second*
forward) is obtained for free here: a boundary finish is deferred one
iteration, and the next iteration's batched Q at the new state is used —
one forward per env step total.

Env stepping can be parallelised across a thread pool (``env_workers``):
each worker owns a contiguous shard of lanes, matching the genuine
CPU-parallelism of the reference's N actor *processes* (train.py:30-34).
ALE releases the GIL inside ``step``, so threads scale for real Atari;
every lane's state (env, LocalBuffer, batched-array row ``i``) is touched
by exactly one worker per iteration, and the block sink is lock-protected
by the replay buffer, so no extra synchronisation is needed.  Block arrival
order at the sink becomes nondeterministic across lanes — use
``env_workers=0`` (serial, the default) where determinism matters.
"""
from __future__ import annotations

import dataclasses
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence

import jax
import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.models.network import R2D2Network
from r2d2_tpu.replay.block import Block, VectorLocalBuffer
from r2d2_tpu.telemetry.tracing import EVENTS
from r2d2_tpu.utils.store import ParamStore

# sink(block, priorities, episode_reward_or_None) — direct buffer.add in the
# single-process trainer, queue.put in the process fabric.
BlockSink = Callable[[Block, np.ndarray, Optional[float]], None]


@dataclasses.dataclass
class AgentState:
    """Recurrent-inference state for ONE env (reference: model.py:9-24).

    Arrays are unbatched host numpy; the vector actor keeps the batched
    (N, ...) stack of these instead.
    """
    obs: np.ndarray            # (*obs_shape) uint8
    last_action: np.ndarray    # (A,) float32 one-hot
    last_reward: float
    hidden: np.ndarray         # (2, layers, H) float32

    @classmethod
    def initial(cls, cfg: Config, obs: np.ndarray, action_dim: int
                ) -> "AgentState":
        la = np.zeros(action_dim, np.float32)
        hidden = np.zeros((2, cfg.lstm_layers, cfg.hidden_dim), np.float32)
        return cls(obs=np.asarray(obs, np.uint8), last_action=la,
                   last_reward=0.0, hidden=hidden)

    def update(self, obs: np.ndarray, action: int, reward: float,
               hidden: np.ndarray) -> None:
        self.obs = np.asarray(obs, np.uint8)
        self.last_action = np.zeros_like(self.last_action)
        self.last_action[action] = 1.0
        self.last_reward = float(reward)
        self.hidden = np.asarray(hidden, np.float32)


def fleet_shards(cfg: Config):
    """``([(lo, hi), ...], env_workers_per_fleet)`` — the single
    definition of the fleet split, shared by the thread transport
    (train._build) and the process transport (parallel/actor_procs) so
    lane→fleet assignment and the global ladder-epsilon slices can never
    diverge between transports.  Lanes split contiguously over
    ``cfg.actor_fleets``; the env-worker budget is a per-HOST tuning
    knob, split across the fleets rather than letting each fleet spawn
    its own full pool."""
    F = cfg.actor_fleets
    bounds = np.linspace(0, cfg.num_actors, F + 1).astype(int)
    shards = [(int(lo), int(hi))
              for lo, hi in zip(bounds[:-1], bounds[1:]) if lo < hi]
    workers = (cfg.env_workers + F - 1) // F if cfg.env_workers else 0
    return shards, workers


def _resolve_act_device(spec: str):
    """Device for actor inference, or None to leave placement alone.

    "auto": the CPU backend when the default backend is an accelerator
    (params get copied host-side once per refresh; every env step's
    dispatch + q fetch then stays on-host).  "cpu": force it.  "default":
    never move — inference shares the learner's device.
    """
    if spec == "default":
        return None
    try:
        cpu = jax.devices("cpu")[0]
    except Exception:  # backend absent/filtered out — leave placement alone
        return None
    if spec == "cpu" or jax.devices()[0].platform != "cpu":
        return cpu
    return None


def make_act_fn(cfg: Config, net: R2D2Network, *,
                retrace_name: str = "actor.act",
                retrace_budget: Optional[int] = None):
    """Jitted batched single-step inference:
    (params, obs (B,*obs) u8, last_action (B,A) f32, last_reward (B,) f32,
    hidden (B,2,layers,H)) → (q (B,A) f32, new hidden).

    ``retrace_name``/``retrace_budget`` override the RETRACES guard entry
    (default: one fixed lane batch, budget 2) — the session tier's
    continuous batcher (serving/batcher.py) reuses this same twin
    resolution but legitimately traces once per bucket shape, so it
    registers under its own name with a bucket-count budget.

    When actor inference runs on the host CPU backend (``cfg.act_device``
    "auto"/"cpu" with an accelerator default backend — see
    :func:`_resolve_act_device`) but the learner's network resolved the
    fused Pallas LSTM (TPU-only lowering), acting uses a **scan-impl twin**
    of the network: the two implementations declare identical parameters
    (models/network.py:resolve_lstm_impl), so the published param
    snapshots apply unchanged — the recurrence engine is just re-chosen
    for the platform the jit will actually lower on.  A CPU act twin also
    computes in float32 regardless of ``cfg.compute_dtype`` (bf16 is
    emulated on CPU; params are float32 either way)."""
    from r2d2_tpu.models.network import create_network, resolve_lstm_impl

    act_dev = _resolve_act_device(cfg.act_device)
    # act_dev None = inference stays wherever the default backend puts it
    # (e.g. evaluating a TPU-trained, explicitly-pallas config on a
    # CPU-only host) — judge by that platform instead
    platform = (act_dev.platform if act_dev is not None
                else jax.default_backend())
    twin = {}
    if (resolve_lstm_impl(cfg) == "pallas"
            and not cfg.pallas_interpret and platform != "tpu"):
        twin["lstm_impl"] = "scan"
    if platform == "cpu" and cfg.compute_dtype == "bfloat16":
        # bf16 matmuls are emulated (slow) on CPU and params are f32
        # anyway; the f32 twin is ~30% faster per inference call — material
        # when the whole fleet shares one host core with the learner loop
        twin["compute_dtype"] = "float32"
    act_net = (create_network(cfg.replace(**twin), net.action_dim)
               if twin else net)

    def act(params, obs, last_action, last_reward, hidden):
        return act_net.apply(params, obs, last_action, last_reward, hidden,
                             method=R2D2Network.act)

    # retrace-guarded (utils/trace.py): one act-fn instance serves one
    # fixed lane batch, so a second trace means shape/dtype drift in the
    # hot loop — the e2e tests assert the budget holds
    from r2d2_tpu.utils.trace import RETRACES

    return jax.jit(RETRACES.wrap(retrace_name, act,
                                 budget=retrace_budget))


class VectorActor:
    """Steps ``num_envs`` environments in lockstep with batched inference.

    ``epsilons`` gives each lane its ladder ε; lanes run independent
    episode lifecycles (reset, block cut, episode-step cap) exactly as N
    reference actors would (worker.py:516-561).
    """

    def __init__(self, cfg: Config, envs: Sequence[Any],
                 epsilons: Sequence[float], act_fn, param_store: ParamStore,
                 sink: BlockSink, rng: Optional[np.random.Generator] = None,
                 env_workers: Optional[int] = None):
        assert len(envs) == len(epsilons)
        self.cfg = cfg
        self.envs = list(envs)
        self.epsilons = np.asarray(epsilons, np.float64)
        self.act_fn = act_fn
        # serve mode (parallel/inference_service.RemoteActClient, duck-
        # typed to avoid the import cycle): acting is an RPC to the
        # trainer's InferenceService — params and recurrent state live
        # server-side, and lane resets must reach the server so it can
        # zero that lane's hidden.  ``peek`` (when the act fn offers it)
        # is the no-state-advance bootstrap forward the episode-step cap
        # needs; local act fns are pure, so the plain call doubles as it.
        self._act_client = act_fn if hasattr(act_fn, "note_reset") else None
        self._peek_fn = getattr(act_fn, "peek", act_fn)
        self.param_store = param_store
        self.sink = sink
        self.rng = rng or np.random.default_rng(cfg.seed)

        self.N = len(envs)
        self._act_device = _resolve_act_device(cfg.act_device)
        if env_workers is None:
            env_workers = cfg.env_workers
        self._pool: Optional[ThreadPoolExecutor] = None
        self._shards: List[range] = [range(self.N)]
        if env_workers > 1 and self.N > 1:
            w = min(env_workers, self.N)
            bounds = np.linspace(0, self.N, w + 1).astype(int)
            self._shards = [range(bounds[j], bounds[j + 1])
                            for j in range(w) if bounds[j] < bounds[j + 1]]
            self._pool = ThreadPoolExecutor(max_workers=len(self._shards),
                                            thread_name_prefix="env")
        self.action_dim = envs[0].action_space.n
        # one preallocated array set for all lanes: per-step recording is a
        # few vectorized writes instead of N×(list appends + array builds)
        self.vbuf = VectorLocalBuffer(cfg, self.action_dim, self.N)
        self.episode_steps = np.zeros(self.N, np.int64)
        self.finish_pending = np.zeros(self.N, bool)  # deferred boundary cut
        # per-lane block start (perf_counter): the cut event's slice spans
        # the block's whole env-step phase, so "env step → cut" renders as
        # one slice on this process's trace track (telemetry/tracing.py)
        self._block_start = np.full(self.N, time.perf_counter())
        self.actor_steps = 0
        self._param_version = 0
        self._params = None

        # batched AgentState
        self.obs = np.zeros((self.N, *cfg.stored_obs_shape), np.uint8)
        self.last_action = np.zeros((self.N, self.action_dim), np.float32)
        self.last_reward = np.zeros(self.N, np.float32)
        self.hidden = np.zeros((self.N, 2, cfg.lstm_layers, cfg.hidden_dim),
                               np.float32)
        # per-iteration env-step scratch, filled by the (possibly pooled)
        # env stepping and consumed by the vectorized batched update
        self._step_reward = np.zeros(self.N, np.float32)
        self._step_done = np.zeros(self.N, bool)
        for i in range(self.N):
            self._reset_lane(i)

    def _reset_lane(self, i: int) -> None:
        obs, _ = self.envs[i].reset()
        self.obs[i] = np.asarray(obs, np.uint8)
        self.last_action[i] = 0.0
        self.last_reward[i] = 0.0
        self.hidden[i] = 0.0
        self.vbuf.reset_lane(i, self.obs[i])
        self.episode_steps[i] = 0
        self.finish_pending[i] = False
        self._block_start[i] = time.perf_counter()
        if self._act_client is not None:
            self._act_client.note_reset(i)

    def _refresh_params(self) -> None:
        if self._act_client is not None:
            return  # serve mode: weights never leave the trainer
        if self._act_device is not None:
            # actor inference runs on the CPU backend: the reference's
            # actors hold CPU model copies (worker.py:504-507), and on an
            # accelerator learner this keeps the per-env-step
            # dispatch+q-fetch off the device interconnect entirely.  One
            # params transfer per refresh (every actor_update_interval
            # steps) replaces a round trip per env step — and the placed
            # copy is CACHED per published version, so a multi-fleet
            # actor plane pays the device→host wire transfer once per
            # publish, not once per fleet.
            version, params = self.param_store.get_placed(self._act_device)
            if params is not None and version != self._param_version:
                self._params = params
                self._param_version = version
            return
        version, params = self.param_store.get()
        if params is not None and version != self._param_version:
            if isinstance(jax.tree.leaves(params)[0], np.ndarray):
                # multi-host publishes HOST arrays (learner._publish) so
                # actor jits stay process-local; commit them to one local
                # device per refresh rather than re-uploading every call
                params = jax.device_put(params, jax.local_devices()[0])
            self._params = params
            self._param_version = version

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """Resumable actor state for the full-state checkpoint: exploration
        RNG, per-lane episode lifecycle, batched agent state, the local
        block-assembly buffers, and — for envs that support ALE-style
        ``clone_state()`` — the env emulator state itself.

        Call only while the actor is quiescent (between :meth:`run` bursts
        / after the fabric stopped): the arrays are not lock-protected.
        Lanes whose env cannot snapshot are restored by reset — their
        in-progress episode is the only loss."""
        env_states = []
        for e in self.envs:
            fn = getattr(e, "clone_state", None)
            try:
                env_states.append(fn() if callable(fn) else None)
            except Exception:
                env_states.append(None)
        return dict(
            num_lanes=self.N,
            rng=self.rng.bit_generator.state,
            actor_steps=int(self.actor_steps),
            episode_steps=self.episode_steps.copy(),
            finish_pending=self.finish_pending.copy(),
            agent=dict(obs=self.obs.copy(), last_action=self.last_action.copy(),
                       last_reward=self.last_reward.copy(),
                       hidden=self.hidden.copy()),
            vbuf=self.vbuf.snapshot(),
            env_states=env_states,
        )

    def restore(self, snap: dict) -> None:
        """Resume from a :meth:`snapshot`.  Lanes with a captured env state
        continue their episode (and in-progress block) mid-stream; the
        rest are reset.  Raises ValueError on a lane-count mismatch (the
        caller warns and resumes cold)."""
        if int(snap["num_lanes"]) != self.N:
            raise ValueError(
                f"actor snapshot has {snap['num_lanes']} lanes, this actor "
                f"has {self.N} — resuming cold")
        if self._act_client is not None:
            # lanes resuming mid-episode must not request a server-side
            # hidden zero — the restored server state is authoritative;
            # non-resumable lanes re-note themselves via _reset_lane below
            self._act_client.clear_reset_notes()
        self.rng.bit_generator.state = snap["rng"]
        self.actor_steps = int(snap["actor_steps"])
        self.episode_steps[:] = snap["episode_steps"]
        self.finish_pending[:] = snap["finish_pending"]
        # belt over the sink-unwind ordering above: a deferred cut is only
        # meaningful for a lane with an unfinished block
        self.finish_pending &= np.asarray(snap["vbuf"]["size"]) > 0
        agent = snap["agent"]
        self.obs[:] = agent["obs"]
        self.last_action[:] = agent["last_action"]
        self.last_reward[:] = agent["last_reward"]
        self.hidden[:] = agent["hidden"]
        self.vbuf.load_snapshot(snap["vbuf"])
        for i, st in enumerate(snap["env_states"]):
            fn = getattr(self.envs[i], "restore_state", None)
            if st is not None and callable(fn):
                fn(st)
            else:
                self._reset_lane(i)  # env can't resume: fresh episode

    def _note_cut(self, i: int, block: Block) -> None:
        """Block-lineage hook at every cut: under an armed capture window
        (telemetry/tracing.py) the block gets a fabric-unique trace id
        and the cut emits the lineage flow START — a slice covering the
        block's env-step phase on this process's track.  Disarmed cost:
        one attribute check and one clock read per BLOCK (not per
        step)."""
        now = time.perf_counter()
        if EVENTS.armed:
            block.trace_id = EVENTS.next_trace_id()
            EVENTS.complete("block.env_steps+cut",
                            float(self._block_start[i]),
                            now - float(self._block_start[i]),
                            flow=block.trace_id, fph="s", arg=i)
        self._block_start[i] = now

    def _step_shard(self, lanes: range, actions: np.ndarray) -> None:
        """Env-step a contiguous lane shard (the only per-lane Python left
        in the hot loop — the gym API is per-env; ALE releases the GIL in
        ``step`` so shards scale across the thread pool).  Results land in
        the batched scratch arrays; all bookkeeping is vectorized later."""
        for i in lanes:
            obs, reward, terminated, truncated, _ = self.envs[i].step(
                int(actions[i]))
            self.obs[i] = np.asarray(obs, np.uint8)
            self._step_reward[i] = reward
            self._step_done[i] = terminated or truncated

    def close(self) -> None:
        """Shut down the env-worker pool (no-op for serial actors).  The
        actor remains usable afterwards — it falls back to serial stepping
        over ALL lanes."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
            self._shards = [range(self.N)]

    def run(self, max_steps: int, stop: Optional[Callable[[], bool]] = None
            ) -> None:
        """Run ``max_steps`` lockstep iterations (= per-actor env steps)."""
        cfg = self.cfg
        self._refresh_params()
        assert self._params is not None or self._act_client is not None, \
            "ParamStore must hold initial params"

        for _ in range(max_steps):
            if stop is not None and stop():
                return
            q, new_hidden = self.act_fn(self._params, self.obs,
                                        self.last_action, self.last_reward,
                                        self.hidden)
            q = np.asarray(q)
            new_hidden = np.asarray(new_hidden)

            # deferred block-boundary cuts: this iteration's Q at the new
            # state is the bootstrap value (worker.py:550-554 semantics,
            # without the second forward)
            for i in np.nonzero(self.finish_pending)[0]:
                # clear BEFORE the sink call: a sink that unwinds mid-
                # delivery (FleetStopped during shutdown) must leave the
                # lane consistent — vbuf already finished, flag cleared —
                # or a snapshot taken now would re-finish an empty lane
                # at resume
                self.finish_pending[i] = False
                item = self.vbuf.finish(i, q[i])
                self._note_cut(i, item[0])
                self.sink(*item)

            explore = self.rng.random(self.N) < self.epsilons
            actions = np.where(explore,
                               self.rng.integers(self.action_dim, size=self.N),
                               q.argmax(axis=1)).astype(np.int64)

            # env stepping: per-lane (gym API), possibly pooled
            if self._pool is None:
                self._step_shard(self._shards[0], actions)
            else:
                futures = [self._pool.submit(self._step_shard, shard, actions)
                           for shard in self._shards]
                for f in futures:
                    f.result()

            # all per-step bookkeeping, vectorized over the whole fleet
            # (reference actor body worker.py:537-554, batched)
            lanes = np.arange(self.N)
            self.last_action[:] = 0.0
            self.last_action[lanes, actions] = 1.0
            self.last_reward[:] = self._step_reward
            np.copyto(self.hidden, new_hidden)
            self.episode_steps += 1
            self.vbuf.add_batch(lanes, actions, self._step_reward, self.obs,
                                q, new_hidden)

            done_lanes = np.nonzero(self._step_done)[0]
            for i in done_lanes:
                # reset BEFORE the sink call (the finished Block owns
                # copies, never vbuf storage): a sink that unwinds during
                # shutdown must leave the lane consistent for the
                # shutdown snapshot — same ordering as the boundary cut
                item = self.vbuf.finish(i, None)
                self._note_cut(i, item[0])
                self._reset_lane(i)
                self.sink(*item)

            capped = np.nonzero(~self._step_done
                                & (self.episode_steps >= cfg.max_episode_steps)
                                )[0]
            boundary = ~self._step_done & (self.vbuf.sizes()
                                           == cfg.block_length)
            self.finish_pending |= boundary & (self.episode_steps
                                               < cfg.max_episode_steps)
            self._step_done[:] = False

            if capped.size:
                # episode-step cap (rare): the bootstrap must be Q at the
                # post-step state (worker.py:550-554 runs a second forward);
                # one extra batched forward covers all capped lanes; the
                # peek variant (serve mode) must not advance server state
                q_fresh, _ = self._peek_fn(self._params, self.obs,
                                           self.last_action,
                                           self.last_reward, self.hidden)
                q_fresh = np.asarray(q_fresh)
                for i in capped:
                    item = self.vbuf.finish(i, q_fresh[i])
                    self._note_cut(i, item[0])
                    self._reset_lane(i)  # before the sink; see done_lanes
                    self.sink(*item)

            self.actor_steps += 1
            if self.actor_steps % cfg.actor_update_interval == 0:
                self._refresh_params()


class Actor(VectorActor):
    """A single-env actor — the reference's unit of deployment
    (worker.py:500-515), as a 1-lane vector actor.  Used by the process
    fabric where each actor owns a thread, and by tests."""

    def __init__(self, cfg: Config, env: Any, epsilon: float, act_fn,
                 param_store: ParamStore, sink: BlockSink,
                 rng: Optional[np.random.Generator] = None):
        super().__init__(cfg, [env], [epsilon], act_fn, param_store, sink,
                         rng=rng)
