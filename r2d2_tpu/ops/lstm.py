"""Pallas fused LSTM **inference** unroll for TPU.

Why: under ``jax.lax.scan`` each of the T unroll steps is its own XLA loop
iteration that re-reads the (H, 4H) recurrent kernel from HBM and pays
per-step kernel overhead.  This kernel runs the whole unroll as one Pallas
program: a sequential grid over T with the recurrent weights, h, and c held
in VMEM across steps, so HBM traffic per step is just the (B, 4H)
input-projection slice in and the (B, H) hidden slice out.  It is the
TPU-native stand-in for the implicit cuDNN fused LSTM the reference gets
for free on the acting path (reference model.py:51,65-79).

**Inference-only — the backward kernel was retired in round 5.**  The
round-4 on-chip measurement (tools/measure_tpu.py:pallas_lstm_section,
v5e, B=64 T=85 H=512 bf16) put the fused forward+backward at 0.96x the
scan recurrence: XLA's scan lowering on current runtimes already keeps
the MXU busy through the training path, so a 150-line custom-VJP kernel
bought nothing there.  The forward-only (inference) path kept a 1.07x
edge — actors and evaluators stream no residuals, and the kernel's
VMEM-resident h/c is exactly what a T=1..85 acting unroll wants — so that
half stays.  Training always runs the scan (learner/step.py builds its
loss networks with ``lstm_impl="scan"``); differentiating through this
kernel is unsupported and raises at trace time.

Numerics: matmul operands are cast to ``compute_dtype`` (bfloat16 in the
flagship config) with float32 accumulation — one rounding *less* than the
scan path's bf16-output matmul, so results match the scan reference to
bf16 tolerance (exactly, in float32 mode).  See tests/test_lstm_pallas.py.
"""
from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM = pltpu.VMEM


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _fwd_infer_kernel(xp_ref, wh_ref, h0_ref, c0_ref,
                      hs_ref, cT_ref, h_scr, c_scr, *, compute_dtype):
    """Residual-free forward: per step ``gates = xp[t] + h @ wh`` (MXU,
    float32 accumulation), gate nonlinearities on the VPU (order i,f,g,o),
    h/c carried in VMEM scratch across the sequential grid."""
    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    c = c_scr[:]
    H = h.shape[-1]
    gates = xp_ref[0] + jnp.dot(h.astype(compute_dtype), wh_ref[:],
                                preferred_element_type=jnp.float32)
    si = _sigmoid(gates[:, 0 * H:1 * H])
    sf = _sigmoid(gates[:, 1 * H:2 * H])
    tg = jnp.tanh(gates[:, 2 * H:3 * H])
    so = _sigmoid(gates[:, 3 * H:4 * H])
    c_new = sf * c + si * tg
    h_new = so * jnp.tanh(c_new)

    hs_ref[0] = h_new
    h_scr[:] = h_new
    c_scr[:] = c_new

    @pl.when(t == T - 1)
    def _():
        cT_ref[:] = c_new


@functools.lru_cache(maxsize=None)
def make_lstm_infer(compute_dtype: Any, interpret: bool):
    """Build the fused inference unroll for one (dtype, interpret) combo.

    Returned fn: ``(xp, wh, h0, c0) -> (hs, h_T, c_T)`` with
    - ``xp``: (T, B, 4H) float32 — hoisted input projection (x@wi + b),
    - ``wh``: (H, 4H) in ``compute_dtype``,
    - ``h0``/``c0``: (B, H) float32,
    - ``hs``: (T, B, H) float32 hidden states, ``h_T``/``c_T`` finals.

    NOT differentiable (the backward kernel was retired; see module
    docstring) — use the scan recurrence for any grad path.
    """
    cd = compute_dtype

    def _scratch(shape):
        return pltpu.VMEM(shape, jnp.float32)

    def _infer_call(xp, wh, h0, c0):
        T, B, H4 = xp.shape
        H = H4 // 4
        f32 = jnp.float32
        kernel = functools.partial(_fwd_infer_kernel, compute_dtype=cd)
        mem = {} if interpret else dict(memory_space=_VMEM)
        hs, cT = pl.pallas_call(
            kernel,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0), **mem),
                pl.BlockSpec((H, H4), lambda t: (0, 0), **mem),
                pl.BlockSpec((B, H), lambda t: (0, 0), **mem),
                pl.BlockSpec((B, H), lambda t: (0, 0), **mem),
            ],
            out_specs=[
                pl.BlockSpec((1, B, H), lambda t: (t, 0, 0), **mem),
                pl.BlockSpec((B, H), lambda t: (0, 0), **mem),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, H), f32),
                jax.ShapeDtypeStruct((B, H), f32),
            ],
            scratch_shapes=[
                _scratch((B, H)),
                _scratch((B, H)),
            ],
            interpret=interpret,
        )(xp, wh, h0.astype(f32), c0.astype(f32))
        return hs, hs[-1], cT

    return _infer_call


def lstm_unroll_pallas(xp_tm: jnp.ndarray, wh: jnp.ndarray, h0: jnp.ndarray,
                       c0: jnp.ndarray, *, compute_dtype: Any = jnp.bfloat16,
                       interpret: bool = False
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused inference unroll: see :func:`make_lstm_infer` for shapes."""
    fn = make_lstm_infer(compute_dtype, interpret)
    return fn(xp_tm, wh.astype(compute_dtype), h0, c0)
