"""Pallas fused LSTM unroll for TPU.

Why: the hot op of the R2D2 train step is the LSTM recurrence (the analogue
of the reference's cuDNN LSTM calls, model.py:51,95-100).  Under
``jax.lax.scan`` each of the T=85 steps is its own XLA loop iteration that
re-reads the (H, 4H) recurrent kernel from HBM and pays per-step kernel
overhead — measured ~20 µs/step on v5e where the recurrent matmul itself is
<1 µs of MXU time.  This kernel runs the **whole unroll as one Pallas
program**: a sequential grid over T with the recurrent weights, h, and c
held in VMEM across steps, so HBM traffic per step is just the (B, 4H)
input-projection slice in and the (B, H) hidden slice out.

Design:
- Forward: grid (T,).  Scratch ``h``/``c`` (float32) persist across the
  sequential TPU grid.  Per step: ``gates = xp[t] + h @ wh`` (MXU,
  float32 accumulation), gate nonlinearities on the VPU, then h/c update.
  Activated gates and cell states are streamed out as residuals for the
  backward pass.
- Backward: custom VJP, grid (T,) iterated in reverse via the BlockSpec
  index maps.  Carries ``dh``/``dc`` in scratch, accumulates ``dwh`` in a
  float32 VMEM scratch written out once at the final grid step, and emits
  the per-step ``dxp`` cotangent.  Gradients for the input projection
  (``wi``, ``b``, ``xs``) fall out of XLA's autodiff of the (hoisted)
  projection matmul outside this kernel.
- Matmul operands are cast to ``compute_dtype`` (bfloat16 in the flagship
  config) with float32 accumulation — one rounding *less* than the scan
  path's bf16-output matmul, so results match the scan reference to bf16
  tolerance (exactly, in float32 mode).  See tests/test_lstm_pallas.py.

The reference has no analogue: this is the TPU-native replacement for the
implicit cuDNN fused LSTM the torch code gets for free.
"""
from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM = pltpu.VMEM


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _fwd_kernel(xp_ref, wh_ref, h0_ref, c0_ref,
                hs_ref, cs_ref, gates_ref, h_scr, c_scr, *, compute_dtype):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    c = c_scr[:]
    H = h.shape[-1]
    gates = xp_ref[0] + jnp.dot(h.astype(compute_dtype), wh_ref[:],
                                preferred_element_type=jnp.float32)
    si = _sigmoid(gates[:, 0 * H:1 * H])
    sf = _sigmoid(gates[:, 1 * H:2 * H])
    tg = jnp.tanh(gates[:, 2 * H:3 * H])
    so = _sigmoid(gates[:, 3 * H:4 * H])
    c_new = sf * c + si * tg
    h_new = so * jnp.tanh(c_new)

    gates_ref[0] = jnp.concatenate([si, sf, tg, so], axis=-1)
    hs_ref[0] = h_new
    cs_ref[0] = c_new
    h_scr[:] = h_new
    c_scr[:] = c_new


def _fwd_infer_kernel(xp_ref, wh_ref, h0_ref, c0_ref,
                      hs_ref, cT_ref, h_scr, c_scr, *, compute_dtype):
    """Residual-free forward for the primal (inference) path: same math as
    :func:`_fwd_kernel` but without streaming gates/cell states to HBM —
    actors and evaluators only need hs and the final (h, c)."""
    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    c = c_scr[:]
    H = h.shape[-1]
    gates = xp_ref[0] + jnp.dot(h.astype(compute_dtype), wh_ref[:],
                                preferred_element_type=jnp.float32)
    si = _sigmoid(gates[:, 0 * H:1 * H])
    sf = _sigmoid(gates[:, 1 * H:2 * H])
    tg = jnp.tanh(gates[:, 2 * H:3 * H])
    so = _sigmoid(gates[:, 3 * H:4 * H])
    c_new = sf * c + si * tg
    h_new = so * jnp.tanh(c_new)

    hs_ref[0] = h_new
    h_scr[:] = h_new
    c_scr[:] = c_new

    @pl.when(t == T - 1)
    def _():
        cT_ref[:] = c_new


def _bwd_kernel(dhs_ref, dcT_ref, wh_ref, gates_ref, cs_ref, hprev_ref,
                cprev_ref, dxp_ref, dwh_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, dwh_scr, *, compute_dtype):
    pid = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(pid == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = dcT_ref[:]
        dwh_scr[:] = jnp.zeros_like(dwh_scr)

    H = dh_scr.shape[-1]
    # cotangent for h_s: carried dh plus this step's output cotangent
    dh = dh_scr[:] + dhs_ref[0]
    g = gates_ref[0]
    si = g[:, 0 * H:1 * H]
    sf = g[:, 1 * H:2 * H]
    tg = g[:, 2 * H:3 * H]
    so = g[:, 3 * H:4 * H]
    tc = jnp.tanh(cs_ref[0])

    do_ = dh * tc
    dc = dc_scr[:] + dh * so * (1.0 - tc * tc)
    di = dc * tg
    dg = dc * si
    df = dc * cprev_ref[0]
    dc_prev = dc * sf

    dzi = di * si * (1.0 - si)
    dzf = df * sf * (1.0 - sf)
    dzg = dg * (1.0 - tg * tg)
    dzo = do_ * so * (1.0 - so)
    dz = jnp.concatenate([dzi, dzf, dzg, dzo], axis=-1)  # (B, 4H) f32

    dxp_ref[0] = dz
    dz_cd = dz.astype(compute_dtype)
    # dh_prev = dz @ wh^T : contract the 4H dim
    dh_prev = jax.lax.dot_general(
        dz_cd, wh_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    # dwh += h_prev^T @ dz : contract the batch dim
    dwh_scr[:] += jax.lax.dot_general(
        hprev_ref[0].astype(compute_dtype), dz_cd,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    dh_scr[:] = dh_prev
    dc_scr[:] = dc_prev

    @pl.when(pid == T - 1)
    def _():
        dwh_ref[:] = dwh_scr[:]
        dh0_ref[:] = dh_prev
        dc0_ref[:] = dc_prev


@functools.lru_cache(maxsize=None)
def make_lstm_unroll(compute_dtype: Any, interpret: bool):
    """Build the custom-VJP fused unroll for one (dtype, interpret) combo.

    Returned fn: ``(xp, wh, h0, c0) -> (hs, h_T, c_T)`` with
    - ``xp``: (T, B, 4H) float32 — hoisted input projection (x@wi + b),
    - ``wh``: (H, 4H) in ``compute_dtype``,
    - ``h0``/``c0``: (B, H) float32,
    - ``hs``: (T, B, H) float32 hidden states, ``h_T``/``c_T`` finals.

    Differentiable in xp, wh, h0, c0.
    """
    cd = compute_dtype

    def _scratch(shape):
        return pltpu.VMEM(shape, jnp.float32)

    def _fwd_call(xp, wh, h0, c0):
        T, B, H4 = xp.shape
        H = H4 // 4
        f32 = jnp.float32
        kernel = functools.partial(_fwd_kernel, compute_dtype=cd)
        mem = {} if interpret else dict(memory_space=_VMEM)
        hs, cs, gates = pl.pallas_call(
            kernel,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0), **mem),
                pl.BlockSpec((H, H4), lambda t: (0, 0), **mem),
                pl.BlockSpec((B, H), lambda t: (0, 0), **mem),
                pl.BlockSpec((B, H), lambda t: (0, 0), **mem),
            ],
            out_specs=[
                pl.BlockSpec((1, B, H), lambda t: (t, 0, 0), **mem),
                pl.BlockSpec((1, B, H), lambda t: (t, 0, 0), **mem),
                pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0), **mem),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, H), f32),
                jax.ShapeDtypeStruct((T, B, H), f32),
                jax.ShapeDtypeStruct((T, B, H4), f32),
            ],
            scratch_shapes=[
                _scratch((B, H)),
                _scratch((B, H)),
            ],
            interpret=interpret,
        )(xp, wh, h0.astype(f32), c0.astype(f32))
        return hs, cs, gates

    def _infer_call(xp, wh, h0, c0):
        T, B, H4 = xp.shape
        H = H4 // 4
        f32 = jnp.float32
        kernel = functools.partial(_fwd_infer_kernel, compute_dtype=cd)
        mem = {} if interpret else dict(memory_space=_VMEM)
        hs, cT = pl.pallas_call(
            kernel,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0), **mem),
                pl.BlockSpec((H, H4), lambda t: (0, 0), **mem),
                pl.BlockSpec((B, H), lambda t: (0, 0), **mem),
                pl.BlockSpec((B, H), lambda t: (0, 0), **mem),
            ],
            out_specs=[
                pl.BlockSpec((1, B, H), lambda t: (t, 0, 0), **mem),
                pl.BlockSpec((B, H), lambda t: (0, 0), **mem),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, H), f32),
                jax.ShapeDtypeStruct((B, H), f32),
            ],
            scratch_shapes=[
                _scratch((B, H)),
                _scratch((B, H)),
            ],
            interpret=interpret,
        )(xp, wh, h0.astype(f32), c0.astype(f32))
        return hs, cT

    def _bwd_call(wh, hs, cs, gates, h0, c0, dhs, dcT):
        T, B, H = hs.shape
        H4 = 4 * H
        f32 = jnp.float32
        hprev = jnp.concatenate([h0.astype(f32)[None], hs[:-1]], axis=0)
        cprev = jnp.concatenate([c0.astype(f32)[None], cs[:-1]], axis=0)
        kernel = functools.partial(_bwd_kernel, compute_dtype=cd)
        mem = {} if interpret else dict(memory_space=_VMEM)
        rev = lambda t: (T - 1 - t, 0, 0)  # noqa: E731 — reversed time
        fix = lambda t: (0, 0)             # noqa: E731
        dxp, dwh, dh0, dc0 = pl.pallas_call(
            kernel,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((1, B, H), rev, **mem),    # dhs
                pl.BlockSpec((B, H), fix, **mem),       # dcT
                pl.BlockSpec((H, H4), fix, **mem),      # wh
                pl.BlockSpec((1, B, H4), rev, **mem),   # gates
                pl.BlockSpec((1, B, H), rev, **mem),    # cs
                pl.BlockSpec((1, B, H), rev, **mem),    # hprev
                pl.BlockSpec((1, B, H), rev, **mem),    # cprev
            ],
            out_specs=[
                pl.BlockSpec((1, B, H4), rev, **mem),   # dxp
                pl.BlockSpec((H, H4), fix, **mem),      # dwh
                pl.BlockSpec((B, H), fix, **mem),       # dh0
                pl.BlockSpec((B, H), fix, **mem),       # dc0
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, H4), f32),
                jax.ShapeDtypeStruct((H, H4), f32),
                jax.ShapeDtypeStruct((B, H), f32),
                jax.ShapeDtypeStruct((B, H), f32),
            ],
            scratch_shapes=[
                _scratch((B, H)),
                _scratch((B, H)),
                _scratch((H, H4)),
            ],
            interpret=interpret,
        )(dhs, dcT, wh, gates, cs, hprev, cprev)
        return dxp, dwh, dh0, dc0

    @jax.custom_vjp
    def lstm_unroll(xp, wh, h0, c0):
        # primal (inference) path: no backward will run, so skip the
        # gates/cs residual streams — ~6x less HBM write traffic for the
        # actor/eval unrolls.  fwd() below is what grad tracing uses.
        hs, cT = _infer_call(xp, wh, h0, c0)
        return hs, hs[-1], cT

    def fwd(xp, wh, h0, c0):
        hs, cs, gates = _fwd_call(xp, wh, h0, c0)
        return (hs, hs[-1], cs[-1]), (wh, hs, cs, gates, h0, c0)

    def bwd(res, cot):
        wh, hs, cs, gates, h0, c0 = res
        dhs, dhT, dcT = cot
        # the final-h cotangent is just an extra contribution to hs[-1]
        dhs = dhs.at[-1].add(dhT)
        dxp, dwh, dh0, dc0 = _bwd_call(wh, hs, cs, gates, h0, c0, dhs, dcT)
        return dxp, dwh.astype(wh.dtype), dh0, dc0

    lstm_unroll.defvjp(fwd, bwd)
    return lstm_unroll


def lstm_unroll_pallas(xp_tm: jnp.ndarray, wh: jnp.ndarray, h0: jnp.ndarray,
                       c0: jnp.ndarray, *, compute_dtype: Any = jnp.bfloat16,
                       interpret: bool = False
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused LSTM unroll: see :func:`make_lstm_unroll` for shapes."""
    fn = make_lstm_unroll(compute_dtype, interpret)
    return fn(xp_tm, wh.astype(compute_dtype), h0, c0)
