"""TPU Pallas kernels for the hot ops."""
from r2d2_tpu.ops.lstm import lstm_unroll_pallas, make_lstm_infer

__all__ = ["lstm_unroll_pallas", "make_lstm_infer"]
