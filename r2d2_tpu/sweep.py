"""Benchmark-ladder sweep orchestration (BASELINE.json configs[3]).

The reference has no multi-game story — one `config.py` edit per run
(README.md:6).  This module runs the Atari-57 ladder (or any game list) as
a sequence of isolated runs: per-game config, per-game checkpoint
directory, training followed by the evaluator's checkpoint sweep, and a
machine-readable summary (`sweep.json`) accumulating learning curves —
resumable per game, so a killed sweep continues where it stopped.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

from r2d2_tpu.config import Config

# The canonical Atari-57 benchmark set (paper list; ALE v5 names).
ATARI_57: List[str] = [
    "Alien", "Amidar", "Assault", "Asterix", "Asteroids", "Atlantis",
    "BankHeist", "BattleZone", "BeamRider", "Berzerk", "Bowling", "Boxing",
    "Breakout", "Centipede", "ChopperCommand", "CrazyClimber", "Defender",
    "DemonAttack", "DoubleDunk", "Enduro", "FishingDerby", "Freeway",
    "Frostbite", "Gopher", "Gravitar", "Hero", "IceHockey", "Jamesbond",
    "Kangaroo", "Krull", "KungFuMaster", "MontezumaRevenge", "MsPacman",
    "NameThisGame", "Phoenix", "Pitfall", "Pong", "PrivateEye", "Qbert",
    "Riverraid", "RoadRunner", "Robotank", "Seaquest", "Skiing", "Solaris",
    "SpaceInvaders", "StarGunner", "Surround", "Tennis", "TimePilot",
    "Tutankham", "UpNDown", "Venture", "VideoPinball", "WizardOfWor",
    "YarsRevenge", "Zaxxon",
]


def run_sweep(games: List[str], base_cfg: Config, out_dir: str,
              env_factory: Optional[Callable[[Config, int], Any]] = None,
              train_fn: Optional[Callable[..., Dict[str, Any]]] = None,
              eval_episodes: Optional[int] = None,
              max_wall_seconds_per_game: Optional[float] = None,
              use_mesh: bool = False, verbose: bool = True
              ) -> Dict[str, Any]:
    """Train + evaluate each game; returns (and writes) the summary.

    Layout: ``out_dir/<game>/`` holds that game's checkpoints;
    ``out_dir/sweep.json`` accumulates per-game results as each finishes.
    A game whose summary entry shows ``num_updates >= training_steps`` is
    skipped; a partially-trained game (e.g. stopped by
    ``max_wall_seconds_per_game``) re-enters training from its checkpoint.
    """
    from r2d2_tpu.envs import create_env
    from r2d2_tpu.evaluate import evaluate_sweep
    from r2d2_tpu.train import train

    train_fn = train_fn or train
    env_factory = env_factory or (
        lambda cfg, seed: create_env(cfg, noop_start=True, seed=seed))
    os.makedirs(out_dir, exist_ok=True)
    summary_path = os.path.join(out_dir, "sweep.json")
    summary: Dict[str, Any] = {}
    if os.path.exists(summary_path):
        with open(summary_path) as f:
            summary = json.load(f)

    for game in games:
        # Skip only games that actually reached the training target: a game
        # cut short by max_wall_seconds_per_game records its partial
        # num_updates and re-enters training (resume=True) on the next
        # sweep invocation — time-sliced sweeps keep making progress.
        prior = summary.get(game)
        if (prior is not None
                and prior.get("num_updates", 0) >= base_cfg.training_steps):
            if verbose:
                print(f"[sweep] {game}: already done, skipping", flush=True)
            continue
        cfg = base_cfg.replace(game_name=game)
        ckpt_dir = os.path.join(out_dir, game)
        if verbose:
            print(f"[sweep] {game}: training → {ckpt_dir}", flush=True)
        metrics = train_fn(cfg, env_factory=env_factory,
                           checkpoint_dir=ckpt_dir, resume=True,
                           use_mesh=use_mesh,
                           max_wall_seconds=max_wall_seconds_per_game,
                           verbose=verbose)
        eval_factory = (
            lambda c, seed: env_factory(c.replace(game_name=game), seed))
        curve = evaluate_sweep(cfg, ckpt_dir, env_factory=eval_factory,
                               episodes=eval_episodes)
        summary[game] = dict(
            num_updates=int(metrics.get("num_updates", 0)),
            env_steps=int(metrics.get("env_steps", 0)),
            minutes=float(metrics.get("minutes", 0.0)),
            mean_loss=float(metrics.get("mean_loss", float("nan"))),
            curve=curve,
            final_reward=(curve[-1]["mean_reward"] if curve else None),
        )
        with open(summary_path, "w") as f:
            json.dump(summary, f, indent=1)
        if verbose:
            print(f"[sweep] {game}: final reward "
                  f"{summary[game]['final_reward']}", flush=True)
    return summary
