"""r2d2_tpu — a TPU-native (JAX/XLA/pjit) R2D2 distributed RL framework.

A from-scratch re-design of the capabilities of ZiyuanMa/R2D2
(Recurrent Experience Replay in Distributed RL, Kapturowski et al. 2019):
Ape-X actor fleets, prioritised sequence replay with burn-in and stored
recurrent state, dueling CNN+LSTM Q-networks, n-step double-Q targets under
value rescaling — built TPU-first on jax.jit / jax.sharding / lax.scan.
"""

from r2d2_tpu.config import (
    Config,
    smoke_config,
    pong_config,
    hard_exploration_config,
    atari57_config,
    impala_deep_config,
    test_config,
)
from r2d2_tpu.checkpoint import Checkpointer
from r2d2_tpu.evaluate import evaluate_params, evaluate_sweep
from r2d2_tpu.train import train, train_sync

__version__ = "0.4.0"
