"""Training orchestration.

Capability-parity with the reference's process topology (train.py:20-44 and
worker.py:77-138): an actor fleet generating blocks, a replay data plane
with three concurrent planes (block ingest / batch assembly / priority
feedback), a stats log loop, and the learner driving gradient steps — plus
checkpoint/resume, which the reference lacks.

TPU-first redesign — one process, many threads, one device program:

- The reference needs N+2 *processes* because CPython+torch actors are
  GIL-bound.  Here actor inference is a single batched jitted call per
  fleet (r2d2_tpu/actor.py), so ``cfg.actor_fleets`` threads (default 1)
  cover the whole lane set; JAX releases the GIL during device execution,
  so actor inference, env stepping, host batch assembly, H2D prefetch,
  and the learner step genuinely overlap.
- Queues are ``queue.Queue`` handoffs between threads rather than pickle
  pipes between processes — blocks move by reference, zero-copy.
- Weight flow is the versioned ParamStore (no shared-memory mutation).
- Multi-host scaling is the learner mesh (parallel/mesh.py), not more
  host processes: the data plane stays host-local per slice, the gradient
  collectives ride ICI.

``train()`` is the threaded fabric; ``train_sync()`` is a deterministic
single-thread interleaving of the same components (the reference's
semantics with ``num_actors`` lanes and no concurrency) used by the
integration tests and useful for debugging.

``cfg.actor_transport = "process"`` swaps the in-process actor threads
for subprocess fleets (parallel/actor_procs.py): blocks come back over a
preallocated shared-memory channel and weights go out on a versioned
publication queue — the reference's N-process acting topology
(train.py:30-34) for GIL-bound envs / multi-core hosts; the rest of the
fabric (replay, learner, supervision) is unchanged.  On top of it,
``cfg.actor_inference = "serve"`` centralizes acting (Sebulba/Seed-RL):
the fleets stop running the network and every env step becomes an RPC to
an InferenceService fabric thread that batches across all fleets and
runs one device act per step (parallel/inference_service.py).
"""
from __future__ import annotations

import collections
import logging
import os
import queue
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from r2d2_tpu.actor import VectorActor, fleet_shards, make_act_fn
from r2d2_tpu.checkpoint import Checkpointer
from r2d2_tpu.config import Config
from r2d2_tpu.envs import create_env
from r2d2_tpu.learner.learner import Learner
from r2d2_tpu.learner.step import create_train_state
from r2d2_tpu.models.network import create_network, init_params
from r2d2_tpu.parallel.mesh import make_mesh
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.telemetry import Telemetry, format_entry
from r2d2_tpu.utils.math import epsilon_ladder
from r2d2_tpu.utils.store import ParamStore
from r2d2_tpu.utils.supervisor import Heartbeat, Supervisor
from r2d2_tpu.utils.trace import Tracer, device_profile

log = logging.getLogger(__name__)

EnvFactory = Callable[[Config, int], Any]


def _default_env_factory(cfg: Config, seed: int):
    return create_env(cfg, noop_start=True, seed=seed)


def _build(cfg: Config, env_factory: EnvFactory, use_mesh: bool,
           checkpoint_dir: Optional[str], resume: bool):
    """Common bring-up: envs, net, state (maybe restored), buffer, stores.

    Returns the EFFECTIVE config under ``"cfg"``: degrade paths (e.g.
    ``in_graph_per`` without a ring) flip flags here, and ``train()``
    must make its fabric decisions from the flipped config — stripping
    the priority thread from the outer (un-flipped) config while the
    learner runs the host-sampled path wedges the learner on a full,
    undrained priority queue after ~its depth in updates.
    """
    if cfg.actor_transport == "process":
        # the fleets own the envs in their subprocesses; the trainer only
        # needs the action space to size the network/replay layouts
        probe = env_factory(cfg, cfg.seed)
        action_dim = probe.action_space.n
        try:
            probe.close()
        except Exception:
            pass
        envs = []
    else:
        envs = [env_factory(cfg, cfg.seed + i) for i in range(cfg.num_actors)]
        action_dim = envs[0].action_space.n
    net = create_network(cfg, action_dim)
    params = init_params(cfg, net, jax.random.PRNGKey(cfg.seed))
    state = create_train_state(cfg, params)

    checkpointer = (Checkpointer(checkpoint_dir, keep=cfg.keep_checkpoints)
                    if checkpoint_dir else None)
    start_env_steps, start_minutes = 0, 0.0
    if (checkpointer is not None and resume
            and checkpointer.latest_step() is not None):
        from r2d2_tpu.checkpoint import check_arch_compat

        check_arch_compat(cfg, checkpointer.peek_meta())
        state, meta = checkpointer.restore(jax.device_get(state))
        start_env_steps = int(meta.get("env_steps", 0))
        start_minutes = float(meta.get("minutes", 0.0))

    mesh = make_mesh(cfg) if use_mesh else None
    # ONE sharding table per bring-up: every sharding constructor (the
    # pjit steps, the DeviceRing slot/PER layouts, checkpoint
    # re-placement) resolves through it (parallel/sharding.py).  On a
    # 1-device trivial mesh it degenerates to all-replicated.
    from r2d2_tpu.parallel.mesh import trivial_mesh
    from r2d2_tpu.parallel.sharding import ShardingTable

    table = ShardingTable(mesh if mesh is not None else trivial_mesh(), cfg)
    if mesh is not None:
        from r2d2_tpu.parallel.distributed import host_batch_size

        # cfg.batch_size is the GLOBAL batch; this host samples only its
        # dp-axis share from its local buffer (single-process: the whole
        # batch)
        host_bs = host_batch_size(cfg, mesh)
    else:
        host_bs = cfg.batch_size
    param_store = ParamStore()
    ring = None
    if cfg.device_replay and jax.process_count() == 1:
        from r2d2_tpu.replay.device_ring import DeviceRing, resolve_layout
        from r2d2_tpu.replay.replay_buffer import data_bytes

        need, dev_cap = data_bytes(cfg, action_dim), _device_memory_bytes()
        if dev_cap is not None:
            cap = dev_cap
        else:
            # backend exposes no memory stats (e.g. the CPU client):
            # "device" memory IS host memory, so apply the host guard
            from r2d2_tpu.replay.replay_buffer import _available_host_bytes

            cap = _available_host_bytes()
        # "auto" shards the slot axis over dp when the ring outgrows one
        # device's HBM; the guard below then checks the per-device share.
        # Only genuine per-device stats (dev_cap) may trigger
        # auto-sharding: on a host-RAM fallback cap every "device" shares
        # one memory, so splitting the accounting per device would wave
        # through a ring the host cannot hold (an explicit 'dp' request
        # still honours the user's judgement).
        layout = resolve_layout(cfg, mesh, need, dev_cap)
        # budget per real device; against a host-RAM fallback cap the
        # shards share one memory, so the whole ring is the burden
        per_device = (need // (mesh.shape["dp"] if layout == "dp" else 1)
                      if dev_cap is not None else need)
        if cap is not None and per_device > 0.8 * cap:
            import warnings

            warnings.warn(
                f"device_replay ring needs {per_device / 1e9:.1f} GB per "
                f"device (layout={layout}) but the device has "
                f"{cap / 1e9:.1f} GB; falling back to host replay — "
                "reduce buffer_capacity to fit", stacklevel=2)
        else:
            ring = (DeviceRing(cfg, action_dim, table=table, layout=layout)
                    if mesh is not None else DeviceRing(cfg, action_dim))
    elif cfg.device_replay:
        # multi-host: each host owns the slot slabs of its dp groups — a
        # dp-layout ring over its LOCAL submesh.  The learner stitches the
        # global ring view per super-step (Learner._run_device_multihost).
        import warnings

        if mesh is None or cfg.device_ring_layout == "replicated":
            warnings.warn(
                "multi-host device_replay needs the global mesh and a "
                "sharded ring (device_ring_layout 'auto'/'dp'); using "
                "host staging instead", stacklevel=2)
        else:
            from r2d2_tpu.parallel.distributed import local_mesh, sync_counter
            from r2d2_tpu.replay.device_ring import DeviceRing
            from r2d2_tpu.replay.replay_buffer import data_bytes

            lmesh = local_mesh(mesh)
            dp_local = lmesh.shape["dp"]
            need, cap = data_bytes(cfg, action_dim), _device_memory_bytes()
            shapes_ok = not (cfg.num_blocks % dp_local
                             or cfg.batch_size % mesh.shape["dp"]
                             or host_bs % dp_local)
            fits = cap is None or need // dp_local <= 0.8 * cap
            # COLLECTIVE decision: run_device's multi-host loop and run's
            # host staging issue different collective sequences, so every
            # process must pick the same path — one host failing its local
            # guard (heterogeneous HBM headroom, uneven device counts)
            # must push the whole pod to host staging, not deadlock it
            ok = sync_counter(int(shapes_ok and fits), reduce="min") > 0
            if ok:
                ring = DeviceRing(cfg, action_dim,
                                  table=ShardingTable(lmesh, cfg),
                                  layout="dp")
            else:
                warnings.warn(
                    "multi-host device_replay disabled (on at least one "
                    f"host): shapes_ok={shapes_ok} (num_blocks "
                    f"{cfg.num_blocks} vs local dp {dp_local}, batch "
                    f"{cfg.batch_size} vs dp {mesh.shape['dp']}), "
                    f"fits={fits} (ring {need / dp_local / 1e9:.1f} GB "
                    "per device); using host staging instead",
                    stacklevel=2)
    if cfg.in_graph_per and ring is None:
        # a ring fallback above (doesn't fit / multi-host shapes failed)
        # must degrade the PER plane with it: device PER cannot run on
        # host staging (ReplayBuffer would fail fast), and the reference
        # behavior here is host replay, not a crash.  The presets default
        # in_graph_per=True, so a single small-HBM chip lands here.
        import warnings

        warnings.warn(
            "in_graph_per disabled: no device ring was built (see the "
            "fallback warning above) — continuing on host-sampled PER; "
            "shrink buffer_capacity to restore the device-PER plane",
            stacklevel=2)
        cfg = cfg.replace(in_graph_per=False)
    # the learner is built AFTER the ring/in_graph_per decisions so it
    # (and everything below) sees the effective config
    learner = Learner(cfg, net, state, mesh=mesh, param_store=param_store,
                      checkpointer=checkpointer,
                      start_env_steps=start_env_steps,
                      start_minutes=start_minutes, table=table)
    replay_plane = None
    if cfg.replay_transport == "socket":
        # cross-host replay fabric (parallel/replay_net.py): the shard
        # RPCs travel as length-framed CRC'd TCP messages, so the K
        # shards may be remote `r2d2_tpu replay-shard` servers
        # (cfg.replay_hosts) or plane-spawned loopback processes (the
        # tier-1-testable default).  Same facade as the shm plane;
        # config validation already rejected device_replay/anakin here.
        from r2d2_tpu.parallel.replay_net import NetShardedReplayPlane

        buffer = NetShardedReplayPlane(
            cfg, action_dim, rng=np.random.default_rng(cfg.seed))
        replay_plane = buffer
    elif cfg.replay_shards > 1:
        # sharded replay plane (parallel/replay_shards.py): K owner
        # processes each run the ReplayBuffer core over their slot
        # slice; this coordinator facade fills the buffer role in the
        # fabric (add/ready/sample_batch/update_priorities/stats/
        # snapshots).  Processes spawn in train() at plane start, like
        # the fleet plane.  Config validation already rejected
        # device_replay here, so `ring` is None on this path.
        from r2d2_tpu.parallel.replay_shards import ShardedReplayPlane

        buffer = ShardedReplayPlane(
            cfg, action_dim, rng=np.random.default_rng(cfg.seed))
        replay_plane = buffer
    else:
        buffer = ReplayBuffer(cfg, action_dim,
                              rng=np.random.default_rng(cfg.seed),
                              device_ring=ring)
    buffer.env_steps = start_env_steps
    epsilons = [epsilon_ladder(i, cfg.num_actors, cfg.base_eps, cfg.eps_alpha)
                for i in range(cfg.num_actors)]
    members = None
    if cfg.population_spec:
        # population plane (league/population.py; Config validation
        # already pinned actor_transport="process" and one fleet per
        # member): member configs resolve here, the global epsilon list
        # becomes per-member ladder slices, and every member env is
        # probed for action-space parity — one Q-head serves the whole
        # population, so a member env with a different action set is a
        # config error, not a runtime shape crash
        from r2d2_tpu.league.population import (
            build_members,
            population_epsilons,
        )

        members = build_members(cfg)
        epsilons = population_epsilons(cfg, members)
        for m in members:
            if m.cfg.game_name == cfg.game_name:
                continue
            probe = env_factory(m.cfg, m.cfg.seed)
            member_dim = probe.action_space.n
            try:
                probe.close()
            except Exception:
                pass
            if member_dim != action_dim:
                raise ValueError(
                    f"population member {m.member_id} ({m.name}): env "
                    f"{m.cfg.game_name!r} has action_dim {member_dim} "
                    f"but the base env has {action_dim} — one Q-head "
                    "serves the whole population")
    plane = None
    if cfg.actor_transport == "process":
        # subprocess fleets (parallel/actor_procs): constructed here, but
        # processes only spawn in train() once the fabric is up
        from r2d2_tpu.parallel.actor_procs import ProcessFleetPlane

        plane = ProcessFleetPlane(cfg, action_dim, env_factory, epsilons,
                                  members=members)
        actors: List[VectorActor] = []
    else:
        act_fn = make_act_fn(cfg, net)
        # actor_fleets independent lockstep fleets over contiguous lane
        # slices (actor.fleet_shards — the split shared with the process
        # transport): the ladder epsilons stay GLOBAL (lane i keeps
        # epsilon_ladder(i, N) regardless of fleet count — the reference's
        # per-actor ladder, train.py:15-17), and each fleet gets its own
        # RNG stream and thread so one fleet's env stepping overlaps
        # another's batched inference
        shards, fleet_workers = fleet_shards(cfg)
        actors = [
            VectorActor(cfg, envs[lo:hi], epsilons[lo:hi], act_fn,
                        param_store, sink=buffer.add,
                        env_workers=fleet_workers,
                        rng=np.random.default_rng(
                            cfg.seed + 7919 + 104729 * f))
            for f, (lo, hi) in enumerate(shards)
        ]
    # full-state resume: a warm replay ring + resumable actor state saved
    # by a previous run's drain-then-save exit (checkpoint.save_replay).
    # Loaded AFTER everything is built so a failure here degrades to the
    # plain learner-state resume above instead of killing bring-up.
    restored_replay = False
    if checkpointer is not None and resume:
        rep = checkpointer.restore_replay()
        if rep is not None and ring is None:
            import warnings

            meta_r, ring_path, actor_snaps = rep
            try:
                buffer.read_state(ring_path, meta_r)
                restored_replay = True
            except (ValueError, OSError) as e:
                warnings.warn(f"replay snapshot not restored: {e}",
                              stacklevel=2)
            if restored_replay and actor_snaps:
                if plane is not None:
                    plane.set_restore_snapshots(actor_snaps)
                else:
                    for a, snap in zip(actors, actor_snaps):
                        if snap is None:
                            continue
                        try:
                            a.restore(snap)
                        except ValueError as e:
                            warnings.warn(f"actor snapshot skipped: {e}",
                                          stacklevel=2)
        elif rep is not None:
            import warnings

            warnings.warn(
                "a replay snapshot exists but this run uses device_replay "
                "— replay state lives in HBM and is not restored (resuming "
                "with a cold ring)", stacklevel=2)
    return dict(cfg=cfg, envs=envs, action_dim=action_dim, net=net,
                learner=learner, buffer=buffer, actors=actors,
                actor=actors[0] if actors else None, plane=plane,
                replay_plane=replay_plane, param_store=param_store,
                restored_replay=restored_replay,
                checkpointer=checkpointer, host_bs=host_bs, ring=ring)


def _device_memory_bytes():
    try:
        stats = jax.devices()[0].memory_stats()
        return int(stats["bytes_limit"]) if stats else None
    except Exception:
        return None


class _HostScaffold:
    """Host-side scaffolding shared by every trainer variant (the
    extraction ROADMAP item 2 flagged, done before a third variant
    appears).

    Owns the pieces ``train()`` and ``_train_anakin`` used to duplicate:
    the stop predicate (event + wall-clock deadline + supervisor failure),
    the SIGTERM/SIGINT drain-then-save handlers, the learner Heartbeat and
    its stall-watchdog loop, the bounded in-memory log ring, the telemetry
    plane (registry/JSONL/exporter) with the supervisor's give-up stamping
    wired in, and the quiesce/teardown order.  Trainer-specific policy —
    the /healthz verdict, the log-loop body, extra fabric loops, chaos
    wiring — stays in the trainer; the scaffold only runs what it is
    handed."""

    def __init__(self, cfg: Config, checkpoint_dir: Optional[str],
                 max_wall_seconds: Optional[float] = None,
                 max_thread_restarts: int = 3,
                 signal_msg: str = "draining fabric, then saving full state",
                 watch_label: str = "learner",
                 stop_fn: Optional[Callable[[], bool]] = None):
        self.cfg = cfg
        # optional caller-provided stop predicate (embedders, tests, the
        # sweep driver): polled alongside the event/deadline/supervisor
        # checks — a programmatic drain-then-save without a signal
        self._stop_fn = stop_fn
        self.checkpoint_dir = checkpoint_dir
        self.telemetry = Telemetry(cfg, checkpoint_dir)
        # learning-health plane (telemetry/learnhealth.py): the alert
        # engine owns the declarative rule set, the learnhealth.alert
        # counters, the durable alerts.jsonl stream and /alertz; the
        # monitor absorbs harvested losses + in-graph diag vectors on
        # the learner thread and trips a clean fabric stop on
        # non-finite numerics (stop() below polls it)
        from r2d2_tpu.telemetry.learnhealth import (
            AlertEngine,
            LearnHealthMonitor,
        )

        self.alerts = AlertEngine(
            cfg, self.telemetry.registry,
            log_dir=(os.path.join(checkpoint_dir, "telemetry")
                     if checkpoint_dir else None))
        self.learnhealth = LearnHealthMonitor(cfg, engine=self.alerts)
        # on-demand capture plane (telemetry/tracing.py), armed by
        # tracing_loops(); exporter_loops() then exposes its /tracez +
        # /profilez trigger routes next to /alertz
        self.trace_slab = None
        self.trace_ctl = None
        self.profile_ctl = None
        self.trace_routes: Dict[str, Any] = {"/alertz": self.alerts.route}
        # a thread exhausting its restart budget is stamped straight into
        # the registry by the supervisor itself — the log loop (the usual
        # absorption path) may be the very thread that died
        self.supervisor = Supervisor(
            max_restarts=max_thread_restarts,
            on_giveup=lambda name: self.telemetry.registry.inc(
                "supervisor.gaveup", thread=name))
        self.stop_event = threading.Event()
        self.deadline = (time.time() + max_wall_seconds
                         if max_wall_seconds else None)
        # learner liveness: the learner beats through every stop poll
        # (loop iterations AND queue waits), so a stale heartbeat means a
        # genuinely frozen thread — wedged collective, dead interconnect,
        # chaos freeze — not a slow batch
        self.heartbeat = Heartbeat()
        self.stall = {"stalled": False}
        # bounded ring (cfg.log_history_cap): the JSONL run log is the
        # durable record; this is the in-memory tail metrics["logs"]
        # returns
        self.logs: collections.deque = collections.deque(
            maxlen=cfg.log_history_cap)
        self._signal_msg = signal_msg
        self._watch_label = watch_label
        self._prev_handlers: Dict[int, Any] = {}

    def stop(self) -> bool:
        return (self.stop_event.is_set() or self.supervisor.any_failed
                or (self.deadline is not None
                    and time.time() > self.deadline)
                # non-finite loss/grads: stop cleanly (drain-then-save)
                # instead of training on through poisoned numerics —
                # the nonfinite alert already fired at trip time
                or self.learnhealth.tripped
                or (self._stop_fn is not None and self._stop_fn()))

    def record_learnhealth(self, entry: Dict[str, Any],
                           replay_health: Optional[Dict[str, Any]] = None
                           ) -> None:
        """The log loops' shared learnhealth step: stamp the monitor
        snapshot (+ replay data-health) into the entry, then run the
        alert engine over it; the entry carries the cumulative alert
        counts for /statusz, the JSONL record and r2d2_top."""
        entry["learnhealth"] = self.learnhealth.snapshot()
        if replay_health is not None:
            entry["replay_health"] = replay_health
        self.alerts.evaluate(dict(
            learnhealth=entry["learnhealth"], replay=replay_health,
            training_steps=entry.get("training_steps", 0)))
        entry["alerts"] = self.alerts.counts()

    def install_signals(self) -> None:
        """SIGTERM/SIGINT request a drain-then-save shutdown.  Signals
        only reach the main thread; a trainer driven from a worker thread
        (tests, sweep) skips the hook.  Handlers stay installed through
        the post-drain save — a second SIGTERM during the drain must keep
        requesting a clean stop, not kill the process mid-write — and
        :meth:`close` restores them on every exit path."""
        if threading.current_thread() is not threading.main_thread():
            return

        def _on_signal(signum, frame):
            log.warning("signal %d: %s", signum, self._signal_msg)
            self.stop_event.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):  # exotic embedding: no signals
                pass

    def _learner_watch(self) -> None:
        cfg = self.cfg
        poll = min(0.05, cfg.learner_stall_timeout / 4)
        while not self.stop():
            time.sleep(poll)
            if self.heartbeat.age() > cfg.learner_stall_timeout:
                self.stall["stalled"] = True
                log.error("%s heartbeat stale for %.1fs (budget %.1fs): "
                          "declaring a stall and stopping the fabric",
                          self._watch_label, self.heartbeat.age(),
                          cfg.learner_stall_timeout)
                self.stop_event.set()
                return

    def watch_loops(self) -> List[Any]:
        """The heartbeat stall-watchdog loop (empty when disabled)."""
        return ([("learner_watch", self._learner_watch)]
                if self.cfg.learner_stall_timeout > 0 else [])

    def _telemetry_dir(self) -> str:
        """Where trace/profile dumps land: next to the JSONL run log, or
        a one-shot temp dir for checkpoint-less runs."""
        if self.checkpoint_dir:
            return os.path.join(self.checkpoint_dir, "telemetry")
        if not hasattr(self, "_tmp_telemetry_dir"):
            import tempfile

            self._tmp_telemetry_dir = tempfile.mkdtemp(
                prefix="r2d2_telemetry_")
        return self._tmp_telemetry_dir

    def tracing_loops(self, num_slots: int,
                      step_fn: Callable[[], int]) -> List[Any]:
        """Build the run's cross-process trace slab (one event-ring slot
        per fabric process — trainer + fleets + replay shards), attach
        the process-wide recorder to slot 0, arm the capture controllers
        (``/tracez`` trace windows, ``/profilez`` device profiles,
        ``cfg.trace_steps`` boot-time capture), and return the
        supervised capture loop.  Call BEFORE :meth:`exporter_loops` so
        the trigger routes are registered on the exporter."""
        from r2d2_tpu.telemetry.tracing import (
            EVENTS,
            ProfileController,
            TraceController,
            TraceSlab,
        )

        cfg = self.cfg
        self.trace_slab = TraceSlab(num_slots, cfg.trace_buffer_events)
        EVENTS.attach(self.trace_slab.writer_info(0, 0, "trainer"))
        out_dir = self._telemetry_dir()
        self.trace_ctl = TraceController(self.trace_slab, step_fn, out_dir,
                                         tracer=EVENTS)
        self.profile_ctl = ProfileController(out_dir)

        def tracez(params: Dict[str, str]):
            if "steps" in params:
                res = self.trace_ctl.arm(int(params["steps"]))
                return (409 if "error" in res else 200), res
            return 200, self.trace_ctl.status()

        def profilez(params: Dict[str, str]):
            if "secs" in params:
                res = self.profile_ctl.arm(float(params["secs"]))
                return (409 if "error" in res else 200), res
            return 200, self.profile_ctl.status()

        self.trace_routes.update({"/tracez": tracez,
                                  "/profilez": profilez})
        if cfg.trace_steps > 0:
            self.trace_ctl.arm(cfg.trace_steps)

        def capture_loop():
            while not self.stop():
                self.trace_ctl.poll()
                self.profile_ctl.poll()
                EVENTS.flush()       # trainer ring publishes like any
                time.sleep(0.1)      # other writer's cadence
            # a window still open at shutdown (short run, stop mid-
            # capture) is force-closed so its dump is never lost
            self.trace_ctl.poll(force=True)

        return [("capture", capture_loop)]

    def exporter_loops(self, healthz: Callable[[], Dict[str, Any]]
                       ) -> List[Any]:
        """Arm the HTTP exporter around the trainer's healthz verdict.
        The loop is close-driven, NOT stop-driven: a stalled/stopping run
        must stay scrapeable (that is when /healthz matters most); quiesce
        closes the exporter before joining it."""
        exporter = self.telemetry.serve(healthz, routes=self.trace_routes)
        if exporter is None:    # telemetry_port == 0
            return []

        def telemetry_loop():
            while not exporter.closed:
                try:
                    exporter.handle_once()
                except (OSError, ValueError):
                    return        # server closed under a late poll

        return [("telemetry", telemetry_loop)]

    def start(self, loops) -> None:
        for name, loop in loops:
            self.supervisor.start(name, loop)

    def quiesce(self) -> None:
        """Stop, then close the exporter BEFORE join_all — the telemetry
        loop exits on close, and a joined-but-serving exporter would stall
        the teardown — then reap the fabric threads."""
        self.stop_event.set()
        self.telemetry.close_exporter()
        self.supervisor.join_all(timeout=5.0)

    def close(self) -> None:
        self.alerts.close()
        self.telemetry.close()
        if self.trace_slab is not None:
            # after the planes' shutdown (train's finally order): every
            # subprocess writer is gone, so the unlink is safe
            from r2d2_tpu.telemetry.tracing import EVENTS

            EVENTS.detach()
            self.trace_slab.close()
        for sig, handler in self._prev_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass


# --------------------------------------------------------------------------
# deterministic single-thread trainer (integration-test / debug path)
# --------------------------------------------------------------------------

def train_sync(cfg: Config, env_factory: EnvFactory = _default_env_factory,
               checkpoint_dir: Optional[str] = None, resume: bool = False,
               actor_steps_per_update: int = 4,
               use_mesh: bool = False) -> Dict[str, Any]:
    """Deterministic interleaving: fill the buffer to ``learning_starts``,
    then alternate ``actor_steps_per_update`` lockstep actor iterations
    with one learner update, applying priority feedback inline.

    Returns metrics incl. the per-update loss curve and episode returns.
    """
    # prefetch would run batch_source (which steps the actor) on a thread,
    # and env workers / multiple fleets would make block arrival order racy
    # — all break the deterministic interleaving this function promises;
    # device_replay's k-step dispatch granularity likewise, and a nonzero
    # result pipeline would defer priority feedback (this path applies it
    # after every single update)
    cfg = cfg.replace(prefetch_batches=0, env_workers=0, actor_fleets=1,
                      device_replay=False, in_graph_per=False,
                      superstep_pipeline=0, actor_transport="thread",
                      actor_inference="local", replay_shards=1,
                      # population members are process fleets and the
                      # eval sidecar is a fabric subprocess — neither
                      # exists in the deterministic single-thread path
                      population_spec="", league_eval=False,
                      # no monitor/alert engine exists here either:
                      # armed diagnostics would pay the in-graph ΔQ
                      # re-unroll only to be discarded at harvest
                      learnhealth_interval=0)
    sys = _build(cfg, env_factory, use_mesh, checkpoint_dir, resume)
    cfg = sys["cfg"]
    actor: VectorActor = sys["actor"]
    buffer: ReplayBuffer = sys["buffer"]
    learner: Learner = sys["learner"]

    while not buffer.ready:
        actor.run(max_steps=cfg.block_length)

    losses: List[float] = []
    episode_returns: List[float] = []

    def batch_source():
        actor.run(max_steps=actor_steps_per_update)
        return buffer.sample_batch(sys["host_bs"])

    def priority_sink(idxes, priorities, old_ptr, loss):
        buffer.update_priorities(idxes, priorities, old_ptr, loss)
        losses.append(loss)
        s = buffer.stats()
        if s["num_episodes"]:
            episode_returns.append(s["episode_reward"] / s["num_episodes"])

    metrics = learner.run(batch_source, priority_sink)
    metrics.update(losses=losses, episode_returns=episode_returns,
                   buffer_size=len(buffer),
                   final_params=learner.state.params)
    return metrics


# --------------------------------------------------------------------------
# anakin trainer: ONE compiled on-device program (learner/anakin.py)
# --------------------------------------------------------------------------

def _train_anakin(cfg: Config, checkpoint_dir: Optional[str] = None,
                  resume: bool = False, use_mesh: bool = False,
                  max_wall_seconds: Optional[float] = None,
                  verbose: bool = True,
                  log_sink: Optional[Callable[[Dict[str, Any]], None]] = None,
                  tracer: Optional[Tracer] = None,
                  profile_dir: Optional[str] = None,
                  stop_fn: Optional[Callable[[], bool]] = None
                  ) -> Dict[str, Any]:
    """``actor_transport="anakin"``: the whole training loop — pure-JAX
    batched env, in-graph actor, in-graph replay writes, train steps —
    is one jitted program (the Podracer "Anakin" architecture,
    learner/anakin.py).  The host dispatches it and reads a (k + 5)-float
    result vector back; there are no actor/sample/priority threads at all
    (the transport is single-process by construction).

    What carries over from the threaded fabric: the telemetry plane
    (registry + JSONL run log + HTTP exporter + the shared console line),
    SIGTERM/SIGINT drain-then-save with full-state resume (the snapshot
    holds the ENTIRE on-device loop state: ring, PER leaves, env
    phase/RNGs, agent LSTM carry, local buffers — ``--resume`` continues
    bit-exact), the learner heartbeat watchdog, and checkpoint cadences.
    Chaos: the fleet/shm fault sites don't exist in this mode, but the
    ``wedge_dispatch`` site does — it stalls one fused-dispatch harvest,
    and ``cfg.dispatch_deadline`` (> 0) turns a dispatch that blows its
    budget into a snapshot-then-clean-abort
    (``metrics["dispatch_wedged"]``) instead of training on through a
    flaky device.  Not supported in this mode (documented in
    docs/OPERATIONS.md): meshes (single-device v1) and custom env
    factories (the env must be jittable; v1 ships the fake env — any
    future jittable env plugs in at ``envs/anakin.AnakinFakeEnv``'s
    four-method surface).
    """
    from r2d2_tpu.learner.anakin import AnakinPlane, run_anakin_loop
    from r2d2_tpu.replay.device_ring import DeviceRing, resolve_layout

    if cfg.game_name != "Fake":
        import warnings

        warnings.warn(
            f"anakin transport needs a jittable env; substituting the "
            f"pure-JAX {cfg.anakin_env!r} env for {cfg.game_name!r} "
            "(cfg.anakin_env selects it)", stacklevel=2)
    # the fused program IS device replay with in-graph PER — flip the
    # flags so the ring/PER state and the train-step composition build
    # exactly as the in_graph_per drivetrain's (effective-config pattern)
    cfg = cfg.replace(device_replay=True, in_graph_per=True)
    action_dim = 4  # both anakin envs' action set (envs/anakin.py)
    net = create_network(cfg, action_dim)
    params = init_params(cfg, net, jax.random.PRNGKey(cfg.seed))
    state = create_train_state(cfg, params)
    checkpointer = (Checkpointer(checkpoint_dir, keep=cfg.keep_checkpoints)
                    if checkpoint_dir else None)
    start_env_steps, start_minutes = 0, 0.0
    if (checkpointer is not None and resume
            and checkpointer.latest_step() is not None):
        from r2d2_tpu.checkpoint import check_arch_compat

        check_arch_compat(cfg, checkpointer.peek_meta())
        state, meta = checkpointer.restore(jax.device_get(state))
        start_env_steps = int(meta.get("env_steps", 0))
        start_minutes = float(meta.get("minutes", 0.0))

    # multi-chip anakin (ROADMAP item 2): under --mesh the fused program
    # compiles through the ONE table-driven sharded entry point — lanes,
    # carry and local buffers over dp, params/moments per the table,
    # ring/PER per the resolved ring layout (the Podracer
    # replicate-the-fused-program scale-out).  Without --mesh the
    # single-device path is unchanged.
    mesh = make_mesh(cfg) if use_mesh else None
    table = None
    if mesh is not None:
        from r2d2_tpu.parallel.sharding import ShardingTable
        from r2d2_tpu.replay.replay_buffer import data_bytes

        table = ShardingTable(mesh, cfg)
        layout = resolve_layout(cfg, mesh, data_bytes(cfg, action_dim),
                                _device_memory_bytes())
        ring = DeviceRing(cfg, action_dim, table=table, layout=layout)
    else:
        ring = DeviceRing(cfg, action_dim)
    # no ParamStore: the fused loop acts on the CURRENT params in-graph
    # and nothing else consumes published snapshots in this mode (no
    # fleets, pump, or inference service) — publishing would just run a
    # jitted whole-tree param copy per cadence for no reader
    learner = Learner(cfg, net, state, mesh=mesh, table=table,
                      checkpointer=checkpointer,
                      start_env_steps=start_env_steps,
                      start_minutes=start_minutes)
    plane = AnakinPlane(cfg, net, action_dim, ring,
                        start_env_steps=start_env_steps, table=table,
                        state_template=learner.state)

    restored_anakin = False
    if checkpointer is not None and resume:
        rep = checkpointer.restore_replay()
        if rep is not None:
            import warnings

            meta_r, ring_path, _ = rep
            if meta_r.get("kind") == "anakin":
                try:
                    plane.read_state(ring_path, meta_r)
                    restored_anakin = True
                except (ValueError, OSError) as e:
                    warnings.warn(f"anakin snapshot not restored: {e}",
                                  stacklevel=2)
            else:
                warnings.warn(
                    "a replay snapshot exists but it is not an anakin "
                    "loop snapshot (different transport) — resuming with "
                    "a cold ring", stacklevel=2)

    tracer = tracer or Tracer()
    scaffold = _HostScaffold(
        cfg, checkpoint_dir, max_wall_seconds=max_wall_seconds,
        signal_msg="draining the anakin loop, then saving full "
                   "on-device state",
        watch_label="anakin loop", stop_fn=stop_fn)
    telemetry, supervisor = scaffold.telemetry, scaffold.supervisor
    heartbeat, stall, logs = (scaffold.heartbeat, scaffold.stall,
                              scaffold.logs)
    stop_event, stop = scaffold.stop_event, scaffold.stop
    # learnhealth: the plane's harvest absorbs losses + the in-graph
    # diag rows riding the fused program's flat result vector
    plane.monitor = scaffold.learnhealth
    chaos = None
    if cfg.chaos_spec:
        from r2d2_tpu.utils.chaos import ChaosInjector

        # only the wedge_dispatch site exists in this transport; other
        # armed kinds simply never reach an opportunity
        chaos = ChaosInjector(cfg.chaos_spec, seed=cfg.seed)
        if checkpointer is not None:
            checkpointer.chaos = chaos
    scaffold.install_signals()

    def learner_stop() -> bool:
        heartbeat.beat()
        return stop()

    def healthz() -> Dict[str, Any]:
        age = heartbeat.age()
        stale = (cfg.learner_stall_timeout > 0
                 and age > cfg.learner_stall_timeout)
        ok = not (supervisor.any_failed or stall["stalled"] or stale)
        # the nonfinite alert rule is the ONE learnhealth signal that
        # degrades /healthz: the checkpoint stream is numerically
        # suspect and an operator must look (docs/OBSERVABILITY.md)
        degraded = ok and scaffold.alerts.nonfinite_active
        return dict(ok=ok,
                    degraded=degraded,
                    status=("failing" if not ok
                            else "degraded" if degraded else "ok"),
                    learner_heartbeat_age=age,
                    learner_stalled=stall["stalled"] or stale,
                    threads=supervisor.health())

    def log_loop():
        last_steps, last_frames, last_time = 0, 0, time.time()
        while not stop():
            time.sleep(min(cfg.log_interval, 0.5))
            now = time.time()
            if now - last_time < cfg.log_interval:
                continue
            s = plane.stats()
            dt = now - last_time
            entry = dict(
                time=now, buffer_size=s["size"], env_steps=s["env_steps"],
                training_steps=s["training_steps"],
                updates_per_sec=(s["training_steps"] - last_steps) / dt,
                mean_episode_return=(s["episode_reward"] / s["num_episodes"]
                                     if s["num_episodes"] else float("nan")),
                mean_loss=(s["sum_loss"]
                           / max(1, s["training_steps"] - last_steps)),
                interval_episodes=s["num_episodes"],
                trace=tracer.snapshot(),
                health=supervisor.health(),
                learner_heartbeat_age=heartbeat.age(),
                telemetry_port=telemetry.port,
                anakin=dict(super_steps=s["super_steps"],
                            frames=s["frames"],
                            frames_per_sec=(s["frames"] - last_frames) / dt,
                            blocks=s["blocks"],
                            episodes_total=s["episodes_total"],
                            # in-graph greedy eval lane
                            # (cfg.anakin_eval_interval): the learning
                            # curve without a host env
                            eval_episodes=s["eval_episodes"],
                            eval_return=s["eval_return"]),
            )
            # learnhealth + alerts: the anakin PER leaves live in-graph
            # (no host tree to walk), so no replay data-health here —
            # the in-graph diag bundle covers the learner side
            scaffold.record_learnhealth(entry)
            logs.append(entry)
            telemetry.record(entry)
            if log_sink is not None:
                log_sink(entry)
            if verbose:
                print(format_entry(entry), flush=True)
            last_steps, last_frames, last_time = (
                s["training_steps"], s["frames"], now)

    want_full_save = checkpointer is not None and cfg.replay_snapshot

    def save_anakin_snapshot(step: int) -> None:
        """Persist the ENTIRE on-device loop state (ring + PER + env/agent
        carry + counters) through the atomic replay-snapshot machinery —
        what ``--resume`` restores via ``plane.read_state``."""
        try:
            checkpointer.save_replay(step, plane.write_state)
        except Exception as e:  # never fail the run over snapshot I/O
            log.warning("anakin full-state snapshot failed: %s", e)

    # tracing: the fused loop is one process, so the capture plane is a
    # single-slot slab — trainer-track spans (dispatch/result-sync) and
    # the /tracez + /profilez triggers work unchanged; block lineage
    # does not exist here (blocks never leave the device)
    loops = ([("log", log_loop)] + scaffold.watch_loops()
             + scaffold.tracing_loops(1, lambda: plane.training_steps)
             + scaffold.exporter_loops(healthz))

    try:
        try:
            scaffold.start(loops)
            with device_profile(profile_dir):
                metrics = run_anakin_loop(
                    learner, plane, stop=learner_stop, tracer=tracer,
                    snapshot_fn=(save_anakin_snapshot if want_full_save
                                 else None), chaos=chaos)
        finally:
            # final health verdict BEFORE quiesce (same rule as the
            # threaded trainer): post-quiesce the heartbeat stops
            # beating and the epilogue snapshot below can outlast the
            # stall budget — a clean run must not misread as failing
            try:
                final_health = healthz()
            except Exception:
                final_health = {}
            scaffold.quiesce()

        # drain-then-save epilogue: the learner state was saved by
        # run_anakin_loop's final _save; persist the on-device loop state
        # next to it so --resume continues warm (ring, RNGs, env phase,
        # LSTM carry — no cold restart).  A wedged abort already parked
        # its snapshot inside the loop (bounded, on a hard wedge) —
        # re-saving here would read the same wedged device UNBOUNDED on
        # the main thread, trading the clean abort back for a hang
        if want_full_save and not metrics.get("dispatch_wedged"):
            save_anakin_snapshot(learner.num_updates)

        metrics.update(buffer_size=plane.fill, logs=list(logs),
                       buffer_training_steps=plane.training_steps,
                       final_params=learner.state.params,
                       restored_replay=restored_anakin,
                       learner_stalled=stall["stalled"],
                       trace=tracer.snapshot(), health=supervisor.health(),
                       telemetry_port=telemetry.port,
                       fabric_failed=supervisor.any_failed,
                       learnhealth=scaffold.learnhealth.snapshot(),
                       alerts=scaffold.alerts.counts(),
                       healthz=final_health)
        if chaos is not None:
            metrics["chaos"] = chaos.counts()
        return metrics
    finally:
        scaffold.close()


# --------------------------------------------------------------------------
# threaded fabric trainer (the reference's process topology, thread-native)
# --------------------------------------------------------------------------

def train(cfg: Config, env_factory: EnvFactory = _default_env_factory,
          checkpoint_dir: Optional[str] = None, resume: bool = False,
          use_mesh: bool = False, max_wall_seconds: Optional[float] = None,
          verbose: bool = True,
          log_sink: Optional[Callable[[Dict[str, Any]], None]] = None,
          tracer: Optional[Tracer] = None,
          profile_dir: Optional[str] = None,
          max_thread_restarts: int = 3,
          stop_fn: Optional[Callable[[], bool]] = None) -> Dict[str, Any]:
    """The full concurrent system (reference train.py:20-44 equivalent).

    Threads and their reference analogues:
      actor[0..F]  — the N actor processes (worker.py:516-561), regrouped
                     into ``cfg.actor_fleets`` lockstep fleet threads with
                     batched inference (one fleet's env stepping overlaps
                     another's inference on multi-core hosts)
      sample       — ReplayBuffer.prepare_data (worker.py:113-122)
      priority     — ReplayBuffer.update_data (worker.py:131-138)
      log          — the buffer process's stats loop (worker.py:89-106)
      prefetch     — Learner.prepare_data (worker.py:309-316), inside
                     Learner.run
      main thread  — the learner hot loop (worker.py:318-381)

    Block ingest (add_data, worker.py:124-129) needs no thread: the actor
    sink calls ``buffer.add`` directly — same-process, lock-protected.

    Beyond the reference: fabric threads run under a Supervisor (crashes
    recorded and restarted up to ``max_thread_restarts``; an exhausted
    budget stops the run instead of hanging — SURVEY §5.3), a Tracer
    records per-stage timings and queue-depth gauges (SURVEY §5.1), and
    ``profile_dir`` captures a ``jax.profiler`` device trace of the run.

    Preemption-safe: SIGTERM/SIGINT trigger a drain-then-save shutdown —
    the learner checkpoints its final state and (``cfg.replay_snapshot``,
    host-ring runs) the replay ring, sum-tree, counters and actor RNG/env
    state are snapshotted atomically so ``resume=True`` restarts warm
    (``cfg.replay_snapshot_interval`` adds periodic mid-run snapshots
    against kill -9).  ``cfg.learner_stall_timeout`` arms a heartbeat
    watchdog that stops the fabric when the learner thread freezes, and
    ``cfg.chaos_spec`` (utils/chaos.py) injects deterministic faults for
    recovery drills.

    Telemetry (r2d2_tpu/telemetry, docs/OBSERVABILITY.md): every log
    interval the stats entry is absorbed into a shared
    :class:`~r2d2_tpu.telemetry.registry.MetricsRegistry` (spans, guard
    counters, replay stats, chaos fires, supervisor/fleet health — the
    process-fleet plane additionally merges actor-side counters
    published through a shared-memory stats slab) and appended to the
    persistent JSONL run log under ``<checkpoint_dir>/telemetry/``
    (append-on-resume: a SIGTERM→resume soak yields one continuous
    curve).  ``cfg.telemetry_port`` arms an HTTP exporter serving
    ``/metrics`` (Prometheus text), ``/healthz`` and ``/statusz`` as a
    supervised fabric thread.  The in-memory ``metrics["logs"]`` list is
    a ``cfg.log_history_cap`` ring — the JSONL file is the durable
    record.
    """
    if cfg.actor_transport == "anakin":
        # the Podracer fused on-device loop (learner/anakin.py): env,
        # actor, replay and learner are ONE jitted program — none of the
        # thread/process fabric below applies
        if env_factory is not _default_env_factory:
            # hard error, not a warning: with two jittable envs behind
            # cfg.anakin_env a custom factory here is a config mistake a
            # silent fallback would hide — host env factories cannot run
            # inside the fused program
            raise ValueError(
                "anakin transport cannot run a host env_factory — the "
                "env must be jnp ops.  Select a jittable env with "
                "cfg.anakin_env ('fake' or 'grid'), or implement the "
                "envs/anakin.py four-method surface "
                "(init_state/observe/step/reset_lanes + STATE_KEYS) and "
                "register it in make_anakin_env")
        if cfg.league_eval:
            import warnings

            warnings.warn(
                "league_eval is not wired into the anakin transport "
                "(the fused loop has its own on-device eval-lane "
                "follow-on, ROADMAP item 2) — running without the eval "
                "sidecar", stacklevel=2)
        return _train_anakin(cfg, checkpoint_dir=checkpoint_dir,
                             resume=resume, use_mesh=use_mesh,
                             max_wall_seconds=max_wall_seconds,
                             verbose=verbose, log_sink=log_sink,
                             tracer=tracer, profile_dir=profile_dir,
                             stop_fn=stop_fn)
    sys = _build(cfg, env_factory, use_mesh, checkpoint_dir, resume)
    cfg = sys["cfg"]  # the EFFECTIVE config (degrade paths flip flags)
    actors: List[VectorActor] = sys["actors"]
    buffer: ReplayBuffer = sys["buffer"]
    learner: Learner = sys["learner"]
    checkpointer = sys["checkpointer"]
    plane = sys["plane"]
    replay_plane = sys["replay_plane"]
    tracer = tracer or Tracer()
    scaffold = _HostScaffold(cfg, checkpoint_dir,
                             max_wall_seconds=max_wall_seconds,
                             max_thread_restarts=max_thread_restarts,
                             stop_fn=stop_fn)
    telemetry, supervisor = scaffold.telemetry, scaffold.supervisor
    heartbeat, stall, logs = (scaffold.heartbeat, scaffold.stall,
                              scaffold.logs)
    stop_event, stop = scaffold.stop_event, scaffold.stop
    # learnhealth: the learner's harvests absorb losses + the in-graph
    # diag vectors (cfg.learnhealth_interval); a non-finite observation
    # fires the nonfinite alert and trips scaffold.stop
    learner.monitor = scaffold.learnhealth

    chaos = None
    if cfg.chaos_spec:
        from r2d2_tpu.utils.chaos import ChaosInjector

        chaos = ChaosInjector(cfg.chaos_spec, seed=cfg.seed)
        if checkpointer is not None:
            checkpointer.chaos = chaos
    # cross-process tracing (telemetry/tracing.py): one event-ring slot
    # per fabric process — trainer (slot 0) + fleets + replay shards —
    # armed fabric-wide by /tracez, --trace-steps, or chaos_soak's
    # --trace round.  Built before the planes spawn so every worker
    # attaches at birth.
    num_trace_slots = (1 + (plane.num_fleets if plane is not None else 0)
                       + (replay_plane.K if replay_plane is not None
                          else 0))
    tracing_loops = scaffold.tracing_loops(
        num_trace_slots, lambda: buffer.training_steps)
    if plane is not None:
        plane.trace_slab = scaffold.trace_slab
        plane.trace_slot_base = 1
    if replay_plane is not None:
        replay_plane.trace_slab = scaffold.trace_slab
        replay_plane.trace_slot_base = 1 + (plane.num_fleets
                                            if plane is not None else 0)

    if plane is not None:
        # CRC-failed blocks dropped at ingest surface in buffer.stats()
        plane.on_corrupt = buffer.note_corrupt_block
        # the plane's counters (respawns, ingest histogram, serve shard
        # resets, slab-merged actor stats) land in the run's namespace
        plane.set_registry(telemetry.registry)
        # fault sites owned by the plane's own loops (freeze_service /
        # stall_pump) and the service's scatter (drop/garble response)
        plane.chaos = chaos
        if plane.service is not None:
            # serve loop spans (assemble/act/scatter) + batch-size gauge
            # land in the same tracer snapshot as every other stage
            plane.service.tracer = tracer
            plane.service.chaos = chaos

    # preemption hook: SIGTERM/SIGINT request a drain-then-save shutdown —
    # the learner exits at its next stop poll, the fabric quiesces, and
    # the epilogue below writes the full-state snapshot (learner state via
    # Learner.run's own final save; replay ring + actor state via
    # checkpointer.save_replay)
    scaffold.install_signals()

    # full-state snapshots need the host ring (device_replay state lives
    # in HBM) and a single process (per-host snapshot dirs would collide)
    want_full_save = (checkpointer is not None and cfg.replay_snapshot
                      and sys["ring"] is None and jax.process_count() == 1)

    if replay_plane is not None:
        # shard counters land in the run's namespace (replay.shard.*);
        # the Checkpointer lets the watchdog restore a respawned shard's
        # slots from the latest committed replay snapshot; the chaos
        # injector arms the garble_sample_response receipt-side site
        replay_plane.set_registry(telemetry.registry)
        if want_full_save:
            replay_plane.checkpointer = checkpointer
        replay_plane.chaos = chaos

    # standing evaluation sidecar (league/eval_service.py): follows this
    # run's checkpoints from a supervised subprocess, scores every
    # population member on its held-out suite, publishes league.jsonl +
    # the /statusz league table.  Its death only ever DEGRADES /healthz
    # — the watchdog loop respawns it (cursor resumed from league.jsonl)
    # and an exhausted budget stops evaluation, never training.
    sidecar = None
    if cfg.league_eval:
        if checkpoint_dir is None:
            log.warning("league_eval requested without a checkpoint_dir "
                        "— the eval sidecar follows checkpoints; "
                        "running without it")
        else:
            from r2d2_tpu.league.eval_service import EvalSidecar

            sidecar = EvalSidecar(cfg, checkpoint_dir, sys["action_dim"],
                                  registry=telemetry.registry)

    def learner_stop() -> bool:
        if chaos is not None:
            freeze = chaos.learner_freeze_seconds()
            if freeze > 0:
                time.sleep(freeze)
            if chaos.poison_params_now():
                # learnhealth NaN-sentry drill: runs ON the learner
                # thread (this predicate is only polled there), so the
                # state handle cannot race an in-flight donation
                log.warning("chaos: poisoning learner params with NaN")
                learner.poison_params()
        heartbeat.beat()
        return stop()

    batch_queue: "queue.Queue" = queue.Queue(maxsize=8)
    priority_queue: "queue.Queue" = queue.Queue(maxsize=8)
    # sample→feedback latency pairing: batches and their priority
    # feedback move through FIFO queues in order, so a deque of enqueue
    # stamps pairs each feedback with its batch without widening the
    # priority-sink signature (bounded: a drained stop drops stragglers)
    sample_ts: collections.deque = collections.deque(maxlen=64)

    def make_actor_loop(a: VectorActor):
        def actor_loop():
            while not stop():
                with tracer.span("actor.run256"):
                    a.run(max_steps=256, stop=stop)
        return actor_loop

    def sample_loop():
        registry = telemetry.registry
        while not stop():
            if not buffer.ready:
                time.sleep(0.05)
                continue
            with tracer.span("buffer.sample_batch"):
                if replay_plane is not None:
                    # the scatter/gather sample RPC; None = every shard
                    # suspect/empty this draw (all RPC deadlines are
                    # bounded) — retry, the watchdog respawns the dead
                    batch = buffer.sample_batch(sys["host_bs"], stop=stop)
                    if batch is None:
                        continue
                else:
                    batch = buffer.sample_batch(sys["host_bs"])
            # block-lineage latency decomposition (docs/OBSERVABILITY.md):
            # per-row ages stamped where the data lives (the K=1 ring or
            # the shard process), observed here where the registry lives.
            # Measured at batch assembly — the learner consumes within
            # the bounded staging window (queue 8 + prefetch), which is
            # the train-time envelope the histogram name promises.
            ages = batch.pop("ages", None)
            if ages is not None:
                ages = np.asarray(ages)
                cut, add = ages[:, 0], ages[:, 1]
                registry.observe_many("pipeline.block_age_at_train_s",
                                      cut[cut >= 0])
                registry.observe_many("pipeline.hop.ingest_to_sample_s",
                                      add[add >= 0])
            while not stop():
                try:
                    batch_queue.put(batch, timeout=0.1)
                    sample_ts.append(time.perf_counter())
                    break
                except queue.Full:
                    continue

    def priority_loop():
        registry = telemetry.registry
        while not stop():
            try:
                idxes, priorities, old_ptr, loss = priority_queue.get(
                    timeout=0.1)
            except queue.Empty:
                continue
            if sample_ts:
                # FIFO pairing with the batch this feedback came from
                try:
                    registry.observe(
                        "pipeline.hop.sample_to_feedback_s",
                        time.perf_counter() - sample_ts.popleft())
                except IndexError:
                    pass   # raced the deque's bound — skip the sample
            with tracer.span("buffer.update_priorities"):
                buffer.update_priorities(idxes, priorities, old_ptr, loss)

    def healthz() -> Dict[str, Any]:
        """The /healthz verdict — three states (docs/OBSERVABILITY.md):
        ``ok`` (everything green), ``degraded`` (still serving HTTP 200,
        but a plane is running on its fallback path — an open act
        circuit, params stale past the budget), and ``failing`` (HTTP
        503: supervisor giveup, failed fleet plane, heartbeat past its
        stall budget).  The exporter keeps answering while the learner
        is merely frozen, so an external prober sees the stall the
        moment it exceeds the budget — before the watchdog has
        necessarily fired."""
        age = heartbeat.age()
        stale = (cfg.learner_stall_timeout > 0
                 and age > cfg.learner_stall_timeout)
        out = dict(
            ok=not (supervisor.any_failed or stall["stalled"] or stale
                    or (plane is not None and plane.failed)
                    or (replay_plane is not None and replay_plane.failed)),
            learner_heartbeat_age=age,
            learner_stalled=stall["stalled"] or stale,
            threads=supervisor.health(),
        )
        degraded = False
        if plane is not None:
            h = plane.health()
            out["fleet"] = dict(fleets=h["fleets"], alive=h["alive"],
                                restarts=h["restarts"], failed=h["failed"],
                                resilience=h["resilience"])
            degraded = bool(h["resilience"].get("degraded"))
        if replay_plane is not None:
            rh = replay_plane.health()
            out["replay_shards"] = dict(shards=rh["shards"],
                                        alive=rh["alive"],
                                        respawns=rh["respawns"],
                                        failed=rh["failed"])
            if "net" in rh:
                # socket transport: surface the per-link verdicts —
                # connection, circuit state, reconnects, epoch drops —
                # so a prober sees WHICH link is partitioned
                out["replay_shards"]["net"] = dict(
                    connected=rh["net"]["connected"],
                    reconnects=rh["net"]["reconnects"],
                    epoch_drops=rh["net"]["epoch_drops"],
                    circuits=[row["circuit"]
                              for row in rh["net"]["links"]])
            # a dead/partitioned shard mid-heal: the plane keeps serving
            # from the survivors (redistributed strata) — degraded, not
            # failing
            degraded = degraded or bool(rh["degraded"])
        if sidecar is not None:
            lh = sidecar.health()
            out["league"] = lh
            # a dead/failed evaluator blinds the run to policy quality
            # but touches nothing on the training path: degraded, never
            # failing — an orchestrator must not evict a training run
            # because its scoreboard died
            degraded = degraded or bool(lh["degraded"])
        # learnhealth: the nonfinite alert rule (and only it) degrades
        # the verdict — the checkpoint stream is numerically suspect
        degraded = degraded or scaffold.alerts.nonfinite_active
        out["degraded"] = degraded and out["ok"]
        out["status"] = ("failing" if not out["ok"]
                         else "degraded" if degraded else "ok")
        return out

    def log_loop():
        last_steps, last_time = 0, time.time()
        while not stop():
            time.sleep(min(cfg.log_interval, 0.5))
            now = time.time()
            if now - last_time < cfg.log_interval:
                continue
            s = buffer.stats()
            dt = now - last_time
            tracer.gauge("batch_queue_depth", batch_queue.qsize())
            tracer.gauge("priority_queue_depth", priority_queue.qsize())
            tracer.gauge("buffer_fill", s["size"])
            entry = dict(
                time=now, buffer_size=s["size"], env_steps=s["env_steps"],
                training_steps=s["training_steps"],
                updates_per_sec=(s["training_steps"] - last_steps) / dt,
                mean_episode_return=(s["episode_reward"] / s["num_episodes"]
                                     if s["num_episodes"] else float("nan")),
                mean_loss=(s["sum_loss"] / max(1, s["training_steps"] - last_steps)),
                interval_episodes=s["num_episodes"],
                trace=tracer.snapshot(),
                health=supervisor.health(),
                learner_heartbeat_age=heartbeat.age(),
                telemetry_port=telemetry.port,
            )
            if chaos is not None:
                entry["chaos"] = chaos.counts()
            if plane is not None:
                entry["fleet"] = plane.health()
            if replay_plane is not None:
                entry["replay_shards"] = replay_plane.health()
            if sidecar is not None:
                # the league standings ride the entry → /statusz
                # last_entry + the JSONL run log + the league.* registry
                # absorption (telemetry/plane.py)
                entry["league"] = sidecar.status()
            # shard-health drive-bys ride the base stats schema (zeros on
            # the in-process path) so r2d2_top renders one line format
            entry["corrupt_blocks"] = s["corrupt_blocks"]
            entry["shard_respawns"] = s.get("shard_respawns", 0)
            # learnhealth: monitor snapshot + replay data-health (ESS /
            # priority histogram / replay ratio / member fractions),
            # then the alert engine's interval evaluation
            try:
                replay_health = buffer.data_health()
            except Exception:   # telemetry must never kill the log loop
                replay_health = None
            scaffold.record_learnhealth(entry, replay_health)
            logs.append(entry)
            # registry absorption + the persistent JSONL record
            telemetry.record(entry)
            if log_sink is not None:
                log_sink(entry)
            if verbose:
                print(format_entry(entry), flush=True)
            last_steps, last_time = s["training_steps"], now

    def chaos_loop():
        # process-plane fault sites (fleet kill, slab garbling, replay
        # shard kill/stall, eval-sidecar kill); learner freeze fires from
        # learner_stop, checkpoint truncation from the Checkpointer
        # itself, sample-response garbling from the replay plane's
        # receipt path
        while not stop():
            time.sleep(0.05)
            if plane is not None:
                chaos.maybe_kill_fleet(plane)
                chaos.maybe_garble_block(plane)
            if replay_plane is not None:
                chaos.maybe_kill_replay_shard(replay_plane)
                chaos.maybe_stall_shard(replay_plane)
            if sidecar is not None:
                chaos.maybe_kill_eval_sidecar(sidecar)

    def snapshot_loop():
        # periodic insurance against kill -9 (no drain possible): the
        # buffer snapshot is lock-consistent; thread-transport actor state
        # is only captured by the quiesced shutdown save
        last = time.time()
        while not stop():
            time.sleep(0.2)
            if time.time() - last < cfg.replay_snapshot_interval:
                continue
            try:
                sys["checkpointer"].save_replay(buffer.training_steps,
                                                buffer.write_state)
            except Exception as e:
                # a snapshot is insurance, not the run: a replay shard
                # dying mid-fan-out (chaos kill) fails THIS save — warn
                # and retry next cadence instead of burning the loop's
                # supervisor restart budget (the shutdown save is
                # equally tolerant)
                log.warning("periodic replay snapshot failed: %s", e)
            last = time.time()

    loops = [(f"actor{f}" if len(actors) > 1 else "actor",
              make_actor_loop(a)) for f, a in enumerate(actors)]
    loops += scaffold.watch_loops()
    if chaos is not None and (
            (plane is not None and (chaos.enabled("kill_fleet")
                                    or chaos.enabled("garble_block")))
            or (replay_plane is not None
                and (chaos.enabled("kill_replay_shard")
                     or chaos.enabled("stall_shard")))
            or (sidecar is not None
                and chaos.enabled("kill_eval_sidecar"))):
        loops.append(("chaos", chaos_loop))
    if want_full_save and cfg.replay_snapshot_interval > 0:
        loops.append(("snapshot", snapshot_loop))
    if plane is not None:
        # process transport: fleets are subprocesses; their trainer-side
        # plumbing (block ingest, weight pump, process watchdog) runs as
        # supervised fabric threads just like the actor threads would
        loops += plane.make_loops(stop, buffer.add)
    if sidecar is not None:
        # the eval sidecar's watchdog (respawn-with-cursor-resume): its
        # budget exhausting degrades health, never the fabric
        loops += sidecar.make_loops(stop)
    if replay_plane is not None:
        # sharded replay: the shard-process watchdog (respawn + restore)
        loops += replay_plane.make_loops(stop)
    loops += [("sample", sample_loop), ("priority", priority_loop),
              ("log", log_loop)]
    loops += tracing_loops
    loops += scaffold.exporter_loops(healthz)
    if sys["ring"] is not None:
        # device replay: the learner samples index bundles itself (cheap,
        # coupled to its dispatch) — no host batch-staging thread
        loops = [(n, f) for n, f in loops if n != "sample"]
    if cfg.in_graph_per:
        # priority feedback never crosses the host (the super-step
        # scatters it on-device) — nothing would ever feed this queue
        loops = [(n, f) for n, f in loops if n != "priority"]

    # both run on the learner thread, so their waits poll learner_stop:
    # the heartbeat keeps beating through a legitimately slow batch (the
    # watchdog only fires on a FROZEN thread), and a chaos freeze bites
    # wherever the learner happens to be waiting
    def batch_source():
        while not learner_stop():
            try:
                return batch_queue.get(timeout=0.1)
            except queue.Empty:
                continue
        return None

    def priority_sink(idxes, priorities, old_ptr, loss):
        while not learner_stop():
            try:
                priority_queue.put((idxes, priorities, old_ptr, loss),
                                   timeout=0.1)
                return
            except queue.Full:
                continue
        # stopped: the learner's exit drain still delivers its pipelined
        # pending results through this sink, and the priority thread may
        # already be gone — apply directly (lock-protected, order-free)
        # instead of silently dropping them
        buffer.update_priorities(idxes, priorities, old_ptr, loss)

    # everything that launches concurrent machinery (fleet subprocesses,
    # fabric threads) lives INSIDE the try: a failure anywhere in bring-up
    # must still reach the teardown below, or a caller catching the
    # exception is left with orphaned processes and /dev/shm slabs
    # handlers stay installed through the post-drain full-state save:
    # a second SIGTERM during the drain/snapshot must keep requesting a
    # clean stop, not kill the process mid-write (the save is atomic
    # either way, but the snapshot would be lost); restored on EVERY
    # exit path, including exceptions
    try:
        fleet_snaps = None
        try:
            if replay_plane is not None:
                # shard processes first: every other plane's ingest path
                # routes into them (restores armed by _build apply here)
                replay_plane.start()
            if plane is not None:
                plane.start(sys["param_store"])
            if sidecar is not None:
                sidecar.start()
            scaffold.start(loops)
            with device_profile(profile_dir):
                if sys["ring"] is not None:
                    metrics = learner.run_device(buffer, sys["ring"],
                                                 priority_sink,
                                                 stop=learner_stop,
                                                 tracer=tracer)
                else:
                    metrics = learner.run(batch_source, priority_sink,
                                          stop=learner_stop, tracer=tracer)
        finally:
            # the run's final health verdict, sampled while every plane
            # still exists (post-shutdown a plane reports alive=0, which
            # would misread as degraded) — metrics["healthz"] below
            try:
                final_health = healthz()
            except Exception:
                final_health = {}
            scaffold.quiesce()
            league_final = None
            if sidecar is not None:
                # status sampled pre-shutdown so metrics report the
                # verdict the run actually served with, then stop the
                # child before the fleet plane: eval is pure overhead
                # during a drain, and a sidecar mid-restore must not
                # race the retention GC the epilogue save may trigger
                league_final = sidecar.status()
                sidecar.shutdown()
            if plane is not None:
                # drain-then-save: collect resumable actor snapshots from the
                # dying fleets (answered by their shutdown handshake)
                fleet_snaps = plane.shutdown(snapshot=want_full_save)
            for a in actors:
                a.close()

        # drain remaining priority feedback so buffer counters are final
        while True:
            try:
                idxes, priorities, old_ptr, loss = priority_queue.get_nowait()
            except queue.Empty:
                break
            buffer.update_priorities(idxes, priorities, old_ptr, loss)

        # full-state snapshot, AFTER the drain so ring priorities/counters are
        # final: the learner state was already saved by Learner.run's epilogue;
        # this persists the warm replay ring + sum-tree + actor RNG/env state
        # next to it, atomically — what --resume restores through _build
        if want_full_save:
            try:
                actor_snaps = (fleet_snaps if plane is not None
                               else [a.snapshot() for a in actors])
                try:
                    step = learner.num_updates
                except Exception:  # learner died mid-dispatch: tag host-side
                    step = buffer.training_steps
                checkpointer.save_replay(step, buffer.write_state,
                                         actors=actor_snaps)
            except Exception as e:  # never fail the run over snapshot I/O
                log.warning("full-state replay snapshot failed: %s", e)

        metrics.update(buffer_size=len(buffer), logs=list(logs),
                       buffer_training_steps=buffer.training_steps,
                       final_params=learner.state.params,
                       restored_replay=sys["restored_replay"],
                       learner_stalled=stall["stalled"],
                       trace=tracer.snapshot(), health=supervisor.health(),
                       telemetry_port=telemetry.port,
                       fabric_failed=(supervisor.any_failed
                                      or (plane is not None and plane.failed)),
                       learnhealth=scaffold.learnhealth.snapshot(),
                       alerts=scaffold.alerts.counts(),
                       healthz=final_health)
        if chaos is not None:
            metrics["chaos"] = chaos.counts()
        if plane is not None:
            metrics["fleet_health"] = plane.health()
        if replay_plane is not None:
            metrics["replay_shard_health"] = replay_plane.health()
        if sidecar is not None:
            # pre-shutdown verdict + a final table re-read (rows the
            # sidecar committed during its own drain still count)
            metrics["league"] = dict(sidecar.status(max_age=0.0),
                                     health=(league_final or {}).get(
                                         "health",
                                         sidecar.health()))
        # member-tagged experience flow ({0: n} outside a population;
        # the sharded facade reports {} — its per-member counts live
        # shard-side, the plane's population rows cover the trainer view)
        metrics["blocks_per_member"] = buffer.stats().get(
            "blocks_per_member", {})
        return metrics
    finally:
        # AFTER the epilogue: the priority drain and the full-state
        # snapshot fan-out above both need live shard processes
        if replay_plane is not None:
            replay_plane.shutdown()
        scaffold.close()
