"""Vectorised prioritised-replay sum tree (host-side).

Same capability as the reference's ``PriorityTree`` (priority_tree.py:4-45):
flat-array binary sum tree, batched leaf updates with level-by-level upward
propagation, stratified proportional sampling with a vectorised top-down
descent, and min-normalised importance-sampling weights.  Stays on the host by
design — it is O(log n) pointer-chasing, the wrong shape for the MXU; the
TPU sees only the resulting batch indices/weights.

The update/descent hot loops run under the replay-buffer lock on a host
core shared with actor inference, so they dispatch to the native C fast
path (r2d2_tpu/native — exact bit-identical ports that also release the
GIL) when it is available, and fall back to the numpy implementations
otherwise (``R2D2_NO_NATIVE=1`` forces the fallback).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from r2d2_tpu import native


class SumTree:
    def __init__(self, capacity: int, prio_exponent: float, is_exponent: float,
                 rng: Optional[np.random.Generator] = None):
        self.capacity = capacity
        # number of levels so that the leaf layer has >= capacity slots
        self.num_levels = 1
        while 2 ** (self.num_levels - 1) < capacity:
            self.num_levels += 1
        self.leaf_offset = 2 ** (self.num_levels - 1) - 1
        self.nodes = np.zeros(2 ** self.num_levels - 1, dtype=np.float64)
        self.prio_exponent = prio_exponent
        self.is_exponent = is_exponent
        self.rng = rng if rng is not None else np.random.default_rng()

    @property
    def total(self) -> float:
        return float(self.nodes[0])

    def update(self, idxes: np.ndarray, td_errors: np.ndarray) -> None:
        """Set leaf priorities to ``td**alpha`` and repair ancestor sums.

        Batched: each tree level is repaired once for the unique set of touched
        parents (reference: priority_tree.py:15-24).
        """
        idxes = np.asarray(idxes, dtype=np.int64)
        if idxes.size == 0:
            return
        leaf_count = self.nodes.size - self.leaf_offset
        if int(idxes.min()) < 0 or int(idxes.max()) >= leaf_count:
            # shared by both backends: the numpy path would otherwise
            # silently overwrite ancestor sums via negative indexing, the C
            # path write outside the nodes heap
            raise IndexError(
                f"sum-tree leaf index out of range [0, {leaf_count}): "
                f"[{int(idxes.min())}, {int(idxes.max())}]")
        prios = np.asarray(td_errors, dtype=np.float64) ** self.prio_exponent
        if native.st_update(self.nodes, self.num_levels, self.leaf_offset,
                            idxes, prios):
            return
        nodes = idxes + self.leaf_offset
        self.nodes[nodes] = prios
        for _ in range(self.num_levels - 1):
            nodes = np.unique((nodes - 1) // 2)
            self.nodes[nodes] = self.nodes[2 * nodes + 1] + self.nodes[2 * nodes + 2]

    def _descend(self, targets: np.ndarray) -> np.ndarray:
        """Vectorised lock-step top-down descent: prefix-sum targets →
        leaf *node* ids (priority_tree.py:26-44 analogue)."""
        out = native.st_descend(self.nodes, self.num_levels, targets)
        if out is not None:
            return out
        targets = targets.copy()
        nodes = np.zeros(targets.shape[0], dtype=np.int64)
        for _ in range(self.num_levels - 1):
            left = 2 * nodes + 1
            left_mass = self.nodes[left]
            go_right = targets >= left_mass
            nodes = np.where(go_right, left + 1, left)
            targets = np.where(go_right, targets - left_mass, targets)
        return nodes

    def sample(self, num_samples: int, raw: bool = False
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Stratified proportional sample of ``num_samples`` leaves.

        The total mass is split into equal strata with one uniform draw each,
        then all descents run lock-step vectorised (priority_tree.py:26-44).
        Returns (leaf indices, IS weights).  Weights are ``(p/min_p)^-beta``
        normalised by the minimum *sampled* priority, so they lie in (0, 1]
        — the reference's scheme, which avoids a global min-tree.

        ``raw=True`` returns the sampled leaf priorities UNNORMALISED (and
        un-clamped) in the weights slot instead: the sharded replay plane's
        shard servers draw per-shard rows this way and the trainer-side
        coordinator applies the zero-leaf clamp + min-normalisation across
        ALL shards' rows at once, preserving the K=1 min-of-the-whole-batch
        IS scheme content-for-content (parallel/replay_shards.py).
        """
        total = self.nodes[0]
        if total <= 0:
            raise ValueError("cannot sample from an empty tree")
        interval = total / num_samples
        targets = interval * np.arange(num_samples, dtype=np.float64)
        targets += self.rng.uniform(0.0, interval, num_samples)
        nodes = self._descend(targets)

        prios = self.nodes[nodes]
        if raw:
            return nodes - self.leaf_offset, prios.copy()
        # numerical guard: a descent can land on a zero leaf when float error
        # accumulates; clamp to the smallest positive sampled priority
        pos = prios[prios > 0]
        min_p = pos.min() if pos.size else 1.0
        prios = np.maximum(prios, min_p)
        is_weights = (prios / min_p) ** (-self.is_exponent)
        return nodes - self.leaf_offset, is_weights

    # ------------------------------------------------------------ snapshot
    def leaf_values(self) -> np.ndarray:
        """Raw leaf priorities (already ``td**alpha``), length ``capacity``
        — the replay-snapshot payload (checkpoint.py save_replay)."""
        return self.nodes[self.leaf_offset:self.leaf_offset
                          + self.capacity].copy()

    def load_leaves(self, leaves: np.ndarray) -> None:
        """Restore raw leaf priorities (as returned by :meth:`leaf_values`)
        and rebuild every ancestor bottom-up.

        Bit-exact with the incrementally-maintained tree: :meth:`update`
        keeps the invariant that every internal node is EXACTLY the float64
        sum of its two children, so a whole-level bottom-up rebuild from
        identical leaves reproduces the identical node array (asserted in
        tests/test_recovery.py)."""
        leaves = np.asarray(leaves, np.float64)
        if leaves.shape != (self.capacity,):
            raise ValueError(
                f"leaf snapshot has shape {leaves.shape}, tree capacity is "
                f"{self.capacity} — replay snapshot written under a "
                "different buffer geometry")
        self.nodes[:] = 0.0
        self.nodes[self.leaf_offset:self.leaf_offset + self.capacity] = leaves
        for level in range(self.num_levels - 2, -1, -1):
            idx = np.arange(2 ** level - 1, 2 ** (level + 1) - 1)
            self.nodes[idx] = self.nodes[2 * idx + 1] + self.nodes[2 * idx + 2]

    def prefix_mass(self, leaf_idx: int) -> float:
        """Total priority mass of all leaves strictly before ``leaf_idx``
        (O(log n) root walk)."""
        leaf_idx = int(leaf_idx)
        if leaf_idx < 0:
            raise IndexError(f"prefix_mass leaf index {leaf_idx} < 0")
        if leaf_idx >= self.leaf_offset + 1:
            # every leaf is strictly before: the root walk below (and its C
            # port) would start one node past the array when the leaf layer
            # is exactly ``capacity`` (power-of-two capacities) and return
            # 0.0 — e.g. ready()'s last-group mass at num_sequences=4096
            return self.total
        mass = native.st_prefix_mass(self.nodes, self.leaf_offset, leaf_idx)
        if mass is not None:
            return mass
        node = leaf_idx + self.leaf_offset
        mass = 0.0
        while node > 0:
            parent = (node - 1) // 2
            if node == 2 * parent + 2:  # right child: count left sibling
                mass += float(self.nodes[2 * parent + 1])
            node = parent
        return mass

    def sample_range(self, num_samples: int, lo: int, hi: int
                     ) -> Tuple[np.ndarray, np.ndarray, float]:
        """Stratified proportional sample restricted to leaves [lo, hi).

        Used by the dp-sharded device ring: each dp group draws its batch
        rows from its own slice of the leaf space.  Returns (leaf indices,
        raw sampled priorities, range mass) — IS-weight normalisation is
        the caller's job so it can normalise across ALL groups' draws at
        once (keeping the reference's min-of-the-whole-batch scheme), and
        the mass it needs is returned rather than recomputed (two O(log n)
        root walks per group saved in the sampling hot path).
        """
        lo_mass = self.prefix_mass(lo)
        mass = self.prefix_mass(hi) - lo_mass
        if mass <= 0:
            raise ValueError(
                f"cannot sample from empty leaf range [{lo}, {hi})")
        interval = mass / num_samples
        targets = lo_mass + interval * np.arange(num_samples,
                                                 dtype=np.float64)
        targets += self.rng.uniform(0.0, interval, num_samples)
        idxes = self._descend(targets) - self.leaf_offset
        # float error at stratum boundaries can step just outside the range
        idxes = np.clip(idxes, lo, hi - 1)
        return idxes, self.nodes[idxes + self.leaf_offset].copy(), mass
