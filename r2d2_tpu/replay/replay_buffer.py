"""Prioritised sequence replay buffer (host-side data plane).

Capability-parity with the reference's ``ReplayBuffer`` (worker.py:38-261):
a ring of blocks with one PER leaf per learning sequence, stratified
prioritised sampling, IS weights, stale-index masking when leaves are
overwritten between sampling and the learner's priority feedback, and
size/env-step/episode-return accounting.

TPU-first redesign vs the reference: blocks live in **preallocated
contiguous ring arrays** instead of a Python list of ragged objects, so a
64-sequence batch is assembled by a handful of vectorised fancy-index
gathers into fixed-shape ``(B, T, ...)`` numpy arrays (replacing the
per-sample Python slicing loop + ``pad_sequence`` at worker.py:176-214).
Fixed shapes mean the jitted learner step compiles once; the gather is the
whole batch cost, which is what lets the host feed a TPU-rate learner.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.replay.block import Block, slot_layout, slot_views
from r2d2_tpu.replay.sum_tree import SumTree
from r2d2_tpu.telemetry.tracing import EVENTS

# at most this many lineage flow points per sampled batch / feedback
# call: a B=64 batch touching 64 distinct blocks must not dump 64 flow
# records into the ring per draw — a few complete chains per capture is
# what the timeline needs
_FLOW_CAP = 8


def _emit_flows(name: str, trace_ids: np.ndarray, fph: str) -> None:
    """Flow points for the distinct nonzero capture-window trace ids in
    ``trace_ids`` (capped) — no-op unless a capture is armed."""
    if not EVENTS.armed:
        return
    seen = 0
    for tid in np.unique(trace_ids):
        if tid == 0:
            continue
        EVENTS.instant(name, flow=int(tid), fph=fph)  # graftlint: disable=telemetry-discipline -- pass-through helper; call sites pass literal names
        seen += 1
        if seen >= _FLOW_CAP:
            break


def _data_spec(cfg: Config, action_dim: int):
    """(name, shape, dtype) of the bulk experience arrays.  These are the
    arrays that can live on-device instead (replay/device_ring.py)."""
    NB, K, MS = cfg.num_blocks, cfg.seqs_per_block, cfg.max_block_steps
    BL, layers, H = cfg.block_length, cfg.lstm_layers, cfg.hidden_dim
    return (
        ("obs", (NB, MS, *cfg.stored_obs_shape), np.uint8),
        ("last_action", (NB, MS, action_dim), bool),
        ("last_reward", (NB, MS), np.float32),
        ("action", (NB, BL), np.uint8),
        ("n_step_reward", (NB, BL), np.float32),
        ("n_step_gamma", (NB, BL), np.float32),
        ("hidden", (NB, K, 2, layers, H), np.float32),
    )


def _count_spec(cfg: Config):
    """(name, shape, dtype) of the per-sequence/per-block accounting arrays
    — always host-side (they drive index computation and sampling)."""
    NB, K = cfg.num_blocks, cfg.seqs_per_block
    return (
        ("burn_in_steps", (NB, K), np.uint8),
        ("learning_steps", (NB, K), np.uint8),
        ("forward_steps", (NB, K), np.uint8),
        ("first_burn_in", (NB,), np.int64),
        ("block_learning_total", (NB,), np.int64),
    )


def _ring_spec(cfg: Config, action_dim: int):
    """(name, shape, dtype) of every preallocated host ring array — the
    single source of truth for both the allocation loop and the RAM
    guard."""
    return _data_spec(cfg, action_dim) + _count_spec(cfg)


def data_bytes(cfg: Config, action_dim: int) -> int:
    """Bytes of the bulk experience arrays alone (what a DeviceRing puts
    in HBM)."""
    return sum(int(np.prod(shape)) * np.dtype(dtype).itemsize
               for _, shape, dtype in _data_spec(cfg, action_dim))


def ring_bytes(cfg: Config, action_dim: int) -> int:
    """Total bytes the preallocated ring arrays will occupy.

    Dominated by ``obs``: at flagship defaults (5,000 blocks × 441 steps ×
    84·84 space-to-depth bytes) the obs ring alone is ~15.5 GB, allocated
    eagerly in ``ReplayBuffer.__init__`` — same transition count as the
    reference's 2M-transition buffer (config.py:16) but contiguous instead
    of lazily-held ragged blocks."""
    return sum(int(np.prod(shape)) * np.dtype(dtype).itemsize
               for _, shape, dtype in _ring_spec(cfg, action_dim))


def _layout_fingerprint(spec) -> list:
    """JSON-able (name, shape, dtype) list identifying a snapshot layout."""
    return [[name, list(shape), np.dtype(dtype).name]
            for name, shape, dtype in spec]


def _available_host_bytes() -> Optional[int]:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:  # non-Linux host: skip the guard
        pass
    return None


class ReplayBuffer:
    """Synchronous core. Thread-safe via one lock; process/queue plumbing
    lives in :mod:`r2d2_tpu.train` so this class stays directly testable."""

    def __init__(self, cfg: Config, action_dim: int,
                 rng: Optional[np.random.Generator] = None,
                 device_ring: Optional[Any] = None):
        """``device_ring`` (replay/device_ring.DeviceRing): when given, the
        bulk experience arrays live in HBM — ``add`` streams each block to
        the device once, ``sample_meta`` yields index bundles for the
        in-graph gather, and the big host data arrays are NOT allocated
        (``sample_batch`` then raises)."""
        self.cfg = cfg
        self.action_dim = action_dim
        self.device_ring = device_ring
        if getattr(cfg, "in_graph_per", False) and device_ring is None:
            # fail HERE with the remedy, not with an AttributeError in an
            # actor thread at the first block commit: device PER cannot
            # run on the host-staged fallback (the host tree is never
            # populated and the priority loop is stripped, train.py)
            raise ValueError(
                "in_graph_per requires a device ring, but none was built "
                "— the ring did not fit the device budget or the "
                "multi-host shape checks failed (see the warning above); "
                "shrink buffer_capacity or set in_graph_per=False")

        # Slot groups (dp-sharded device ring): the ring's slot axis is
        # partitioned into G contiguous slabs, one per dp mesh group.  The
        # logical FIFO walk maps onto physical slots round-robin across the
        # slabs (see _phys_block) so every group fills from the first
        # block, and sampling draws each group's batch rows from its own
        # slab (sample_meta) so the in-graph gather never crosses shards.
        # G == 1 (host ring / replicated device ring) makes every mapping
        # the identity.
        self.G = (getattr(device_ring, "num_groups", 1)
                  if device_ring is not None else 1)
        assert cfg.num_blocks % self.G == 0  # DeviceRing validated this
        self._blocks_per_group = cfg.num_blocks // self.G
        # in-graph PER + dp slabs: host-side record of which slabs have
        # ever received a block with positive mass (the `ready` gate —
        # the host tree stays empty in that mode)
        self._group_filled = np.zeros(self.G, bool)

        spec = _count_spec(cfg) if device_ring is not None else _ring_spec(
            cfg, action_dim)
        # Fail fast with an actionable message instead of letting the
        # allocator OOM partway through the allocation loop (or, worse,
        # later as the lazily-committed pages fill).  Cap at 90% of
        # MemAvailable: the model, staged batches, and XLA host buffers
        # need their own headroom.
        need = sum(int(np.prod(shape)) * np.dtype(dtype).itemsize
                   for _, shape, dtype in spec)
        avail = _available_host_bytes()
        if avail is not None and need > 0.9 * avail:
            raise MemoryError(
                f"replay ring needs {need / 1e9:.1f} GB but only "
                f"{avail / 1e9:.1f} GB of host memory is available "
                "(guard requires 10% headroom) — reduce buffer_capacity / "
                "block_length / obs size (flagship defaults need ~16 GB; "
                "see README)")

        for name, shape, dtype in spec:
            setattr(self, name, np.zeros(shape, dtype))

        self.tree = SumTree(cfg.num_sequences, cfg.prio_exponent,
                            cfg.importance_sampling_exponent, rng=rng)

        # data-health sidecar (telemetry/learnhealth.py): the resident
        # block's member id per physical slot + cumulative sampled-row
        # counts per member — the replay-side proof that every
        # population member's experience is actually being TRAINED on,
        # not just stored.  Not part of the snapshot layout (a resume
        # recounts from its warm ring's new adds/draws).
        self._slot_member = np.zeros(cfg.num_blocks, np.int32)
        self.samples_per_member: Dict[int, int] = {}

        # block-lineage sidecar (telemetry/tracing.py): per PHYSICAL slot,
        # the resident block's cut/add wall-clock stamps (feed the
        # pipeline.block_age_at_train_s / pipeline.hop.* histograms) and
        # its capture-window trace id (0 in steady state).  Deliberately
        # NOT part of the snapshot layout: after a restore the stamps are
        # zero and age observation skips those slots.
        self._slot_cut_ts = np.zeros(cfg.num_blocks)
        self._slot_add_ts = np.zeros(cfg.num_blocks)
        self._slot_trace = np.zeros(cfg.num_blocks, np.int64)

        self.lock = threading.Lock()
        self.block_ptr = 0
        self.size = 0            # total learning steps stored (reference "size")
        self.env_steps = 0
        self.num_episodes = 0
        self.episode_reward = 0.0
        self.training_steps = 0
        self.sum_loss = 0.0
        self.corrupt_blocks = 0  # wire-format CRC mismatches, never reset
        # member-tagged experience flow (league/population.py): blocks
        # added per Block.member_id — cumulative, telemetry-only (not in
        # the replay snapshot: a resume recounts from its warm ring's
        # NEW adds).  {0: n} outside a population run
        self.blocks_per_member: Dict[int, int] = {}

    def __len__(self) -> int:
        return self.size

    def _phys_block(self, n):
        """Logical ring position → physical slot (round-robin over the G
        group slabs; identity for G == 1).  Bijection on [0, num_blocks)."""
        return (n % self.G) * self._blocks_per_group + n // self.G

    def _log_block(self, p):
        """Physical slot → logical ring position (inverse of
        :meth:`_phys_block`)."""
        return (p % self._blocks_per_group) * self.G + p // self._blocks_per_group

    @property
    def ready(self) -> bool:
        if self.size < self.cfg.learning_starts:
            return False
        if self.G > 1:
            # per-group sampling needs every slab non-empty; round-robin
            # fill reaches all slabs within the first G blocks, long before
            # any realistic learning_starts, but guard the degenerate case.
            if getattr(self.cfg, "in_graph_per", False):
                # priorities live on-device (the host tree stays empty):
                # gate on the host-side ever-filled record instead — a
                # slab counts filled once a block with positive mass
                # landed in it (add() below)
                return bool(self._group_filled.all())
            # Unlike the GIL-atomic `size` read above, the mass walk spans
            # many tree nodes — take the lock so a concurrent update's
            # level-order repair can't produce a torn (spuriously positive)
            # difference.
            K = self.cfg.seqs_per_block
            span = self._blocks_per_group * K
            with self.lock:
                if any(self.tree.prefix_mass((g + 1) * span)
                       - self.tree.prefix_mass(g * span) <= 0.0
                       for g in range(self.G)):
                    return False
        return True

    # ------------------------------------------------------------------ add
    def add(self, block: Block, priorities: np.ndarray,
            episode_reward: Optional[float]) -> None:
        """Overwrite the ring slot at ``block_ptr`` (worker.py:141-161)."""
        cfg = self.cfg
        K = cfg.seqs_per_block
        # Stage the device copy OUTSIDE the lock: the zero-pad + H2D
        # transfers are the expensive part of a device-ring write, and the
        # learner's sample+dispatch serialises on this same lock.  Only the
        # donated commit (one async dispatch) needs the ordering the lock
        # provides.
        staged = (self.device_ring.stage(block)
                  if self.device_ring is not None else None)
        in_graph = getattr(cfg, "in_graph_per", False)
        if in_graph:
            # device-PER leaves: td**alpha — ``priorities`` arrives
            # K-length zero-padded past the block's real sequences
            # (block.py:108), and 0**alpha keeps the padding zero ==
            # unsampleable for the in-graph categorical; the metadata
            # bundle is per real sequence (k_seq-length)
            k_seq = block.num_sequences
            prios_alpha = (np.asarray(priorities, np.float64)
                           ** cfg.prio_exponent).astype(np.float32)
            meta = np.zeros((K, 3), np.int32)
            meta[:k_seq, 0] = block.burn_in_steps
            meta[:k_seq, 1] = block.learning_steps
            meta[:k_seq, 2] = block.forward_steps
        with self.lock:
            ptr = self.block_ptr
            # every array (and the PER leaves) is keyed by the PHYSICAL
            # slot; the logical ptr only orders the FIFO walk
            slot = self._phys_block(ptr)
            if in_graph:
                # priorities live on-device; the host tree stays empty
                self.device_ring.commit_per(slot, prios_alpha, meta,
                                            int(block.burn_in_steps[0]))
                if prios_alpha.max() > 0:
                    self._group_filled[slot // self._blocks_per_group] = True
            else:
                leaf_idxes = np.arange(slot * K, (slot + 1) * K,
                                       dtype=np.int64)
                self.tree.update(leaf_idxes, priorities)

            self.size -= int(self.block_learning_total[slot])

            k = block.num_sequences
            if staged is not None:
                # bulk data goes straight to HBM (once per block); the
                # stream-order/donation contract is upheld because we hold
                # self.lock, the same lock sample_meta dispatches under
                self.device_ring.commit(staged, slot)
            else:
                n_obs = block.obs.shape[0]
                n_steps = block.action.shape[0]
                self.obs[slot, :n_obs] = block.obs
                self.last_action[slot, :n_obs] = block.last_action
                self.last_reward[slot, :n_obs] = block.last_reward
                self.action[slot, :n_steps] = block.action
                self.n_step_reward[slot, :n_steps] = block.n_step_reward
                self.n_step_gamma[slot, :n_steps] = block.n_step_gamma
                self.hidden[slot, :k] = block.hidden
            self.burn_in_steps[slot] = 0
            self.learning_steps[slot] = 0
            self.forward_steps[slot] = 0
            self.burn_in_steps[slot, :k] = block.burn_in_steps
            self.learning_steps[slot, :k] = block.learning_steps
            self.forward_steps[slot, :k] = block.forward_steps
            self.first_burn_in[slot] = int(block.burn_in_steps[0])

            total = int(block.learning_steps.sum())
            self.block_learning_total[slot] = total
            self.size += total
            self.env_steps += total

            self.block_ptr = (ptr + 1) % cfg.num_blocks
            self._slot_cut_ts[slot] = block.cut_ts
            self._slot_add_ts[slot] = time.time()
            self._slot_trace[slot] = block.trace_id
            m = int(block.member_id)
            self._slot_member[slot] = m
            self.blocks_per_member[m] = self.blocks_per_member.get(m, 0) + 1
            if episode_reward is not None:
                self.episode_reward += episode_reward
                self.num_episodes += 1
        if block.trace_id:
            # lineage hop (armed capture only): the block landed in a ring
            # — the same event whether this buffer is the K=1 in-process
            # ring or a shard owner process's slice
            _emit_flows("replay.add_block", np.array([block.trace_id]),
                        "t")

    # --------------------------------------------------------------- sample
    def sample_batch(self, batch_size: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Assemble one fixed-shape training batch.

        Returns a dict of arrays (B = batch, T = seq_len, L = learning_steps):
        obs (B,T,*obs) u8 · last_action (B,T,A) f32 · last_reward (B,T) f32 ·
        hidden (B,2,layers,H) · action (B,L) i32 · n_step_reward/gamma (B,L) ·
        burn_in/learning/forward (B,) i32 · is_weights (B,) f32, plus host-only
        bookkeeping: idxes, block_ptr snapshot, env_steps (worker.py:219-238).
        """
        cfg = self.cfg
        if self.device_ring is not None:
            raise RuntimeError(
                "sample_batch needs host data arrays; this buffer runs "
                "device_replay — use sample_meta + the in-graph gather")
        B = batch_size or cfg.batch_size
        with self.lock:
            if self.size == 0:
                raise RuntimeError(
                    "sample_batch on an empty buffer; wait for add() (use "
                    "`ready` to gate on learning_starts)")
            idxes, is_weights = self.tree.sample(B)
            self._note_sampled(idxes)
            batch = dict(
                self._gather_rows(idxes),
                is_weights=is_weights.astype(np.float32),
                idxes=idxes,
                block_ptr=self.block_ptr,
                env_steps=self.env_steps,
                ages=self._row_ages(idxes),
            )
        if EVENTS.armed:
            _emit_flows("replay.sample",
                        self._slot_trace[idxes // cfg.seqs_per_block], "t")
        return batch

    def _note_sampled(self, idxes: np.ndarray) -> None:
        """Count sampled rows per resident member (caller holds the
        lock) — the per-member sample fractions of the data-health
        surface."""
        members = self._slot_member[idxes // self.cfg.seqs_per_block]
        for m, c in zip(*np.unique(members, return_counts=True)):
            m = int(m)
            self.samples_per_member[m] = (
                self.samples_per_member.get(m, 0) + int(c))

    def _row_ages(self, idxes: np.ndarray) -> np.ndarray:
        """(n, 2) float32 per-row block ages at gather time — seconds
        since the block was cut (column 0: the end-to-end freshness the
        learner trains on) and since it landed in this ring (column 1:
        the replay-residency hop).  Rows whose slot has no stamp (a
        restored snapshot — the sidecar is not persisted) carry -1 and
        the observers skip them.  Caller holds the lock."""
        slots = idxes // self.cfg.seqs_per_block
        now = time.time()
        cut, add = self._slot_cut_ts[slots], self._slot_add_ts[slots]
        ages = np.empty((idxes.shape[0], 2), np.float32)
        ages[:, 0] = np.where(cut > 0, np.maximum(0.0, now - cut), -1.0)
        ages[:, 1] = np.where(add > 0, np.maximum(0.0, now - add), -1.0)
        return ages

    def _gather_rows(self, idxes: np.ndarray,
                     out: Optional[Dict[str, np.ndarray]] = None
                     ) -> Dict[str, np.ndarray]:
        """The vectorised fancy-index gather of the per-row batch fields
        for leaf ``idxes`` — the assembly core shared by
        :meth:`sample_batch` (K=1 in-process path) and
        :meth:`serve_sample` (a sharded-plane owner process gathering its
        preassembled response rows).  Caller holds the lock.

        ``out``: destination views (the sharded plane's response slab,
        each already sliced to ``len(idxes)`` rows) — the dominant
        ``obs`` gather then runs as ONE ``np.take(..., out=)`` pass
        straight into the slab instead of materialising an intermediate
        batch-sized array first (tens of MB per RPC at pong scale).

        INVARIANT (load-bearing): the clamp below pads short sequences
        with whatever bytes previously occupied the ring slot.  This is
        safe because every index the learner gathers is
        < burn_in + learning + forward (learner/step.py:_window_indices
        clamps to that bound), i.e. strictly before the stale region,
        and loss/priorities are masked to the learning window.  The
        stale tail does flow through the LSTM scan, but only *after*
        the last gathered timestep, so it cannot affect any used
        output.  Tested in tests/test_replay_buffer.py.
        """
        cfg = self.cfg
        K, L, T = cfg.seqs_per_block, cfg.learning_steps, cfg.seq_len
        block_idx = idxes // K
        seq_idx = idxes % K

        burn_in = self.burn_in_steps[block_idx, seq_idx].astype(np.int64)
        learning = self.learning_steps[block_idx, seq_idx].astype(np.int64)
        forward = self.forward_steps[block_idx, seq_idx].astype(np.int64)

        # obs-coordinate window start: first burn-in prefix + k full
        # learning windows (worker.py:186), reaching back over this
        # sequence's own burn-in.
        start = self.first_burn_in[block_idx] + seq_idx * L
        t0 = start - burn_in
        time_idx = np.minimum(t0[:, None] + np.arange(T),
                              cfg.max_block_steps - 1)
        bcol = block_idx[:, None]
        widx = np.minimum(seq_idx[:, None] * L + np.arange(L),
                          cfg.block_length - 1)
        if out is None:
            return dict(
                obs=self.obs[bcol, time_idx],
                last_action=self.last_action[bcol, time_idx].astype(
                    np.float32),
                last_reward=self.last_reward[bcol, time_idx],
                hidden=self.hidden[block_idx, seq_idx],
                action=self.action[bcol, widx].astype(np.int32),
                n_step_reward=self.n_step_reward[bcol, widx],
                n_step_gamma=self.n_step_gamma[bcol, widx],
                burn_in=burn_in.astype(np.int32),
                learning=learning.astype(np.int32),
                forward=forward.astype(np.int32),
            )
        n = idxes.shape[0]
        # obs dominates the batch bytes: one flat-index take straight
        # into the destination (same [block, time] pairs as the fancy
        # gather above — bit-identical rows, one fewer full pass)
        flat_t = (block_idx[:, None] * cfg.max_block_steps
                  + time_idx).ravel()
        np.take(self.obs.reshape(cfg.num_blocks * cfg.max_block_steps, -1),
                flat_t, axis=0, out=out["obs"].reshape(n * T, -1))
        # the rest is small relative to obs: plain gathers/casts into out
        out["last_action"][...] = self.last_action[bcol, time_idx]
        out["last_reward"][...] = self.last_reward[bcol, time_idx]
        out["hidden"][...] = self.hidden[block_idx, seq_idx]
        out["action"][...] = self.action[bcol, widx]
        out["n_step_reward"][...] = self.n_step_reward[bcol, widx]
        out["n_step_gamma"][...] = self.n_step_gamma[bcol, widx]
        out["burn_in"][...] = burn_in
        out["learning"][...] = learning
        out["forward"][...] = forward
        return out

    def serve_sample(self, n: int,
                     out: Optional[Dict[str, np.ndarray]] = None):
        """One shard-side sample service call (the sharded replay plane's
        owner processes, parallel/replay_shards.py): a stratified draw of
        ``n`` rows over THIS buffer's own tree plus the gathered row
        fields.  Returns ``(rows, idxes, raw_prios, block_ptr,
        env_steps)`` — priorities travel RAW (no zero-clamp, no IS
        normalisation) because the trainer-side coordinator normalises by
        the min across ALL shards' rows at once, preserving the K=1
        min-of-the-whole-batch scheme; ``block_ptr`` is this buffer's
        local FIFO pointer, which the shard's own
        :meth:`update_priorities` stale-mask needs at feedback time.
        ``out``: response-slab destination views (already sliced to
        ``n`` rows) the gather writes straight into.  The trailing
        ``ages`` element is the :meth:`_row_ages` lineage decomposition
        the trainer-side coordinator feeds into the ``pipeline.*``
        histograms (the shard process has no registry of its own)."""
        with self.lock:
            if self.size == 0 or self.tree.total <= 0:
                # the coordinator's mass vector can be one publish stale —
                # answer empty instead of raising so the trainer
                # redistributes the rows over the shards that have mass
                return None
            idxes, prios = self.tree.sample(n, raw=True)
            self._note_sampled(idxes)
            rows = self._gather_rows(idxes, out=out)
            ages = self._row_ages(idxes)
        if EVENTS.armed:
            _emit_flows("replay.sample",
                        self._slot_trace[idxes // self.cfg.seqs_per_block],
                        "t")
        return rows, idxes, prios, self.block_ptr, self.env_steps, ages

    # ---------------------------------------------------------- sample (meta)
    def sample_meta(self, k: int, batch_size: Optional[int] = None,
                    dispatch=None,
                    raw_densities: bool = False) -> Dict[str, np.ndarray]:
        """Sample ``k`` index bundles for the in-graph device gather
        (replay/device_ring.gather_batch) — the index arithmetic of
        ``sample_batch`` without touching any data array.

        The k bundles are drawn without intermediate priority feedback,
        mirroring the prefetch depth of the queued host path (the reference
        stages up to 8+4 batches ahead of the learner, worker.py:300-316).

        ``dispatch``, when given, is called as ``dispatch(ints, weights)``
        while the buffer lock is still held and its result returned under
        ``meta["dispatched"]`` — this orders the train-step dispatch before
        any later ring write (the device_ring concurrency contract).

        dp-sharded rings (G > 1): batch rows [g·B/G, (g+1)·B/G) are drawn
        from group g's slab via :meth:`SumTree.sample_range`, so row chunk
        g — which a ``P(None, "dp")`` sharding places on dp-index g — only
        references slots that device group holds.  Priorities still drive
        selection *within* each group; the fixed B/G per-group allocation
        is the one deviation from global stratified sampling (group
        assignment is round-robin, i.e. priority-independent, so group
        masses stay near-equal).  IS weights are exact for the realised
        distribution: row inclusion density is prio/mass_group, and weights
        are ``(q/min_q)^-beta`` min-normalised across the WHOLE batch —
        the reference scheme applied to the true per-group probabilities.

        ``raw_densities=True`` returns the inclusion densities q in the
        ``is_weights`` slots instead of normalised weights — the
        multi-host device-replay plane samples per host and normalises by
        the min across ALL hosts' rows (learner/learner.py), keeping the
        min-of-the-whole-batch scheme across the pod.

        Returns ints (k,B,6) i32 · is_weights (k,B) f32 · idxes (k,B) i64 ·
        block_ptr · env_steps.
        """
        cfg = self.cfg
        B = batch_size or cfg.batch_size
        K, L = cfg.seqs_per_block, cfg.learning_steps
        if B % self.G:
            raise ValueError(
                f"batch_size {B} not divisible by the ring's {self.G} "
                "slot groups")
        ints = np.empty((k, B, 6), np.int32)
        weights = np.empty((k, B), np.float32)
        idxes = np.empty((k, B), np.int64)
        with self.lock:
            if self.size == 0:
                raise RuntimeError(
                    "sample_meta on an empty buffer; wait for add() (use "
                    "`ready` to gate on learning_starts)")
            for j in range(k):
                if raw_densities:
                    idx, w = self._grouped_densities(B)
                elif self.G == 1:
                    idx, w = self.tree.sample(B)
                else:
                    idx, w = self._sample_grouped(B)
                block_idx = idx // K
                seq_idx = idx % K
                burn_in = self.burn_in_steps[block_idx, seq_idx].astype(
                    np.int64)
                start = self.first_burn_in[block_idx] + seq_idx * L
                ints[j, :, 0] = block_idx
                ints[j, :, 1] = start - burn_in          # t0, always >= 0
                ints[j, :, 2] = seq_idx
                ints[j, :, 3] = burn_in
                ints[j, :, 4] = self.learning_steps[block_idx, seq_idx]
                ints[j, :, 5] = self.forward_steps[block_idx, seq_idx]
                weights[j] = w
                idxes[j] = idx
                self._note_sampled(idx)
            meta = dict(ints=ints, is_weights=weights, idxes=idxes,
                        block_ptr=self.block_ptr, env_steps=self.env_steps)
            if dispatch is not None:
                meta["dispatched"] = dispatch(ints, weights)
        return meta

    def _grouped_densities(self, B: int) -> Tuple[np.ndarray, np.ndarray]:
        """One B-row draw (B/G rows per group slab) returning the raw
        per-row inclusion densities prio/mass_group (caller holds the
        lock).  Zero-density leaves (a descent landing on a zero leaf
        through float error) are clamped to the smallest positive sampled
        density, mirroring SumTree.sample's guard."""
        K = self.cfg.seqs_per_block
        span = self._blocks_per_group * K
        per = B // self.G
        idx_parts, q_parts = [], []
        for g in range(self.G):
            lo, hi = g * span, (g + 1) * span
            part, prios, mass = self.tree.sample_range(per, lo, hi)
            idx_parts.append(part)
            q_parts.append(prios / mass)
        idx = np.concatenate(idx_parts)
        q = np.concatenate(q_parts)
        pos = q[q > 0]
        q = np.maximum(q, pos.min() if pos.size else 1.0)
        return idx, q

    def _sample_grouped(self, B: int) -> Tuple[np.ndarray, np.ndarray]:
        """One B-row draw for a G-group ring with IS weights normalised by
        the minimum sampled density (caller holds the lock)."""
        idx, q = self._grouped_densities(B)
        w = (q / q.min()) ** (-self.tree.is_exponent)
        return idx, w

    # ------------------------------------------------------- priority update
    def update_priorities(self, idxes: np.ndarray, priorities: np.ndarray,
                          old_ptr: int, loss: float) -> None:
        """Write back learner priorities, discarding indices whose ring slots
        were overwritten since the batch was sampled (worker.py:242-261).

        The overwritten set is the interval [old_ptr, new_ptr) of the
        LOGICAL ring walk (with wraparound); leaf indices are physical, so
        they map back through :meth:`_log_block` first (identity for
        G == 1, where this reduces to the reference's pointer arithmetic).
        """
        K = self.cfg.seqs_per_block
        with self.lock:
            new_ptr = self.block_ptr
            n = self._log_block(idxes // K)
            if new_ptr > old_ptr:
                mask = (n < old_ptr) | (n >= new_ptr)
            elif new_ptr < old_ptr:
                mask = (n < old_ptr) & (n >= new_ptr)
            else:
                mask = np.ones_like(idxes, dtype=bool)
            self.tree.update(idxes[mask], priorities[mask])
            self.training_steps += 1
            self.sum_loss += float(loss)
            traces = (self._slot_trace[idxes[mask] // K]
                      if EVENTS.armed and mask.any() else None)
        if traces is not None:
            # lineage terminus (armed capture only): priority feedback
            # landed back on the owning ring — the end of the flow chain
            _emit_flows("replay.priority_feedback", traces, "f")

    def note_corrupt_block(self) -> None:
        """A wire-format integrity check failed and the block was dropped
        (actor_procs.ingest_once): count it so the log plane surfaces a
        garbling transport instead of silently thinning the data."""
        with self.lock:
            self.corrupt_blocks += 1

    def note_updates(self, n: int, loss_sum: float) -> None:
        """Learner-side update accounting when priority feedback never
        crosses the host (``cfg.in_graph_per`` — the scatter happens
        inside the super-step), so the log plane's ``stats()`` counters
        stay live without :meth:`update_priorities`."""
        with self.lock:
            self.training_steps += n
            self.sum_loss += float(loss_sum)

    # ------------------------------------------------------------- snapshot
    # scalar state that rides the replay snapshot's JSON meta (arrays ride
    # the binary payload); order is the wire order of the restore loop
    STATE_COUNTERS = ("block_ptr", "size", "env_steps", "num_episodes",
                      "episode_reward", "training_steps", "sum_loss",
                      "corrupt_blocks")

    def state_spec(self):
        """(name, shape, dtype) of the on-disk replay-snapshot payload: the
        ring arrays (the block.py slot layout reused at whole-ring scale)
        plus the PER leaf vector."""
        return _ring_spec(self.cfg, self.action_dim) + (
            ("tree_leaves", (self.tree.capacity,), np.float64),)

    def write_state(self, path: str) -> Dict[str, Any]:
        """Serialise the full replay state into ``path`` — one flat binary
        laid out by :func:`~r2d2_tpu.replay.block.slot_layout` over
        :meth:`state_spec` (the shm wire format's own layout scheme, so the
        on-disk format cannot drift from the ring a future field change
        lands in).  Returns the JSON-able meta (counters + sampling RNG +
        layout fingerprint) that :meth:`read_state` validates against.

        Host-ring buffers only: a device ring's bulk arrays live in HBM
        (and under ``in_graph_per`` so do the priorities) — those runs
        save learner state alone (documented in docs/OPERATIONS.md)."""
        if self.device_ring is not None:
            raise RuntimeError(
                "replay snapshot requires the host ring; device_replay "
                "runs persist learner state only")
        spec = self.state_spec()
        nbytes, offsets = slot_layout(spec)
        mm = np.memmap(path, np.uint8, "w+", shape=(nbytes,))
        views = slot_views(mm, spec, offsets, nbytes, 0)
        # the lock covers only the RAM-speed copy into the page cache (a
        # consistent ring+tree+counter cut); the msync below — the
        # disk-bound part, seconds at flagship ring sizes — runs with the
        # lock RELEASED so periodic snapshots don't flatline actor ingest
        # and batch staging for the duration of the write
        with self.lock:
            for name, _, _ in spec:
                views[name][:] = (self.tree.leaf_values()
                                  if name == "tree_leaves"
                                  else getattr(self, name))
            meta = dict(
                layout=_layout_fingerprint(spec),
                nbytes=nbytes,
                counters={k: getattr(self, k) for k in self.STATE_COUNTERS},
                rng_state=self.tree.rng.bit_generator.state,
                tree_total=self.tree.total,
            )
        del views
        mm.flush()
        del mm
        return meta

    def read_state(self, path: str, meta: Dict[str, Any]) -> None:
        """Restore the state :meth:`write_state` captured.  Raises
        ``ValueError`` when the snapshot was written under a different
        buffer geometry (the caller warns and resumes cold instead of
        ingesting a misaligned ring)."""
        spec = self.state_spec()
        nbytes, offsets = slot_layout(spec)
        want = _layout_fingerprint(spec)
        if meta.get("layout") != want:
            raise ValueError(
                "replay snapshot layout mismatch — written under a "
                "different buffer geometry/config; resuming with a cold "
                f"buffer (snapshot {meta.get('layout')} vs config {want})")
        mm = np.memmap(path, np.uint8, "r", shape=(nbytes,))
        views = slot_views(mm, spec, offsets, nbytes, 0)
        with self.lock:
            for name, _, _ in spec:
                if name == "tree_leaves":
                    self.tree.load_leaves(views[name])
                else:
                    getattr(self, name)[:] = views[name]
            c = meta["counters"]
            self.block_ptr = int(c["block_ptr"])
            self.size = int(c["size"])
            self.env_steps = int(c["env_steps"])
            self.num_episodes = int(c["num_episodes"])
            self.episode_reward = float(c["episode_reward"])
            self.training_steps = int(c["training_steps"])
            self.sum_loss = float(c["sum_loss"])
            self.corrupt_blocks = int(c.get("corrupt_blocks", 0))
            if meta.get("rng_state") is not None:
                self.tree.rng.bit_generator.state = meta["rng_state"]
        del views
        del mm

    # ---------------------------------------------------------- data health
    def data_health(self) -> Dict[str, Any]:
        """Learning-health view of the replay plane (telemetry/
        learnhealth.py; docs/OBSERVABILITY.md `learnhealth.replay.*`):
        the PER distribution's effective sample size + fixed-bucket
        priority histogram over the sum-tree leaves, the cumulative
        replay-ratio gauge (samples consumed per transition inserted),
        and per-member sampled-row counts (the ``member_id`` stamp).

        Under ``in_graph_per`` the priority leaves live on-device (the
        host tree stays empty) — ``priorities`` is then None; fetching
        the leaf vector per log interval would race the dispatch loop's
        donated handles, so the device-PER plane reports ratio/member
        flow only (documented in docs/OBSERVABILITY.md)."""
        from r2d2_tpu.telemetry.learnhealth import (
            priority_health,
            replay_ratio,
        )

        cfg = self.cfg
        in_graph = (getattr(cfg, "in_graph_per", False)
                    and self.device_ring is not None)
        with self.lock:
            leaves = None if in_graph else self.tree.leaf_values()
            training_steps = self.training_steps
            env_steps = self.env_steps
            samples = dict(self.samples_per_member)
        out: Dict[str, Any] = dict(
            replay_ratio=replay_ratio(cfg, training_steps, env_steps),
            samples_per_member=samples,
            priorities=None if leaves is None else priority_health(leaves),
        )
        return out

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, float]:
        with self.lock:
            s = dict(
                size=self.size, env_steps=self.env_steps,
                training_steps=self.training_steps,
                num_episodes=self.num_episodes,
                episode_reward=self.episode_reward,
                sum_loss=self.sum_loss,
                corrupt_blocks=self.corrupt_blocks,
                # the in-process buffer has no owner processes to lose;
                # the key exists so the log plane / r2d2_top render one
                # schema whether replay is sharded
                # (parallel/replay_shards.py reports real counts) or not
                shard_respawns=0,
                # member-tagged blocks (population runs tag via the wire
                # format's member_id word; {0: n} otherwise) — the
                # replay-side proof that every member's experience is
                # actually flowing
                blocks_per_member=dict(self.blocks_per_member),
            )
            self.episode_reward = 0.0
            self.num_episodes = 0
            self.sum_loss = 0.0
        return s
