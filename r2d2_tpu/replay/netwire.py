"""Cross-host replay wire format: the shard RPC vocabulary over TCP.

The sharded replay plane's RPC vocabulary (block ingest, stratified
sample request/response, priority feedback, mass/stat gossip,
snapshot/drain control — parallel/replay_shards.py) re-expressed as
length-framed CRC'd messages so the shards can live on OTHER HOSTS
(parallel/replay_net.py drives it; the in-network experience-sampling
deployment blueprint in PAPERS.md — sampling moves toward the data).
Nothing about the *content* changes: every payload spec here is DERIVED
from the shm plane's canonical slot specs (``replay/block.py``
``block_slot_spec`` / ``batch_slot_spec``), so the socket plane and the
shm plane can never drift field-for-field, and the framing reuses the
session tier's grammar verbatim (``serving/wire.py``: ``u32 length``,
``HEADER_WORDS`` int64 header words, payload arrays in ``slot_layout``
packing, CRC32 LAST over header + arrays via ``payload_crc32`` — one CRC
definition all the way down, enforced by the ``wire-format`` graftlint
rule, for which THIS module is the third canonical vocabulary).

Header convention (the session tier's four int64 words, reinterpreted):
``(kind, epoch, seq, aux)``.

- ``kind`` — one of the ``NMSG_*`` constants below (numbered disjoint
  from the session tier's ``MSG_*`` so a frame delivered to the wrong
  port is unmistakably foreign).
- ``epoch`` — the shard's incarnation tag: the PR 9 *generation* made a
  wire word.  A shard server stamps its epoch into every frame it sends;
  the trainer stamps the epoch it believes the shard is in.  A mismatch
  means one side restarted/restored across the exchange — the receiver
  DROPS the frame and counts it (``epoch_drops``): stale priority
  feedback must never scribble on a restored ring, stale responses must
  never enter a batch.
- ``seq`` — per-link monotone request token (a retry supersedes).
- ``aux`` — kind-specific small scalar (shard id, row count, status).

Kinds:

- ``NMSG_HELLO``   (trainer → shard): attach request.  Payload
  ``net_hello_spec`` carries the geometry ``layout_token`` (a CRC over
  the derived frame layouts) and the shard id the trainer expects — a
  mis-wired endpoint or drifted config fails the handshake instead of
  garbling traffic.
- ``NMSG_WELCOME`` (shard → trainer): handshake reply; ``epoch`` is the
  shard's current epoch, ``aux`` the shard id (−1 = geometry/identity
  rejected, connection closes).
- ``NMSG_INGEST``  (trainer → shard): one routed block.  Payload
  ``net_ingest_spec`` = the shm block slot spec plus the shape header
  words that ride the metadata queue on the shm path.
- ``NMSG_SAMPLE_REQ`` (trainer → shard): stratified sample request;
  ``aux`` = rows wanted.  Payload-free.
- ``NMSG_SAMPLE_RSP`` (shard → trainer): the preassembled batch rows.
  Payload ``net_sample_response_spec`` = the shm sample slab minus the
  slab-only request/seq/CRC scalar words (the frame header and frame CRC
  carry those roles).
- ``NMSG_PRIO``    (trainer → shard): priority feedback for up to a
  batch of rows; ``aux`` = used rows.  Payload ``net_feedback_spec``.
- ``NMSG_STATS``   (shard → trainer): mass/stat gossip — the shm stats
  slab's float64 vector pushed over the wire on the shard's publish
  cadence; ``seq`` is the publish sequence the trainer-side
  CounterMerger folds across reconnects/respawns.
- ``NMSG_SAVE``    (trainer → shard): drain-then-save control
  (``net_save_spec``: snapshot path + the routed/feedback expectations
  the shard drains to before writing).
- ``NMSG_SAVE_RSP`` (shard → trainer): the shard's snapshot meta as
  JSON bytes (``net_save_response_spec``); ``aux`` 0 = ok.
"""
from __future__ import annotations

import json
from typing import Tuple

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.replay.block import (
    batch_slot_spec,
    block_slot_spec,
    payload_crc32,
    slot_layout,
)
from r2d2_tpu.serving.wire import HEADER_WORDS  # noqa: F401  (re-export:
# netwire frames use the session grammar's header geometry verbatim)

# message kinds (header word 0) — disjoint from serving/wire.py MSG_*
NMSG_HELLO = 16
NMSG_WELCOME = 17
NMSG_INGEST = 18
NMSG_SAMPLE_REQ = 19
NMSG_SAMPLE_RSP = 20
NMSG_PRIO = 21
NMSG_STATS = 22
NMSG_SAVE = 23
NMSG_SAVE_RSP = 24

# bounded string/JSON payload regions of the save control frames
SAVE_PATH_BYTES = 4096
SAVE_META_BYTES = 1 << 16


def net_hello_spec():
    """Attach-request payload: the geometry token + expected shard id."""
    return (("hello_token", (1,), np.int64),
            ("hello_shard", (1,), np.int64))


def net_ingest_spec(cfg: Config, action_dim: int):
    """One routed block as a frame payload: the canonical shm block slot
    spec (CRC word included — written by ``write_block`` exactly as on
    the shm path, a second integrity word under the frame CRC) plus the
    shape header that crosses the metadata queue on the shm transport."""
    return block_slot_spec(cfg, action_dim) + (
        ("ing_k", (1,), np.int64),
        ("ing_n_obs", (1,), np.int64),
        ("ing_n_steps", (1,), np.int64),
        ("ing_episode_reward", (1,), np.float64),
        ("ing_has_reward", (1,), np.int64),
    )


# slab-only scalar words of batch_slot_spec that the frame grammar
# already carries (header seq / frame CRC) or that are trainer-written
_SLAB_ONLY_FIELDS = frozenset(
    ("req_n", "req_seq", "req_crc", "rsp_seq", "rsp_crc"))


def net_sample_response_spec(cfg: Config, action_dim: int, batch: int):
    """The preassembled-batch response payload, derived from the shm
    sample slab spec by dropping the slab-only request/seq/CRC words —
    the row fields stay byte-identical to what the shm plane's slab
    carries, so the two transports assemble the same learner batch."""
    return tuple(e for e in batch_slot_spec(cfg, action_dim, batch)
                 if e[0] not in _SLAB_ONLY_FIELDS)


def net_feedback_spec(batch: int):
    """Priority-feedback payload: up to ``batch`` (idx, priority) rows
    plus the sample-time FIFO pointer the shard's stale mask keys on and
    the loss scalar the shard's stats accumulate."""
    return (("fb_idxes", (batch,), np.int64),
            ("fb_prios", (batch,), np.float64),
            ("fb_ptr", (1,), np.int64),
            ("fb_loss", (1,), np.float64))


def net_stats_spec(num_fields: int):
    """Mass/stat gossip payload: the stats-slab value vector (the shm
    plane's ``(seq, values, crc)`` slot with seq in the frame header and
    the CRC role taken by the frame CRC)."""
    return (("stats", (num_fields,), np.float64),)


def net_save_spec():
    """Drain-then-save control payload: snapshot path (length-prefixed
    bytes) + the routed-block / feedback expectations the shard must
    consume before writing (the shm plane's ctrl-queue tuple)."""
    return (("save_path", (SAVE_PATH_BYTES,), np.uint8),
            ("save_path_len", (1,), np.int64),
            ("save_blocks", (1,), np.int64),
            ("save_fb", (1,), np.int64))


def net_save_response_spec():
    """Save reply payload: the shard's snapshot meta as JSON bytes."""
    return (("meta_json", (SAVE_META_BYTES,), np.uint8),
            ("meta_len", (1,), np.int64))


def put_json(views: dict, field: str, len_field: str, obj) -> None:
    """Serialise ``obj`` into a bounded uint8 payload region."""
    raw = json.dumps(obj).encode()
    cap = views[field].shape[0]
    if len(raw) > cap:
        raise ValueError(
            f"{field}: {len(raw)} bytes exceeds the {cap}-byte region")
    views[field][:len(raw)] = np.frombuffer(raw, np.uint8)
    views[len_field][0] = len(raw)


def get_json(views: dict, field: str, len_field: str):
    """Inverse of :func:`put_json`."""
    n = int(views[len_field][0])
    return json.loads(bytes(views[field][:n]).decode())


def put_str(views: dict, field: str, len_field: str, s: str) -> None:
    raw = s.encode()
    cap = views[field].shape[0]
    if len(raw) > cap:
        raise ValueError(
            f"{field}: {len(raw)} bytes exceeds the {cap}-byte region")
    views[field][:len(raw)] = np.frombuffer(raw, np.uint8)
    views[len_field][0] = len(raw)


def get_str(views: dict, field: str, len_field: str) -> str:
    n = int(views[len_field][0])
    return bytes(views[field][:n]).decode()


def layout_token(cfg: Config, action_dim: int) -> int:
    """Geometry fingerprint of the derived frame layouts, exchanged in
    the HELLO handshake: a trainer and a shard built from drifted
    configs (different block geometry, batch size, leaf count) fail the
    attach instead of mis-framing every later message."""
    ing_n, _ = slot_layout(net_ingest_spec(cfg, action_dim))
    rsp_n, _ = slot_layout(
        net_sample_response_spec(cfg, action_dim, cfg.batch_size))
    return payload_crc32(
        (ing_n, rsp_n, cfg.batch_size, cfg.num_sequences, action_dim), [])


def max_net_frame_bytes(cfg: Config, action_dim: int) -> int:
    """The FrameReader desync bound for this geometry: the largest
    legitimate frame (ingest or sample response) plus header/CRC/framing
    slack — layout-derived so the bound stays tight at every scale."""
    ing_n, _ = slot_layout(net_ingest_spec(cfg, action_dim))
    rsp_n, _ = slot_layout(
        net_sample_response_spec(cfg, action_dim, cfg.batch_size))
    biggest = max(ing_n, rsp_n, SAVE_META_BYTES + SAVE_PATH_BYTES)
    return biggest + HEADER_WORDS * 8 + 64


def ingest_shape_header(views: dict) -> Tuple[int, int, int]:
    """The shm metadata-queue shape tuple of a decoded ingest frame."""
    return (int(views["ing_k"][0]), int(views["ing_n_obs"][0]),
            int(views["ing_n_steps"][0]))
