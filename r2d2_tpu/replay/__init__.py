from r2d2_tpu.replay.sum_tree import SumTree
from r2d2_tpu.replay.block import Block, LocalBuffer
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
