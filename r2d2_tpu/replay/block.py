"""Block wire format: the actor → replay unit of experience.

Capability-parity with the reference's ``Block`` dataclass (worker.py:23-36)
and ``LocalBuffer`` (worker.py:395-497): an episode is cut into blocks of up
to ``block_length`` env steps; each block carries the observation stream
*including a burn-in prefix carried over from the previous block*, per-step
n-step returns and bootstrap discounts (terminality encoded as a zero
discount tail instead of done flags), stored recurrent states at sequence
starts, per-sequence window sizes, and actor-computed initial priorities.

Intentional divergence from the reference: stored hidden states are recorded
at each sequence's **burn-in start** (the R2D2 paper's scheme).  The
reference samples them at ``i * learning_steps`` into the buffer
(worker.py:461), which for blocks whose carried burn-in prefix is shorter
than ``burn_in_steps`` (i.e. the first block of every episode) feeds a state
recorded *after* the burn-in window it is unrolled over.  The reference's
indexing is available as a compat switch
(``Config.stored_hidden_mode="seq_start"``) so the divergence can be A/B'd;
the two schemes coincide whenever the carried prefix is full.
"""
from __future__ import annotations

import dataclasses
import math
import time
import zlib
from typing import List, Optional, Tuple

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.utils.math import mixed_td_errors, n_step_gamma_tail, n_step_return


@dataclasses.dataclass
class Block:
    """One actor-produced chunk of experience.

    Array shapes (S = env steps in the block, P = burn-in prefix length,
    K = number of sequences, A = action dim, layers/H = LSTM geometry):

    - ``obs``:          (P + S + 1, *obs_shape) uint8 — includes next-obs tail
    - ``last_action``:  (P + S + 1, A) bool (one-hot)
    - ``last_reward``:  (P + S + 1,) float32
    - ``action``:       (S,) uint8
    - ``n_step_reward``:(S,) float32
    - ``n_step_gamma``: (S,) float32 — 0 tail encodes terminal
    - ``hidden``:       (K, 2, layers, H) float32 — state at burn-in start
    - ``burn_in_steps``/``learning_steps``/``forward_steps``: (K,) uint8
    """
    obs: np.ndarray
    last_action: np.ndarray
    last_reward: np.ndarray
    action: np.ndarray
    n_step_reward: np.ndarray
    n_step_gamma: np.ndarray
    hidden: np.ndarray
    num_sequences: int
    burn_in_steps: np.ndarray
    learning_steps: np.ndarray
    forward_steps: np.ndarray
    # block lineage (telemetry/tracing.py, docs/OBSERVABILITY.md):
    # ``cut_ts`` is the wall-clock time the block was cut (always
    # stamped — one time.time() per block, which feeds the
    # pipeline.block_age_at_train_s decomposition); ``trace_id`` is the
    # nonzero flow id of an armed capture window (0 in steady state —
    # the capture flag that keeps disarmed overhead at zero);
    # ``member_id`` is the population member that produced the block
    # (league/population.py — stamped by the fleet-side producer, 0 for
    # non-population runs), so per-member experience flow is countable
    # at every hop (replay stats, population.* telemetry)
    cut_ts: float = 0.0
    trace_id: int = 0
    member_id: int = 0


def assemble_block(cfg: Config, *, obs: np.ndarray, last_action: np.ndarray,
                   last_reward: np.ndarray, hidden_stream: np.ndarray,
                   actions: np.ndarray, rewards: np.ndarray,
                   qvals: np.ndarray, prefix: int, size: int, done: bool
                   ) -> Tuple[Block, np.ndarray]:
    """The block math shared by :class:`LocalBuffer` (list-backed) and
    :class:`VectorLocalBuffer` (preallocated-array-backed): per-sequence
    window sizes (worker.py:471-474), stored-hidden selection, n-step
    targets, and the actor-side initial priorities (worker.py:477-483 —
    plain max-Q n-step TD, no value rescale / double-Q, replicating the
    reference's asymmetry vs the learner).

    ``obs``/``last_action``/``last_reward``/``hidden_stream`` are the full
    (prefix + size + 1)-entry streams; ``qvals`` is (size+1, A) with the
    bootstrap value (zeros when ``done``) in the last row.  The arrays are
    stored in the Block as-is — callers reusing backing storage must pass
    copies.
    """
    L, n = cfg.learning_steps, cfg.forward_steps
    c = prefix
    num_sequences = math.ceil(size / L)

    gamma_tail = n_step_gamma_tail(size, n, cfg.gamma, done)
    nstep_r = n_step_return(np.asarray(rewards, np.float32), n, cfg.gamma)

    # per-sequence window sizes (worker.py:471-474 invariants)
    seq_ids = np.arange(num_sequences)
    burn_in = np.minimum(seq_ids * L + c, cfg.burn_in_steps).astype(np.uint8)
    learning = np.minimum(L, size - seq_ids * L).astype(np.uint8)
    forward = np.minimum(n, size + 1 - np.cumsum(learning)).astype(np.uint8)
    assert forward[-1] == 1 and burn_in[0] == min(c, cfg.burn_in_steps)

    # recurrent state at each sequence's burn-in start (paper-correct; see
    # module docstring for the divergence from worker.py:461), or the
    # reference's own indexing under stored_hidden_mode="seq_start"
    if cfg.stored_hidden_mode == "seq_start":
        hidden_idx = seq_ids * L
    else:
        hidden_idx = c + seq_ids * L - burn_in.astype(np.int64)
    hiddens = np.asarray(hidden_stream[hidden_idx], np.float32)

    max_forward = min(size, n)
    max_q = qvals[max_forward:size + 1].max(axis=1)
    max_q = np.pad(max_q, (0, max_forward - 1), mode="edge")
    taken_q = qvals[np.arange(size), actions]
    td = np.abs(nstep_r + gamma_tail * max_q - taken_q).astype(np.float32)
    priorities = np.zeros(cfg.seqs_per_block, np.float32)
    priorities[:num_sequences] = mixed_td_errors(td, learning)

    block = Block(
        obs=obs, last_action=last_action, last_reward=last_reward,
        action=actions, n_step_reward=nstep_r, n_step_gamma=gamma_tail,
        hidden=hiddens, num_sequences=num_sequences,
        burn_in_steps=burn_in, learning_steps=learning,
        forward_steps=forward,
        cut_ts=time.time(),   # block-lineage birth stamp (Block docstring)
    )
    return block, priorities


# --------------------------------------------------------------------------
# block <-> shared-memory slot (the process-fleet transport's wire format)
# --------------------------------------------------------------------------

def block_slot_spec(cfg: Config, action_dim: int):
    """(name, max shape, dtype) of ONE preallocated block slot — the wire
    format of the shared-memory block channel (parallel/actor_procs.py).

    DERIVED from the replay ring's own layout (replay_buffer._data_spec /
    _count_spec with the slot axis dropped) plus the actor-computed
    initial priorities, so the wire format cannot drift from the ring a
    future field/dtype change lands in: a fleet subprocess serialises a
    Block with a handful of vectorised array copies and the trainer's
    ingest reconstructs zero-copy views — bulk experience never goes
    through pickle."""
    # lazy import: replay_buffer imports this module (Block)
    from r2d2_tpu.replay.replay_buffer import _count_spec, _data_spec

    per_block = tuple((name, shape[1:], dtype)
                      for name, shape, dtype in _data_spec(cfg, action_dim))
    # of the accounting arrays, only the per-sequence windows travel;
    # first_burn_in / block_learning_total are derived at add() time
    windows = tuple((name, shape[1:], dtype)
                    for name, shape, dtype in _count_spec(cfg)
                    if name in ("burn_in_steps", "learning_steps",
                                "forward_steps"))
    return per_block + windows + (
        ("priorities", (cfg.seqs_per_block,), np.float32),
        # block lineage (telemetry/tracing.py): the cut wall-clock stamp
        # (always written — feeds the pipeline.* latency histograms), the
        # capture-window flow id (0 when no capture is armed), and the
        # population member id (league/population.py; 0 outside a
        # population run).  Deliberately OUTSIDE the slot CRC: telemetry,
        # not experience — a garbled stamp must never cost a valid block
        ("cut_ts", (1,), np.float64),
        ("trace_id", (1,), np.int64),
        ("member_id", (1,), np.int64),
        # integrity word: CRC32 over the slot's used payload bytes + the
        # shape header, written LAST by the producer.  A torn write (a
        # producer SIGKILLed mid-slot) or garbled slab shows up as a
        # mismatch at ingest, where the trainer drops the block instead of
        # feeding torn experience to the learner (actor_procs.ingest_once).
        ("crc32", (1,), np.uint32),)


def batch_slot_spec(cfg: Config, action_dim: int, batch_size: int):
    """(name, shape, dtype) of ONE preassembled sample-batch RPC slot —
    the wire format of the sharded replay plane's stratified sample RPC
    (parallel/replay_shards.py): request words in, a preassembled batch
    back, over one preallocated shared-memory slab per shard.

    The row fields mirror — by name, shape and dtype — the batch
    ``ReplayBuffer.sample_batch`` assembles, so the trainer-side
    concatenation of K shard responses is byte-compatible with the
    in-process K=1 batch and the learner never special-cases the
    transport.  ``prios`` travel RAW (``td**alpha`` leaf values, f64)
    instead of IS weights: normalisation by the minimum sampled priority
    happens across ALL shards' rows at once (the K=1 scheme), and
    ``idxes`` are shard-LOCAL leaf indices the trainer offsets into the
    global leaf space.  Rows are sized for the full ``batch_size`` —
    under skewed priority mass one shard can legitimately serve the
    whole batch.

    Request region (trainer-written): ``req_n`` rows wanted, ``req_seq``
    (a retry supersedes older tokens), ``req_crc`` written last.
    Response region (shard-written): the rows above plus ``rsp_n`` rows
    actually served (< req_n only when the shard drained empty under a
    stale mass vector), the shard's local FIFO ``rsp_block_ptr`` (the
    priority-feedback stale mask), ``rsp_env_steps``, ``rsp_seq`` and
    ``rsp_crc`` — written LAST, the block channel's torn-write
    discipline."""
    B, T, L = batch_size, cfg.seq_len, cfg.learning_steps
    return (
        ("obs", (B, T, *cfg.stored_obs_shape), np.uint8),
        ("last_action", (B, T, action_dim), np.float32),
        ("last_reward", (B, T), np.float32),
        ("hidden", (B, 2, cfg.lstm_layers, cfg.hidden_dim), np.float32),
        ("action", (B, L), np.int32),
        ("n_step_reward", (B, L), np.float32),
        ("n_step_gamma", (B, L), np.float32),
        ("burn_in", (B,), np.int32),
        ("learning", (B,), np.int32),
        ("forward", (B,), np.int32),
        ("prios", (B,), np.float64),
        ("idxes", (B,), np.int64),
        # block-lineage ages per served row (seconds since cut / since
        # ring add, measured shard-side at gather time — the shard owns
        # the stamps; telemetry/tracing.py).  Outside BATCH_ROW_FIELDS,
        # hence outside the response CRC: telemetry, not experience
        ("ages", (B, 2), np.float32),
        ("req_n", (1,), np.int64),
        ("req_seq", (1,), np.int64),
        ("req_crc", (1,), np.uint32),
        ("rsp_n", (1,), np.int64),
        ("rsp_block_ptr", (1,), np.int64),
        ("rsp_env_steps", (1,), np.int64),
        ("rsp_seq", (1,), np.int64),
        ("rsp_crc", (1,), np.uint32),
    )


# the response-payload fields a sample-RPC CRC covers, in slot order —
# shared by the shard-side writer and the trainer-side verifier
# (parallel/replay_shards.py) so the two can never drift
BATCH_ROW_FIELDS = ("obs", "last_action", "last_reward", "hidden",
                    "action", "n_step_reward", "n_step_gamma", "burn_in",
                    "learning", "forward", "prios", "idxes")


# The ONE CRC convention every shm channel shares (the block channel here,
# the act slab in parallel/inference_service.py, the sharded replay
# plane's sample slab in parallel/replay_shards.py): int64 header words
# first, then the payload arrays in their declared order, masked to 32
# bits.  The transport modules must import it rather than restate it —
# enforced by the `wire-format` graftlint rule
# (r2d2_tpu/analysis/wire_format.py).
CRC_MASK = 0xFFFFFFFF


def payload_crc32(header, arrays) -> int:
    """CRC32 over ``header`` (a sequence of ints, hashed as int64 words —
    covering the shape/token metadata so a header/payload mismatch is
    caught too) followed by ``arrays`` (numpy views, hashed in order).

    Arrays hash through the buffer protocol, NOT ``.tobytes()``: the
    byte stream (and therefore the CRC) is identical, but tobytes
    copies the whole payload first — at the sharded replay plane's
    batch-response scale (tens of MB per RPC) that copy cost as much
    as the hash itself.  Non-contiguous views still pay one compaction
    copy (``ascontiguousarray``)."""
    c = zlib.crc32(np.asarray(list(header), np.int64).tobytes())
    for a in arrays:
        c = zlib.crc32(memoryview(np.ascontiguousarray(a)).cast("B"), c)
    return c & CRC_MASK


# (field, used-length selector) pairs of the payload a slot CRC covers —
# shared by the producer (write_block) and the verifying consumer so the
# two can never drift
_CRC_FIELDS = (("obs", "n_obs"), ("last_action", "n_obs"),
               ("last_reward", "n_obs"), ("action", "n_steps"),
               ("n_step_reward", "n_steps"), ("n_step_gamma", "n_steps"),
               ("hidden", "k"), ("burn_in_steps", "k"),
               ("learning_steps", "k"), ("forward_steps", "k"))


def slot_crc(views: dict, k: int, n_obs: int, n_steps: int) -> int:
    """CRC32 of a block slot's used payload bytes (plus the shape header,
    so a header/payload mismatch is also caught)."""
    used = dict(k=k, n_obs=n_obs, n_steps=n_steps)
    return payload_crc32(
        (k, n_obs, n_steps),
        [views[name][:used[sel]] for name, sel in _CRC_FIELDS]
        + [views["priorities"]])


def slot_layout(spec) -> Tuple[int, dict]:
    """(slot_nbytes, {name: byte offset}) for a :func:`block_slot_spec`,
    every array 8-byte aligned so the shm views are properly aligned for
    their dtypes."""
    offsets, off = {}, 0
    for name, shape, dtype in spec:
        off = (off + 7) & ~7
        offsets[name] = off
        off += int(np.prod(shape)) * np.dtype(dtype).itemsize
    return (off + 7) & ~7, offsets


def slot_views(buf, spec, offsets: dict, slot_nbytes: int, slot: int) -> dict:
    """Numpy views of slot ``slot`` inside a shared-memory buffer — the
    same call serves the producer (writes) and the consumer (zero-copy
    reads)."""
    base = slot * slot_nbytes
    return {name: np.ndarray(shape, dtype=dtype, buffer=buf,
                             offset=base + offsets[name])
            for name, shape, dtype in spec}


def write_block(views: dict, block: Block, priorities: np.ndarray
                ) -> Tuple[int, int, int]:
    """Serialise ``block`` into a slot's views.  Returns the shape header
    ``(num_sequences, n_obs, n_steps)`` — the only thing that crosses the
    metadata queue (a tuple of ints; the arrays travel through shm)."""
    k = block.num_sequences
    n_obs = block.obs.shape[0]
    n_steps = block.action.shape[0]
    views["obs"][:n_obs] = block.obs
    views["last_action"][:n_obs] = block.last_action
    views["last_reward"][:n_obs] = block.last_reward
    views["action"][:n_steps] = block.action
    views["n_step_reward"][:n_steps] = block.n_step_reward
    views["n_step_gamma"][:n_steps] = block.n_step_gamma
    views["hidden"][:k] = block.hidden
    views["burn_in_steps"][:k] = block.burn_in_steps
    views["learning_steps"][:k] = block.learning_steps
    views["forward_steps"][:k] = block.forward_steps
    views["priorities"][:] = priorities
    # lineage stamps travel outside the CRC (block_slot_spec) — always
    # written so a recycled slot can never leak its previous block's id
    views["cut_ts"][0] = block.cut_ts
    views["trace_id"][0] = block.trace_id
    views["member_id"][0] = block.member_id
    # CRC last: a slot is only valid once its integrity word matches
    views["crc32"][0] = slot_crc(views, k, n_obs, n_steps)
    return k, n_obs, n_steps


def read_block(views: dict, k: int, n_obs: int, n_steps: int
               ) -> Tuple[Block, np.ndarray]:
    """Reconstruct ``(block, priorities)`` from a slot's views — zero
    copy: the Block fields alias the shm slab, valid until the slot is
    released back to the free list (ReplayBuffer.add copies them into the
    ring / stages them to the device before that happens)."""
    block = Block(
        obs=views["obs"][:n_obs],
        last_action=views["last_action"][:n_obs],
        last_reward=views["last_reward"][:n_obs],
        action=views["action"][:n_steps],
        n_step_reward=views["n_step_reward"][:n_steps],
        n_step_gamma=views["n_step_gamma"][:n_steps],
        hidden=views["hidden"][:k],
        num_sequences=k,
        burn_in_steps=views["burn_in_steps"][:k],
        learning_steps=views["learning_steps"][:k],
        forward_steps=views["forward_steps"][:k],
        cut_ts=float(views["cut_ts"][0]),
        trace_id=int(views["trace_id"][0]),
        member_id=int(views["member_id"][0]),
    )
    return block, views["priorities"]


class LocalBuffer:
    """Actor-side accumulator that cuts episodes into Blocks.

    Mirrors the reference's LocalBuffer lifecycle (worker.py:413-497):
    ``reset`` at episode start, ``add`` once per env step, ``finish`` at
    episode end / block boundary / episode-step cap.  ``finish`` retains the
    trailing ``burn_in_steps + 1`` entries so the next block of the same
    episode starts with a warm burn-in prefix.
    """

    def __init__(self, cfg: Config, action_dim: int):
        self.cfg = cfg
        self.action_dim = action_dim
        self.hidden_shape = (2, cfg.lstm_layers, cfg.hidden_dim)
        self.curr_burn_in_steps = 0
        self.size = 0

    def __len__(self) -> int:
        return self.size

    def reset(self, init_obs: np.ndarray) -> None:
        noop_one_hot = np.zeros(self.action_dim, dtype=bool)
        noop_one_hot[0] = True
        self.obs_buffer: List[np.ndarray] = [np.asarray(init_obs, dtype=np.uint8)]
        self.last_action_buffer: List[np.ndarray] = [noop_one_hot]
        self.last_reward_buffer: List[float] = [0.0]
        self.hidden_buffer: List[np.ndarray] = [np.zeros(self.hidden_shape, np.float32)]
        self.action_buffer: List[int] = []
        self.reward_buffer: List[float] = []
        self.qval_buffer: List[np.ndarray] = []
        self.curr_burn_in_steps = 0
        self.size = 0
        self.sum_reward = 0.0
        self.done = False

    def add(self, action: int, reward: float, next_obs: np.ndarray,
            q_value: np.ndarray, hidden: np.ndarray) -> None:
        """Record one env step.  ``hidden`` is the recurrent state *after*
        consuming the obs that produced ``q_value`` (so buffers stay aligned:
        entry i is the state with which obs i is consumed)."""
        one_hot = np.zeros(self.action_dim, dtype=bool)
        one_hot[action] = True
        self.action_buffer.append(action)
        self.reward_buffer.append(reward)
        self.obs_buffer.append(np.asarray(next_obs, dtype=np.uint8))
        self.last_action_buffer.append(one_hot)
        self.last_reward_buffer.append(reward)
        self.hidden_buffer.append(np.asarray(hidden, np.float32).reshape(self.hidden_shape))
        self.qval_buffer.append(np.asarray(q_value, np.float32).reshape(self.action_dim))
        self.sum_reward += reward
        self.size += 1

    def finish(self, last_qval: Optional[np.ndarray] = None
               ) -> Tuple[Block, np.ndarray, Optional[float]]:
        """Close the current chunk into a Block.

        ``last_qval=None`` means the episode terminated (bootstrap discount
        tail is zeroed); otherwise it is the Q-value at the final obs, used to
        bootstrap a truncated chunk (worker.py:443-453).

        Returns ``(block, per-leaf priorities, episode_reward or None)``.
        """
        cfg = self.cfg
        assert 0 < self.size <= cfg.block_length
        size = self.size
        c = self.curr_burn_in_steps
        self.done = last_qval is None

        qvals = list(self.qval_buffer)
        if self.done:
            qvals.append(np.zeros(self.action_dim, np.float32))
        else:
            qvals.append(np.asarray(last_qval, np.float32).reshape(self.action_dim))
        qvals = np.stack(qvals)                       # (size+1, A)

        block, priorities = assemble_block(
            cfg,
            obs=np.stack(self.obs_buffer),
            last_action=np.stack(self.last_action_buffer),
            last_reward=np.asarray(self.last_reward_buffer, np.float32),
            hidden_stream=np.stack(self.hidden_buffer),
            actions=np.asarray(self.action_buffer, np.uint8),
            rewards=np.asarray(self.reward_buffer, np.float32),
            qvals=qvals, prefix=c, size=size, done=self.done)
        episode_reward = self.sum_reward if self.done else None

        # carry the burn-in prefix into the next block (worker.py:486-493)
        keep = cfg.burn_in_steps + 1
        self.obs_buffer = self.obs_buffer[-keep:]
        self.last_action_buffer = self.last_action_buffer[-keep:]
        self.last_reward_buffer = self.last_reward_buffer[-keep:]
        self.hidden_buffer = self.hidden_buffer[-keep:]
        self.action_buffer.clear()
        self.reward_buffer.clear()
        self.qval_buffer.clear()
        self.curr_burn_in_steps = len(self.obs_buffer) - 1
        self.size = 0

        return block, priorities, episode_reward


class VectorLocalBuffer:
    """Batched LocalBuffer: one preallocated array set shared by N lanes.

    The per-env-step host cost of N :class:`LocalBuffer`\\ s (5 list appends
    + 2 small array builds per lane per step — the reference's per-actor
    hot loop, worker.py:426-435) becomes a handful of vectorized
    fancy-indexed writes per *batched* step, one numpy op per field for
    ALL lanes at once.  Blocks and priorities are bit-identical to the
    list-backed implementation (shared :func:`assemble_block`; oracle test
    in tests/test_local_buffer.py).

    Lifecycle per lane mirrors LocalBuffer: ``reset_lane`` at episode
    start, one ``add_batch`` row per env step, ``finish(i)`` at episode
    end / block boundary / step cap (the trailing ``burn_in_steps + 1``
    stream entries are retained in place as the next block's warm
    prefix).
    """

    def __init__(self, cfg: Config, action_dim: int, num_lanes: int):
        self.cfg = cfg
        self.action_dim = action_dim
        N, B = num_lanes, cfg.block_length
        cap = cfg.burn_in_steps + B + 1  # obs-stream entries per block max
        self.cap = cap
        self.obs = np.zeros((N, cap, *cfg.stored_obs_shape), np.uint8)
        self.last_action = np.zeros((N, cap, action_dim), bool)
        self.last_reward = np.zeros((N, cap), np.float32)
        self.hidden = np.zeros(
            (N, cap, 2, cfg.lstm_layers, cfg.hidden_dim), np.float32)
        self.action = np.zeros((N, B), np.uint8)
        self.reward = np.zeros((N, B), np.float32)
        self.qval = np.zeros((N, B + 1, action_dim), np.float32)
        self.prefix = np.zeros(N, np.int64)      # carried burn-in length c
        self.size = np.zeros(N, np.int64)        # env steps in current block
        self.sum_reward = np.zeros(N, np.float64)

    def sizes(self) -> np.ndarray:
        """Per-lane current block sizes (read-only view)."""
        return self.size

    # every array attribute, i.e. the buffer's whole mutable state — the
    # actor snapshot payload (VectorActor.snapshot)
    _STATE_FIELDS = ("obs", "last_action", "last_reward", "hidden",
                     "action", "reward", "qval", "prefix", "size",
                     "sum_reward")

    def snapshot(self) -> dict:
        """Copy of the full buffer state (all lanes) for the resumable
        actor snapshot — in-progress blocks and carried burn-in prefixes
        survive a preemption with it."""
        return {k: getattr(self, k).copy() for k in self._STATE_FIELDS}

    def load_snapshot(self, snap: dict) -> None:
        """Restore state captured by :meth:`snapshot` (same geometry)."""
        for k in self._STATE_FIELDS:
            dst = getattr(self, k)
            if dst.shape != snap[k].shape:
                raise ValueError(
                    f"local-buffer snapshot field {k!r} has shape "
                    f"{snap[k].shape}, expected {dst.shape}")
            dst[:] = snap[k]

    def reset_lane(self, i: int, init_obs: np.ndarray) -> None:
        self.obs[i, 0] = np.asarray(init_obs, np.uint8)
        self.last_action[i, 0] = False
        self.last_action[i, 0, 0] = True  # noop one-hot
        self.last_reward[i, 0] = 0.0
        self.hidden[i, 0] = 0.0
        self.prefix[i] = 0
        self.size[i] = 0
        self.sum_reward[i] = 0.0

    def add_batch(self, idx: np.ndarray, actions: np.ndarray,
                  rewards: np.ndarray, next_obs: np.ndarray,
                  q: np.ndarray, hidden: np.ndarray) -> None:
        """Record one env step for every lane in ``idx``.

        ``next_obs``/``q``/``hidden`` are the full (N, ...) batched arrays
        (rows outside ``idx`` ignored); ``hidden`` rows are the state
        *after* consuming the obs that produced ``q`` (same alignment as
        LocalBuffer.add).
        """
        p = self.prefix[idx] + self.size[idx] + 1  # append position
        self.obs[idx, p] = next_obs[idx]
        self.last_action[idx, p] = False
        self.last_action[idx, p, actions[idx]] = True
        self.last_reward[idx, p] = rewards[idx]
        self.hidden[idx, p] = hidden[idx]
        s = self.size[idx]
        self.action[idx, s] = actions[idx]
        self.reward[idx, s] = rewards[idx]
        self.qval[idx, s] = q[idx]
        self.sum_reward[idx] += rewards[idx]
        self.size[idx] += 1

    def finish(self, i: int, last_qval: Optional[np.ndarray] = None
               ) -> Tuple[Block, np.ndarray, Optional[float]]:
        """Close lane ``i``'s current chunk into a Block (LocalBuffer.finish
        semantics: ``last_qval=None`` = terminated; returns
        ``(block, priorities, episode_reward or None)``)."""
        cfg = self.cfg
        size, c = int(self.size[i]), int(self.prefix[i])
        assert 0 < size <= cfg.block_length
        done = last_qval is None
        entries = c + size + 1

        qvals = self.qval[i, :size + 1].copy()
        qvals[size] = (np.zeros(self.action_dim, np.float32) if done
                       else np.asarray(last_qval, np.float32
                                       ).reshape(self.action_dim))

        block, priorities = assemble_block(
            cfg,
            # copies: the Block must not alias storage the next block reuses
            obs=self.obs[i, :entries].copy(),
            last_action=self.last_action[i, :entries].copy(),
            last_reward=self.last_reward[i, :entries].copy(),
            hidden_stream=self.hidden[i, :entries],  # fancy-indexed → copies
            actions=self.action[i, :size].copy(),
            rewards=self.reward[i, :size],
            qvals=qvals, prefix=c, size=size, done=done)
        episode_reward = float(self.sum_reward[i]) if done else None

        # retain the trailing burn_in+1 stream entries as the next block's
        # warm prefix (worker.py:486-493), in place
        keep = min(cfg.burn_in_steps + 1, entries)
        lo = entries - keep
        for arr in (self.obs, self.last_action, self.last_reward,
                    self.hidden):
            arr[i, :keep] = arr[i, lo:entries].copy()  # overlap-safe
        self.prefix[i] = keep - 1
        self.size[i] = 0

        return block, priorities, episode_reward
