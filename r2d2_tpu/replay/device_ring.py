"""Device-resident replay ring: replay data lives in HBM, not host RAM.

The reference's data plane moves every training batch across the host↔device
boundary (worker.py:330-342 `.to(device)` per step).  At flagship shapes that
is ~40 MB per batch — the dominant system cost on any real interconnect
(PCIe, and catastrophically so on a tunneled chip).  The TPU-first redesign
inverts the flow:

- Each experience block crosses H2D **once**, when the actor produces it
  (~3 MB, at block-production rate — orders of magnitude less traffic than
  per-batch staging).
- The ring arrays (same layout as the host ring, replay_buffer.py) live on
  the device; batch assembly is an in-graph gather executed at HBM
  bandwidth inside the jitted train step.
- The host keeps what it is good at: the sum-tree, priorities, ring
  accounting, and stale-index masking.  Only tiny index/weight arrays cross
  per batch.

Writes are donated ``dynamic_update_index_in_dim`` updates — the ring is
updated in place on device, never reallocated.

Capacity envelope — two mesh layouts (``layout=``):

- ``"replicated"``: every device holds the full ring; gathers need no
  collectives, capacity is bounded by ONE chip's HBM.
- ``"dp"``: the slot axis shards over the ``dp`` mesh axis, so capacity
  scales with the mesh — e.g. the flagship 2M-transition buffer
  (~15.5 GB) does not fit a single v5e chip (16 GB) next to params, but
  dp=8 holds ~2 GB/chip.  The ReplayBuffer walks ring slots round-robin
  across the dp groups' contiguous slot slabs (every group fills from the
  first block; replay_buffer._phys_block), samples each group's batch
  rows from its own leaf slice (``SumTree.sample_range``, IS weights
  min-normalised across the whole batch), and maps physical slots back to
  the logical FIFO walk for stale-feedback masking.  The in-graph gather
  uses GLOBAL slot indices under GSPMD — the sharding table declares the
  slot-axis layout (``ring.*`` entries, parallel/sharding.py) and XLA
  partitions the gather; because each dp group's sampled rows reference
  only its own slab (sample_meta's per-group quota), the partitioned
  gather stays local in practice, with no hand-written shard_map.

Multi-host meshes compose the same layout across processes: each host
builds a dp ring over its LOCAL submesh (its dp groups' slabs) and fills
it with its own actors' experience; the learner stitches the per-host
device shards into the global ring view with zero data movement and
dispatches the same sharded super-step in SPMD lockstep
(``Learner._run_device_multihost``) — replay capacity scales with the
pod, batch bytes never touch host RAM or DCN.

CONCURRENCY CONTRACT: ``write`` and ``snapshot``+train-step-dispatch must
be externally serialised (the ReplayBuffer's lock is the coordination
point — add() writes under it, the learner samples indices and dispatches
under it).  Two reasons: a ``write`` donates the current handles, so a
racing dispatch could hand XLA a deleted buffer; and an index bundle
computed from the host accounting must be dispatched before any later
write lands, or the on-device gather could read a slot newer than the
indices describe.  Device-stream ordering guarantees the rest: dispatches
execute in order, so a bundle dispatched before a write reads pre-write
data.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.replay.block import Block

# data arrays mirrored on device; the count arrays (burn_in/learning/
# forward, first_burn_in) stay host-only — they are needed for *index
# computation*, which is host work.  Single-sourced from the sharding
# table's RING_DATA_KEYS so the ring's slabs and the table's `ring.*`
# sharding entries can never drift.
from r2d2_tpu.parallel.sharding import RING_DATA_KEYS as _DATA_KEYS


def _slot_shapes(cfg: Config, action_dim: int) -> Dict[str, Any]:
    MS, BL = cfg.max_block_steps, cfg.block_length
    K, layers, H = cfg.seqs_per_block, cfg.lstm_layers, cfg.hidden_dim
    return dict(
        obs=((MS, *cfg.stored_obs_shape), np.uint8),
        last_action=((MS, action_dim), np.bool_),
        last_reward=((MS,), np.float32),
        action=((BL,), np.uint8),
        n_step_reward=((BL,), np.float32),
        n_step_gamma=((BL,), np.float32),
        hidden=((K, 2, layers, H), np.float32),
    )


def _write_slot_fn(arrays: Dict[str, jnp.ndarray],
                   slot: Dict[str, jnp.ndarray], ptr: jnp.ndarray):
    return {k: jax.lax.dynamic_update_index_in_dim(arrays[k], slot[k], ptr,
                                                   axis=0)
            for k in arrays}


_write_slot = jax.jit(_write_slot_fn, donate_argnums=(0,))


def gather_batch(cfg: Config, arrays: Dict[str, jnp.ndarray],
                 ints: jnp.ndarray, is_weights: jnp.ndarray
                 ) -> Dict[str, jnp.ndarray]:
    """In-graph batch assembly — the device twin of
    ``ReplayBuffer.sample_batch`` (replay_buffer.py), same index arithmetic,
    same clamp invariant (stale/padded bytes can only occupy positions the
    loss masks out; see the INVARIANT note there).

    ``ints`` is (B, 6) int32: [block_idx, t0, seq_idx, burn_in, learning,
    forward] computed host-side under the buffer lock.
    """
    L, T = cfg.learning_steps, cfg.seq_len
    block_idx, t0 = ints[:, 0], ints[:, 1]
    seq_idx = ints[:, 2]

    time_idx = jnp.minimum(t0[:, None] + jnp.arange(T),
                           cfg.max_block_steps - 1)          # (B, T)
    bcol = block_idx[:, None]
    widx = jnp.minimum(seq_idx[:, None] * L + jnp.arange(L),
                       cfg.block_length - 1)                 # (B, L)
    return dict(
        obs=arrays["obs"][bcol, time_idx],
        last_action=arrays["last_action"][bcol, time_idx].astype(jnp.float32),
        last_reward=arrays["last_reward"][bcol, time_idx],
        hidden=arrays["hidden"][block_idx, seq_idx],
        action=arrays["action"][bcol, widx].astype(jnp.int32),
        n_step_reward=arrays["n_step_reward"][bcol, widx],
        n_step_gamma=arrays["n_step_gamma"][bcol, widx],
        burn_in=ints[:, 3],
        learning=ints[:, 4],
        forward=ints[:, 5],
        is_weights=is_weights,
    )


def resolve_layout(cfg: Config, mesh, need_bytes: int,
                   cap_bytes: Optional[int]) -> str:
    """Resolve ``cfg.device_ring_layout`` to a concrete mesh layout.

    ``"auto"`` shards the ring over dp exactly when the full ring would
    not fit one device's HBM budget (80%, leaving headroom for params,
    activations and staged slots) AND the shapes allow it (num_blocks and
    batch_size divisible by dp).  Explicit ``"dp"`` raises when the
    shapes or mesh make it impossible — silent fallback would defeat the
    reason the user asked for sharding (review: a knob that validates but
    does nothing).
    """
    requested = cfg.device_ring_layout
    has_dp = (mesh is not None and "dp" in mesh.axis_names
              and mesh.shape["dp"] > 1)
    if not has_dp:
        if requested == "dp":
            raise ValueError(
                "device_ring_layout='dp' needs a mesh with a dp axis > 1")
        return "replicated"
    dp = mesh.shape["dp"]
    can_dp = (cfg.num_blocks % dp == 0) and (cfg.batch_size % dp == 0)
    if requested == "dp":
        if not can_dp:
            raise ValueError(
                f"device_ring_layout='dp' needs num_blocks "
                f"({cfg.num_blocks}) and batch_size ({cfg.batch_size}) "
                f"divisible by dp={dp}")
        return "dp"
    if requested == "replicated":
        return "replicated"
    # "auto": replicate if it fits, shard if it must and can
    if can_dp and cap_bytes is not None and need_bytes > 0.8 * cap_bytes:
        return "dp"
    return "replicated"


def _write_per_fn(prios: jnp.ndarray, seq_meta: jnp.ndarray,
                  first_burn: jnp.ndarray, prios_slot: jnp.ndarray,
                  meta_slot: jnp.ndarray, first_val: jnp.ndarray,
                  slot: jnp.ndarray, K: int):
    """Donated in-place write of one block's PER leaves + sampling
    metadata (in-graph-PER mode, see :class:`DeviceRing`)."""
    prios = jax.lax.dynamic_update_slice(prios, prios_slot, (slot * K,))
    seq_meta = jax.lax.dynamic_update_index_in_dim(seq_meta, meta_slot,
                                                   slot, 0)
    first_burn = jax.lax.dynamic_update_index_in_dim(
        first_burn, first_val, slot, 0)
    return prios, seq_meta, first_burn


class DeviceRing:
    """Owns the device-resident ring arrays and their write path.

    ``placement`` may be a Device (single-chip) or a Sharding; use
    ``table=..., layout=...`` (a :class:`~r2d2_tpu.parallel.sharding.
    ShardingTable`) instead to derive it — the ring's slot-axis layout is
    a sharding-table decision (``ring.*`` / ``per.*`` entries), not a
    local heuristic.  ``layout="dp"`` additionally sets ``num_groups`` —
    the replay buffer then walks ring slots round-robin across the dp
    groups' slot ranges and samples each group's batch rows from its own
    slots.
    """

    def __init__(self, cfg: Config, action_dim: int,
                 placement: Optional[Any] = None,
                 table: Optional[Any] = None, layout: str = "replicated"):
        self.cfg = cfg
        self.action_dim = action_dim
        self.layout = layout
        self.num_groups = 1
        self.table = table
        self._slot_placement = placement  # incoming slots: device or repl.
        self._write_fn = _write_slot
        if table is not None:
            if layout == "dp":
                dp = table.mesh.shape["dp"]
                if cfg.num_blocks % dp:
                    raise ValueError(
                        f"device_ring_layout='dp' needs num_blocks "
                        f"({cfg.num_blocks}) divisible by dp={dp}")
                self.num_groups = dp
            sharding = table.ring_shardings(layout)
            placement = sharding["obs"]
            self._slot_placement = table.replicated()
            # pin the write's output layout: GSPMD would usually preserve
            # the donated input sharding, but with a dp-sharded slot axis
            # the partitioner must not be left free to re-lay-out the ring
            self._write_fn = jax.jit(
                _write_slot_fn, donate_argnums=(0,),
                out_shardings={k: sharding[k] for k in _DATA_KEYS})
        self._placement = placement
        NB = cfg.num_blocks
        self.blocks_per_group = NB // self.num_groups
        self._slot_shapes = _slot_shapes(cfg, action_dim)
        self.arrays = {
            k: self._put(np.zeros((NB, *shape), dtype))
            for k, (shape, dtype) in self._slot_shapes.items()}

        # --- in-graph PER state (cfg.in_graph_per) ---------------------
        # Leaf priorities (td**alpha; 0 = never-sampleable) plus the
        # per-sequence window metadata the in-graph sampler needs to
        # build index bundles without the host (learner/step.py
        # _in_graph_sample).  Replicated under a mesh; dp layout shards
        # the leaf axis with the ring slabs (the table's per.* entries).
        # The priorities handle is READ-WRITE from the learner's super
        # step (donated carry) AND written by actor block commits —
        # both sides mutate it only under the module's coordinating
        # lock, via take_prios()/put_prios() and commit_per().
        self._per_write = None
        if getattr(cfg, "in_graph_per", False):
            K = cfg.seqs_per_block
            if self.num_groups > 1:
                # dp layout: the PER leaves shard with the ring slabs —
                # the global stratified sampler reads them through GSPMD
                # (parallel/sharding.pjit_in_graph_per_super_step)
                psh = table.per_shardings("dp")
                self._per_prios = jax.device_put(
                    np.zeros((NB * K,), np.float32), psh["prios"])
                self._per_seq_meta = jax.device_put(
                    np.zeros((NB, K, 3), np.int32), psh["seq_meta"])
                self._per_first = jax.device_put(
                    np.zeros((NB,), np.int32), psh["first"])
                self._per_write = jax.jit(
                    functools.partial(_write_per_fn, K=K),
                    donate_argnums=(0, 1, 2),
                    out_shardings=(psh["prios"], psh["seq_meta"],
                                   psh["first"]))
            else:
                self._per_prios = self._put_slot(
                    np.zeros((NB * K,), np.float32))
                self._per_seq_meta = self._put_slot(
                    np.zeros((NB, K, 3), np.int32))
                self._per_first = self._put_slot(np.zeros((NB,), np.int32))
                self._per_write = jax.jit(
                    functools.partial(_write_per_fn, K=K),
                    donate_argnums=(0, 1, 2))

    def _put(self, x):
        return (jax.device_put(x, self._placement)
                if self._placement is not None else jax.device_put(x))

    def _put_slot(self, x):
        return (jax.device_put(x, self._slot_placement)
                if self._slot_placement is not None else jax.device_put(x))

    def nbytes(self) -> int:
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in self.arrays.values())

    def stage(self, block: Block) -> Dict[str, jnp.ndarray]:
        """Host-side half of a ring write: zero-pad the block to the fixed
        slot shape and start its H2D transfers.  Needs NO lock — staging
        touches no ring state, so callers should do it *outside* the
        coordinating lock (the transfers are the expensive part of a
        write; holding the lock across them would stall a concurrent
        sample+dispatch for the full H2D latency).

        Short blocks are zero-padded; the padding occupies exactly the
        positions the host ring would leave stale, which the sampling
        clamp invariant already guarantees are loss-masked.
        """
        slot = {}
        for k, (shape, dtype) in self._slot_shapes.items():
            arr = np.zeros(shape, dtype)
            src = getattr(block, k)
            if k == "hidden":
                arr[:block.num_sequences] = src
            else:
                arr[:src.shape[0]] = src
            slot[k] = self._put_slot(arr)
        return slot

    def commit(self, slot: Dict[str, jnp.ndarray], ptr: int) -> None:
        """Device-side half of a ring write: the donated in-place update
        into (physical) slot ``ptr``.  Caller holds the coordinating lock
        (see the module contract) — this is just one async dispatch, so
        the lock hold is microseconds."""
        self.arrays = self._write_fn(self.arrays, slot,
                                     jnp.asarray(ptr, jnp.int32))

    def write(self, block: Block, ptr: int) -> None:
        """stage + commit in one call (caller holds the coordinating
        lock — see the module contract)."""
        self.commit(self.stage(block), ptr)

    def snapshot(self) -> Dict[str, jnp.ndarray]:
        """Current ring handles, safe to pass to a train-step dispatch
        (caller holds the coordinating lock — see the module contract)."""
        return self.arrays

    # ------------------------------------------------- in-graph PER state
    def commit_per(self, slot: int, prios_alpha: np.ndarray,
                   meta: np.ndarray, first_burn: int) -> None:
        """Write one block's PER leaves (td**alpha, (K,) f32, zero-padded
        past num_sequences = unsampleable) + sampling metadata ((K, 3)
        i32 [burn, learn, fwd]; first_burn scalar).  Caller holds the
        coordinating lock."""
        self._per_prios, self._per_seq_meta, self._per_first = (
            self._per_write(
                self._per_prios, self._per_seq_meta, self._per_first,
                jnp.asarray(prios_alpha, jnp.float32),
                jnp.asarray(meta, jnp.int32),
                jnp.asarray(first_burn, jnp.int32),
                jnp.asarray(slot, jnp.int32)))

    def take_prios(self) -> jnp.ndarray:
        """The current priorities handle, for a super-step dispatch that
        DONATES it (the dispatch's returned handle must be stored back
        with :meth:`put_prios` before the lock is released)."""
        return self._per_prios

    def put_prios(self, handle: jnp.ndarray) -> None:
        self._per_prios = handle

    def per_meta(self) -> Dict[str, jnp.ndarray]:
        """Read-only sampling metadata handles for a dispatch."""
        return dict(seq_meta=self._per_seq_meta, first=self._per_first)

    def put_per_meta(self, seq_meta: jnp.ndarray,
                     first: jnp.ndarray) -> None:
        """Store back PER sampling-metadata handles returned by a dispatch
        that DONATED them.  Host-side commits (:meth:`commit_per`) write
        these in place, but the anakin fused loop (learner/anakin.py)
        writes them in-graph instead — its dispatches consume the current
        handles and this stores the returned generation, the same
        discipline as :meth:`take_prios`/:meth:`put_prios`."""
        self._per_seq_meta = seq_meta
        self._per_first = first
