"""Deterministic gridworld environment — the second jittable-env oracle.

The anakin transport's "fast path for free" claim (ROADMAP item 2, the
Podracer paper) is that ANY env expressible as jnp ops inherits the fused
on-device loop unchanged.  This module is the host-numpy half of the
proof: a tiny goal-seeking gridworld with the wrapped-ALE interface
(gymnasium 5-tuple, ``clone_state``/``restore_state``), whose device twin
:class:`~r2d2_tpu.envs.anakin.AnakinGridEnv` runs through the unchanged
fused program.  The parity contract mirrors the fake env's
(tests/test_anakin.py): given the same reset draws, every observation
byte, reward and truncation flag is bit-exact — the dynamics are integer
arithmetic plus the constants {0.0, 1.0}, so float equality is exact.

Dynamics (deliberately REACTIVE where the fake env is open-loop — the
fake env's phase advances regardless of the action, this one's state is
the action's consequence, so it exercises the policy-dependent
trajectory path the fake env cannot):

- A ``GRID x GRID`` board (:data:`GRID` = 4).  The agent occupies one
  cell (rendered as a bright 255 block), the goal another (a dim 128
  block) — both fully observable, so even an MLP torso can learn
  "move toward the goal".
- Actions 0/1/2/3 move up/down/left/right, clamped at the borders.
- Stepping onto the goal pays +1.0 and the goal relocates
  DETERMINISTICALLY to the next cell in scan order that is not the
  agent's (randomness only at reset, exactly the fake env's RNG
  discipline — which is what keeps the jax/numpy parity test's
  replay-the-reset-draws scheme sufficient).
- Episodes truncate after ``episode_len`` steps; ``terminated`` is
  always False (the anakin loop's truncation-only episode contract).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from r2d2_tpu.envs.fake import _Box, _Discrete

# board side; cells render as (H // GRID) x (W // GRID) pixel blocks
# (rows/cols past GRID * (dim // GRID) stay black — no divisibility
# requirement on the observation shape)
GRID = 4
AGENT_PIXEL = 255
GOAL_PIXEL = 128


def next_goal(goal: int, agent: int) -> int:
    """The deterministic goal relocation rule, shared with the jittable
    twin: the next cell in scan order, skipping the agent's cell."""
    g = (goal + 1) % (GRID * GRID)
    if g == agent:
        g = (g + 1) % (GRID * GRID)
    return g


class GridWorldEnv:
    """Deterministic-by-seed gridworld with the wrapped-ALE interface."""

    def __init__(self, obs_shape: Tuple[int, ...] = (84, 84, 1),
                 action_dim: int = 4, episode_len: int = 32, seed: int = 0):
        if action_dim != 4:
            raise ValueError(
                f"GridWorldEnv has exactly 4 move actions, got action_dim "
                f"{action_dim}")
        self._rng = np.random.default_rng(seed)
        self.observation_space = _Box(obs_shape, np.uint8)
        self.action_space = _Discrete(action_dim, self._rng)
        self.episode_len = episode_len
        self._agent = 0
        self._goal = 1
        self._t = 0

    def _obs(self) -> np.ndarray:
        h, w = self.observation_space.shape[:2]
        ch, cw = max(1, h // GRID), max(1, w // GRID)
        obs = np.zeros(self.observation_space.shape, np.uint8)
        for idx, val in ((self._goal, GOAL_PIXEL),
                         (self._agent, AGENT_PIXEL)):
            r, c = divmod(idx, GRID)
            obs[r * ch:(r + 1) * ch, c * cw:(c + 1) * cw] = val
        return obs

    def reset(self, *, seed: Optional[int] = None, **kwargs):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
            self.action_space._rng = self._rng  # fake.py reseed contract
        m = GRID * GRID
        self._agent = int(self._rng.integers(m))
        # goal drawn uniformly over the other m-1 cells
        d = int(self._rng.integers(m - 1))
        self._goal = d + (1 if d >= self._agent else 0)
        self._t = 0
        return self._obs(), {}

    def step(self, action: int):
        r, c = divmod(self._agent, GRID)
        a = int(action)
        dr = (-1, 1, 0, 0)[a]
        dc = (0, 0, -1, 1)[a]
        r = min(max(r + dr, 0), GRID - 1)
        c = min(max(c + dc, 0), GRID - 1)
        self._agent = r * GRID + c
        reached = self._agent == self._goal
        reward = 1.0 if reached else 0.0
        if reached:
            self._goal = next_goal(self._goal, self._agent)
        self._t += 1
        terminated = False
        truncated = self._t >= self.episode_len
        return self._obs(), reward, terminated, truncated, {}

    def clone_state(self) -> dict:
        return dict(rng=self._rng.bit_generator.state, agent=self._agent,
                    goal=self._goal, t=self._t)

    def restore_state(self, state: dict) -> None:
        self._rng.bit_generator.state = state["rng"]
        self._agent = int(state["agent"])
        self._goal = int(state["goal"])
        self._t = int(state["t"])

    def close(self):
        pass
