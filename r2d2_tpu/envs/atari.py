"""ALE Atari wrappers (gymnasium API).

Behavioral parity with the reference stack (environment.py:8-74): grayscale
obs, frameskip 4, no sticky actions, minimal action set, cv2 INTER_AREA warp
to 84×84, 1-30 random no-ops at reset, **no frame stacking** (the LSTM
supplies memory).  Differences are deliberate and TPU-native:

- NHWC uint8 observations ``(84, 84, 1)`` instead of the reference's CHW
  ``(1, 84, 84)`` (environment.py:52) — NHWC is XLA's native conv layout.
- gymnasium 5-tuple step API instead of the legacy gym 4-tuple
  (environment.py:29).

ALE is optional in this image; ``atari_available()`` gates it and
``create_env`` falls back to the fake env so every code path stays
runnable without ROMs.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.envs.fake import FakeAtariEnv, _Box

try:  # gymnasium is baked in; the ALE plugin may not be
    import gymnasium

    try:
        import ale_py  # noqa: F401  (registers ALE/* envs)

        _HAS_ALE = True
    except ImportError:
        _HAS_ALE = False
except ImportError:  # pragma: no cover
    gymnasium = None
    _HAS_ALE = False


def atari_available() -> bool:
    return _HAS_ALE


class SeedFirstReset:
    """Thread the lane seed into the wrapped env's FIRST ``reset``
    (gymnasium's seeding API is reset-time).  Without it only the noop
    RNG was seeded and the underlying ALE stream drew from OS entropy —
    real-Atari runs were irreproducible even with a fixed config seed.
    Subsequent resets deliberately pass no seed: reseeding every episode
    would replay the identical episode forever."""

    def __init__(self, env, seed: Optional[int]):
        self.env = env
        self._seed = seed

    def __getattr__(self, name):
        return getattr(self.env, name)

    def reset(self, **kwargs):
        if self._seed is not None:
            kwargs.setdefault("seed", self._seed)
            self._seed = None
        return self.env.reset(**kwargs)

    def step(self, action):
        return self.env.step(action)


class NoopResetEnv:
    """1..noop_max random no-op steps at reset (environment.py:8-35).

    Action 0 is asserted to be NOOP, matching the reference's guard
    (environment.py:17).
    """

    def __init__(self, env, noop_max: int = 30,
                 rng: Optional[np.random.Generator] = None):
        self.env = env
        self.noop_max = noop_max
        self.noop_action = 0
        self._rng = rng or np.random.default_rng()
        meanings = env.unwrapped.get_action_meanings()
        assert meanings[0] == "NOOP", meanings

    def __getattr__(self, name):
        return getattr(self.env, name)

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        noops = int(self._rng.integers(1, self.noop_max + 1))
        for _ in range(noops):
            obs, _, terminated, truncated, info = self.env.step(self.noop_action)
            if terminated or truncated:
                obs, info = self.env.reset(**kwargs)
        return obs, info

    def step(self, action):
        return self.env.step(action)


class WarpFrame:
    """cv2 INTER_AREA resize to (height, width, 1) uint8 (environment.py:39-63),
    NHWC instead of the reference's CHW."""

    def __init__(self, env, width: int = 84, height: int = 84):
        import cv2  # local import: cv2 is present in the image but heavy

        self._cv2 = cv2
        self.env = env
        self._width = width
        self._height = height
        self.observation_space = _Box((height, width, 1), np.uint8)

    def __getattr__(self, name):
        return getattr(self.env, name)

    def _warp(self, obs):
        obs = self._cv2.resize(obs, (self._width, self._height),
                               interpolation=self._cv2.INTER_AREA)
        return obs[..., None].astype(np.uint8)

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        return self._warp(obs), info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        return self._warp(obs), reward, terminated, truncated, info


class SpaceToDepth:
    """Fold 4×4 pixel blocks into channels: (H, W, C) uint8 →
    (H/4, W/4, 16C) uint8.

    Applied host-side at emission so the device never pays the relayout
    (the on-device transform of a training batch costs more than the conv
    it feeds — see NatureTorso docstring).  A ~7 KB numpy transpose per
    env step.
    """

    def __init__(self, env):
        self.env = env
        h, w, c = env.observation_space.shape
        self.observation_space = _Box((h // 4, w // 4, 16 * c), np.uint8)

    def __getattr__(self, name):
        return getattr(self.env, name)

    @staticmethod
    def fold(obs: np.ndarray) -> np.ndarray:
        h, w, c = obs.shape
        obs = obs.reshape(h // 4, 4, w // 4, 4, c)
        return np.ascontiguousarray(
            obs.transpose(0, 2, 1, 3, 4)).reshape(h // 4, w // 4, 16 * c)

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        return self.fold(obs), info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        return self.fold(obs), reward, terminated, truncated, info


def create_env(cfg: Config, noop_start: bool = True,
               seed: Optional[int] = None):
    """The single env factory (reference: environment.py:66-74).

    ``cfg.game_name == "Fake"`` or missing ALE → :class:`FakeAtariEnv`
    (emitting ``cfg.stored_obs_shape`` directly — the fake env's content
    is seed-derived noise either way).
    """
    if cfg.game_name == "Fake" or not _HAS_ALE:
        if cfg.game_name != "Fake":
            import warnings

            warnings.warn(
                f"ALE not installed; substituting FakeAtariEnv for "
                f"{cfg.game_name!r}", stacklevel=2)
        return FakeAtariEnv(obs_shape=cfg.stored_obs_shape, action_dim=4,
                            seed=0 if seed is None else seed)

    env = gymnasium.make(
        f"ALE/{cfg.game_name}-v5", obs_type="grayscale",
        frameskip=cfg.frameskip, repeat_action_probability=0.0,
        full_action_space=False)
    env = SeedFirstReset(env, seed)
    env = WarpFrame(env, width=cfg.obs_shape[1], height=cfg.obs_shape[0])
    if noop_start:
        env = NoopResetEnv(env, noop_max=cfg.noop_max,
                           rng=np.random.default_rng(seed))
    if cfg.obs_space_to_depth:
        env = SpaceToDepth(env)
    return env
