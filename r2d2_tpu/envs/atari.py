"""ALE Atari wrappers (gymnasium API).

Behavioral parity with the reference stack (environment.py:8-74): grayscale
obs, frameskip 4, no sticky actions, minimal action set, cv2 INTER_AREA warp
to 84×84, 1-30 random no-ops at reset, **no frame stacking** (the LSTM
supplies memory).  Differences are deliberate and TPU-native:

- NHWC uint8 observations ``(84, 84, 1)`` instead of the reference's CHW
  ``(1, 84, 84)`` (environment.py:52) — NHWC is XLA's native conv layout.
- gymnasium 5-tuple step API instead of the legacy gym 4-tuple
  (environment.py:29).

ALE is optional in this image; ``atari_available()`` gates it and
``create_env`` falls back to the fake env so every code path stays
runnable without ROMs.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.envs.fake import FakeAtariEnv

try:  # gymnasium is baked in; the ALE plugin may not be
    import gymnasium

    try:
        import ale_py  # noqa: F401  (registers ALE/* envs)

        _HAS_ALE = True
    except ImportError:
        _HAS_ALE = False
except ImportError:  # pragma: no cover
    gymnasium = None
    _HAS_ALE = False


def atari_available() -> bool:
    return _HAS_ALE


class NoopResetEnv:
    """1..noop_max random no-op steps at reset (environment.py:8-35).

    Action 0 is asserted to be NOOP, matching the reference's guard
    (environment.py:17).
    """

    def __init__(self, env, noop_max: int = 30,
                 rng: Optional[np.random.Generator] = None):
        self.env = env
        self.noop_max = noop_max
        self.noop_action = 0
        self._rng = rng or np.random.default_rng()
        meanings = env.unwrapped.get_action_meanings()
        assert meanings[0] == "NOOP", meanings

    def __getattr__(self, name):
        return getattr(self.env, name)

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        noops = int(self._rng.integers(1, self.noop_max + 1))
        for _ in range(noops):
            obs, _, terminated, truncated, info = self.env.step(self.noop_action)
            if terminated or truncated:
                obs, info = self.env.reset(**kwargs)
        return obs, info

    def step(self, action):
        return self.env.step(action)


class WarpFrame:
    """cv2 INTER_AREA resize to (height, width, 1) uint8 (environment.py:39-63),
    NHWC instead of the reference's CHW."""

    def __init__(self, env, width: int = 84, height: int = 84):
        import cv2  # local import: cv2 is present in the image but heavy

        self._cv2 = cv2
        self.env = env
        self._width = width
        self._height = height
        self.observation_space = type(
            "Box", (), {"shape": (height, width, 1), "dtype": np.uint8})()

    def __getattr__(self, name):
        return getattr(self.env, name)

    def _warp(self, obs):
        obs = self._cv2.resize(obs, (self._width, self._height),
                               interpolation=self._cv2.INTER_AREA)
        return obs[..., None].astype(np.uint8)

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        return self._warp(obs), info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        return self._warp(obs), reward, terminated, truncated, info


def create_env(cfg: Config, noop_start: bool = True,
               seed: Optional[int] = None):
    """The single env factory (reference: environment.py:66-74).

    ``cfg.game_name == "Fake"`` or missing ALE → :class:`FakeAtariEnv`.
    """
    if cfg.game_name == "Fake" or not _HAS_ALE:
        if cfg.game_name != "Fake":
            import warnings

            warnings.warn(
                f"ALE not installed; substituting FakeAtariEnv for "
                f"{cfg.game_name!r}", stacklevel=2)
        h, w = cfg.obs_shape[0], cfg.obs_shape[1]
        return FakeAtariEnv(obs_shape=(h, w, 1), action_dim=4,
                            seed=0 if seed is None else seed)

    env = gymnasium.make(
        f"ALE/{cfg.game_name}-v5", obs_type="grayscale",
        frameskip=cfg.frameskip, repeat_action_probability=0.0,
        full_action_space=False)
    env = WarpFrame(env, width=cfg.obs_shape[1], height=cfg.obs_shape[0])
    if noop_start:
        env = NoopResetEnv(env, noop_max=cfg.noop_max,
                           rng=np.random.default_rng(seed))
    return env
