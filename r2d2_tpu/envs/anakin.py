"""Pure-JAX batched FakeAtariEnv: the anakin transport's jittable env.

The Podracer "Anakin" architecture (PAPERS.md) collapses actor, replay and
learner into ONE compiled on-device program — which requires the
environment itself to be expressible as jnp ops.  This module is the
device twin of :class:`r2d2_tpu.envs.fake.FakeAtariEnv`: the same tiny
learnable POMDP (hidden phase counter, bright horizontal band observation,
truncation at ``episode_len`` with a +2 terminal bonus), vmapped over a
``(num_lanes, ...)`` state pytree so the whole fleet steps as a handful of
array ops inside the fused super-step (learner/anakin.py).

Bit-exactness contract (pinned by tests/test_anakin.py): given the same
initial phase and action sequence, ``step``/``observe`` reproduce the
numpy env's observation bytes, rewards and truncation flags exactly — the
dynamics are integer arithmetic plus the constants {0.0, 1.0, 2.0}, so
float equality is exact.  The one divergence is *where randomness comes
from*: the numpy env draws its reset phase from a ``np.random.Generator``,
which has no jittable twin, so this env draws reset phases from a
counter-based per-lane ``jax.random`` stream instead.  The parity test
replays this env's phase draws into the numpy oracle through its
resumable-state API (``restore_state``), which isolates the RNG-stream
choice from the dynamics being verified.

API shape (functional, all methods safe under jit/vmap/scan):

- ``init_state(key) -> state``: every lane reset, phases drawn from
  per-lane folded streams.
- ``observe(state) -> (N, *obs_shape) uint8``: pure function of state.
- ``step(state, actions) -> (state', reward (N,) f32, truncated (N,) bool)``:
  no auto-reset — the caller records the post-step observation first
  (exactly the VectorActor ordering) and then calls
- ``reset_lanes(state, mask) -> state'``: redraw phase / zero the step
  counter for masked lanes only.

Any jittable env that implements this same four-method surface (plus a
``STATE_KEYS`` tuple naming its per-lane state-pytree entries) inherits
the anakin fast path for free — :class:`AnakinGridEnv` below is the
second proof after the fake env, and :func:`make_anakin_env` is the
selection point (``cfg.anakin_env``) the trainer resolves through.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.envs.grid import AGENT_PIXEL, GOAL_PIXEL, GRID


def make_anakin_env(cfg, action_dim: int):
    """The anakin transport's env-selection point: resolve
    ``cfg.anakin_env`` to a jittable env over ``cfg.num_actors`` lanes.
    Both built-ins share the 4-action set; a custom jittable env plugs in
    by implementing the same four-method surface and being returned from
    here (train() hard-errors on host env factories in anakin mode — the
    env must be jnp ops, not a subprocess)."""
    kind = getattr(cfg, "anakin_env", "fake")
    cls = {"fake": AnakinFakeEnv, "grid": AnakinGridEnv}.get(kind)
    if cls is None:
        raise ValueError(f"unknown anakin_env {kind!r} "
                         "(expected 'fake' or 'grid')")
    return cls(obs_shape=cfg.stored_obs_shape, action_dim=action_dim,
               episode_len=cfg.anakin_episode_len,
               num_lanes=cfg.num_actors)


class AnakinFakeEnv:
    """Vmapped, jit-safe :class:`~r2d2_tpu.envs.fake.FakeAtariEnv` twin.

    State pytree (all device arrays, N = num_lanes):
      ``phase`` (N,) int32 — the hidden phase counter (monotone within an
      episode, like the numpy env's ``_phase``),
      ``t`` (N,) int32 — steps into the current episode,
      ``key`` (N, 2) uint32 — per-lane reset-phase streams.
    """

    # the env-state pytree entries the fused loop carries as
    # ``ast["env_<key>"]`` (learner/anakin.py) and the snapshot persists
    STATE_KEYS = ("phase", "t", "key")

    def __init__(self, obs_shape: Tuple[int, ...] = (84, 84, 1),
                 action_dim: int = 4, episode_len: int = 32,
                 num_lanes: int = 1):
        self.obs_shape = tuple(obs_shape)
        self.action_dim = int(action_dim)
        self.episode_len = int(episode_len)
        self.num_lanes = int(num_lanes)
        h = self.obs_shape[0]
        self._rows_per_band = max(1, h // self.action_dim)

    # ------------------------------------------------------------ lifecycle
    def init_state(self, key: jax.Array) -> dict:
        """All lanes reset: per-lane streams are ``fold_in(key, lane)`` so
        lane phase sequences are independent and reproducible."""
        lanes = jnp.arange(self.num_lanes, dtype=jnp.uint32)
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(lanes)
        state = dict(
            phase=jnp.zeros(self.num_lanes, jnp.int32),
            t=jnp.zeros(self.num_lanes, jnp.int32),
            key=keys,
        )
        return self.reset_lanes(state,
                                jnp.ones(self.num_lanes, bool))

    def reset_lanes(self, state: dict, mask: jax.Array) -> dict:
        """Redraw the phase and zero the step counter for masked lanes
        (the numpy env's ``reset``: ``phase = rng.integers(action_dim)``,
        ``t = 0``).  Unmasked lanes are untouched, including their RNG
        stream position."""
        def draw(k):
            k_next, sub = jax.random.split(k)
            phase = jax.random.randint(sub, (), 0, self.action_dim,
                                       dtype=jnp.int32)
            return k_next, phase

        new_key, new_phase = jax.vmap(draw)(state["key"])
        return dict(
            phase=jnp.where(mask, new_phase, state["phase"]),
            t=jnp.where(mask, 0, state["t"]),
            key=jnp.where(mask[:, None], new_key, state["key"]),
        )

    # ------------------------------------------------------------- dynamics
    def observe(self, state: dict) -> jax.Array:
        """(N, *obs_shape) uint8 — the numpy ``_obs`` band, vectorized:
        rows [band·rpb, (band+1)·rpb) are 255, everything else 0."""
        h = self.obs_shape[0]
        rpb = self._rows_per_band
        band = state["phase"] % self.action_dim            # (N,)
        r0 = band * rpb
        rows = jnp.arange(h, dtype=jnp.int32)              # (H,)
        mask = ((rows[None, :] >= r0[:, None])
                & (rows[None, :] < (r0 + rpb)[:, None]))   # (N, H)
        extra = (1,) * (len(self.obs_shape) - 1)
        mask = mask.reshape(mask.shape + extra)            # (N, H, 1, 1...)
        obs = jnp.where(mask, jnp.uint8(255), jnp.uint8(0))
        return jnp.broadcast_to(
            obs, (state["phase"].shape[0], *self.obs_shape))

    def step(self, state: dict, actions: jax.Array
             ) -> Tuple[dict, jax.Array, jax.Array]:
        """One lockstep env step for every lane.

        Mirrors ``FakeAtariEnv.step`` exactly: reward 1.0 on the phase-
        matching action, phase and t advance, truncation at
        ``episode_len`` adds the +2.0 bonus.  ``terminated`` is always
        False in the numpy env, so only ``truncated`` is returned.  Lanes
        are NOT auto-reset — call :meth:`reset_lanes` with the truncated
        mask after recording the post-step observation.
        """
        target = state["phase"] % self.action_dim
        reward = jnp.where(actions.astype(jnp.int32) == target,
                           jnp.float32(1.0), jnp.float32(0.0))
        phase = state["phase"] + 1
        t = state["t"] + 1
        truncated = t >= self.episode_len
        reward = reward + jnp.where(truncated, jnp.float32(2.0),
                                    jnp.float32(0.0))
        return (dict(phase=phase, t=t, key=state["key"]),
                reward, truncated)

    # ----------------------------------------------------- host-side mirror
    def host_phase_draw(self, key: np.ndarray) -> Tuple[np.ndarray, int]:
        """The host-numpy mirror of one lane's reset-phase draw — the
        parity tests use it to force the numpy oracle's phase to this
        env's stream (module docstring).  ``key`` is one lane's (2,)
        uint32 key; returns ``(next_key, phase)`` with identical values
        to the in-graph draw."""
        k = jnp.asarray(key, jnp.uint32)
        k_next, sub = jax.random.split(k)
        phase = int(jax.random.randint(sub, (), 0, self.action_dim,
                                       dtype=jnp.int32))
        return np.asarray(k_next), phase


class AnakinGridEnv:
    """Vmapped, jit-safe :class:`~r2d2_tpu.envs.grid.GridWorldEnv` twin —
    the second jittable env through the four-method surface (the "fast
    path for free" proof: the fused program in learner/anakin.py runs it
    UNCHANGED).

    State pytree (all device arrays, N = num_lanes):
      ``agent`` (N,) int32 — the agent's flattened board cell,
      ``goal`` (N,) int32 — the goal's flattened board cell,
      ``t`` (N,) int32 — steps into the current episode,
      ``key`` (N, 2) uint32 — per-lane reset-draw streams.

    Bit-exactness contract (tests/test_anakin.py): given the same reset
    draws, ``step``/``observe`` reproduce the numpy env's observation
    bytes, rewards and truncation flags exactly — in-episode dynamics
    (moves, goal relocation) are deterministic integer arithmetic, so
    the replay-the-reset-draws parity scheme covers the whole episode.
    """

    STATE_KEYS = ("agent", "goal", "t", "key")

    def __init__(self, obs_shape: Tuple[int, ...] = (84, 84, 1),
                 action_dim: int = 4, episode_len: int = 32,
                 num_lanes: int = 1):
        if action_dim != 4:
            raise ValueError(
                f"AnakinGridEnv has exactly 4 move actions, got "
                f"action_dim {action_dim}")
        self.obs_shape = tuple(obs_shape)
        self.action_dim = int(action_dim)
        self.episode_len = int(episode_len)
        self.num_lanes = int(num_lanes)

    # ------------------------------------------------------------ lifecycle
    def init_state(self, key: jax.Array) -> dict:
        lanes = jnp.arange(self.num_lanes, dtype=jnp.uint32)
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(lanes)
        state = dict(
            agent=jnp.zeros(self.num_lanes, jnp.int32),
            goal=jnp.ones(self.num_lanes, jnp.int32),
            t=jnp.zeros(self.num_lanes, jnp.int32),
            key=keys,
        )
        return self.reset_lanes(state, jnp.ones(self.num_lanes, bool))

    def reset_lanes(self, state: dict, mask: jax.Array) -> dict:
        """Redraw agent and goal cells (goal uniform over the other
        ``GRID**2 - 1`` cells — the numpy env's exact scheme) and zero the
        step counter for masked lanes.  Unmasked lanes are untouched,
        including their RNG stream position.  Per-lane draws are
        elementwise in the lane axis, so a dp-sharded lane layout cannot
        change the generated bits (unlike fleet-wide counter-based
        draws — learner/anakin.py pins those replicated instead)."""
        m = GRID * GRID

        def draw(k):
            k_next, s1, s2 = jax.random.split(k, 3)
            agent = jax.random.randint(s1, (), 0, m, dtype=jnp.int32)
            d = jax.random.randint(s2, (), 0, m - 1, dtype=jnp.int32)
            goal = d + (d >= agent).astype(jnp.int32)
            return k_next, agent, goal

        new_key, new_agent, new_goal = jax.vmap(draw)(state["key"])
        return dict(
            agent=jnp.where(mask, new_agent, state["agent"]),
            goal=jnp.where(mask, new_goal, state["goal"]),
            t=jnp.where(mask, 0, state["t"]),
            key=jnp.where(mask[:, None], new_key, state["key"]),
        )

    # ------------------------------------------------------------- dynamics
    def observe(self, state: dict) -> jax.Array:
        """(N, *obs_shape) uint8 — agent cell bright (255), goal cell dim
        (128), vectorized over lanes; the numpy ``_obs`` block layout."""
        h, w = self.obs_shape[:2]
        ch, cw = max(1, h // GRID), max(1, w // GRID)
        rows = jnp.arange(h, dtype=jnp.int32)
        cols = jnp.arange(w, dtype=jnp.int32)

        def cell_mask(idx):                       # (N,) -> (N, H, W) bool
            r, c = idx // GRID, idx % GRID
            rm = ((rows[None, :] >= (r * ch)[:, None])
                  & (rows[None, :] < ((r + 1) * ch)[:, None]))
            cm = ((cols[None, :] >= (c * cw)[:, None])
                  & (cols[None, :] < ((c + 1) * cw)[:, None]))
            return rm[:, :, None] & cm[:, None, :]

        img = jnp.where(cell_mask(state["goal"]), jnp.uint8(GOAL_PIXEL),
                        jnp.uint8(0))
        img = jnp.where(cell_mask(state["agent"]), jnp.uint8(AGENT_PIXEL),
                        img)
        extra = (1,) * (len(self.obs_shape) - 2)
        img = img.reshape(img.shape + extra)
        return jnp.broadcast_to(
            img, (state["agent"].shape[0], *self.obs_shape))

    def step(self, state: dict, actions: jax.Array
             ) -> Tuple[dict, jax.Array, jax.Array]:
        """One lockstep move for every lane — GridWorldEnv.step exactly:
        clamped moves, +1.0 on reaching the goal, deterministic goal
        relocation (scan order, skipping the agent), truncation at
        ``episode_len``.  No RNG is consumed (randomness is reset-only,
        the fake env's discipline).  Lanes are NOT auto-reset."""
        a = actions.astype(jnp.int32)
        r, c = state["agent"] // GRID, state["agent"] % GRID
        dr = jnp.asarray((-1, 1, 0, 0), jnp.int32)[a]
        dc = jnp.asarray((0, 0, -1, 1), jnp.int32)[a]
        r = jnp.clip(r + dr, 0, GRID - 1)
        c = jnp.clip(c + dc, 0, GRID - 1)
        agent = r * GRID + c
        reached = agent == state["goal"]
        reward = jnp.where(reached, jnp.float32(1.0), jnp.float32(0.0))
        m = GRID * GRID
        g1 = (state["goal"] + 1) % m              # grid.next_goal, vmapped
        g1 = jnp.where(g1 == agent, (g1 + 1) % m, g1)
        goal = jnp.where(reached, g1, state["goal"])
        t = state["t"] + 1
        truncated = t >= self.episode_len
        return (dict(agent=agent, goal=goal, t=t, key=state["key"]),
                reward, truncated)

    # ----------------------------------------------------- host-side mirror
    def host_reset_draw(self, key: np.ndarray) -> Tuple[np.ndarray, int, int]:
        """One lane's reset draw on the host — the parity tests use it to
        force the numpy oracle's agent/goal to this env's stream (module
        docstring).  Returns ``(next_key, agent, goal)`` with identical
        values to the in-graph draw."""
        m = GRID * GRID
        k = jnp.asarray(key, jnp.uint32)
        k_next, s1, s2 = jax.random.split(k, 3)
        agent = int(jax.random.randint(s1, (), 0, m, dtype=jnp.int32))
        d = int(jax.random.randint(s2, (), 0, m - 1, dtype=jnp.int32))
        return np.asarray(k_next), agent, d + (1 if d >= agent else 0)
