"""Pure-JAX batched FakeAtariEnv: the anakin transport's jittable env.

The Podracer "Anakin" architecture (PAPERS.md) collapses actor, replay and
learner into ONE compiled on-device program — which requires the
environment itself to be expressible as jnp ops.  This module is the
device twin of :class:`r2d2_tpu.envs.fake.FakeAtariEnv`: the same tiny
learnable POMDP (hidden phase counter, bright horizontal band observation,
truncation at ``episode_len`` with a +2 terminal bonus), vmapped over a
``(num_lanes, ...)`` state pytree so the whole fleet steps as a handful of
array ops inside the fused super-step (learner/anakin.py).

Bit-exactness contract (pinned by tests/test_anakin.py): given the same
initial phase and action sequence, ``step``/``observe`` reproduce the
numpy env's observation bytes, rewards and truncation flags exactly — the
dynamics are integer arithmetic plus the constants {0.0, 1.0, 2.0}, so
float equality is exact.  The one divergence is *where randomness comes
from*: the numpy env draws its reset phase from a ``np.random.Generator``,
which has no jittable twin, so this env draws reset phases from a
counter-based per-lane ``jax.random`` stream instead.  The parity test
replays this env's phase draws into the numpy oracle through its
resumable-state API (``restore_state``), which isolates the RNG-stream
choice from the dynamics being verified.

API shape (functional, all methods safe under jit/vmap/scan):

- ``init_state(key) -> state``: every lane reset, phases drawn from
  per-lane folded streams.
- ``observe(state) -> (N, *obs_shape) uint8``: pure function of state.
- ``step(state, actions) -> (state', reward (N,) f32, truncated (N,) bool)``:
  no auto-reset — the caller records the post-step observation first
  (exactly the VectorActor ordering) and then calls
- ``reset_lanes(state, mask) -> state'``: redraw phase / zero the step
  counter for masked lanes only.

Any future jittable env (gridworlds, procgen-style) that implements this
same four-method surface inherits the anakin fast path for free.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class AnakinFakeEnv:
    """Vmapped, jit-safe :class:`~r2d2_tpu.envs.fake.FakeAtariEnv` twin.

    State pytree (all device arrays, N = num_lanes):
      ``phase`` (N,) int32 — the hidden phase counter (monotone within an
      episode, like the numpy env's ``_phase``),
      ``t`` (N,) int32 — steps into the current episode,
      ``key`` (N, 2) uint32 — per-lane reset-phase streams.
    """

    def __init__(self, obs_shape: Tuple[int, ...] = (84, 84, 1),
                 action_dim: int = 4, episode_len: int = 32,
                 num_lanes: int = 1):
        self.obs_shape = tuple(obs_shape)
        self.action_dim = int(action_dim)
        self.episode_len = int(episode_len)
        self.num_lanes = int(num_lanes)
        h = self.obs_shape[0]
        self._rows_per_band = max(1, h // self.action_dim)

    # ------------------------------------------------------------ lifecycle
    def init_state(self, key: jax.Array) -> dict:
        """All lanes reset: per-lane streams are ``fold_in(key, lane)`` so
        lane phase sequences are independent and reproducible."""
        lanes = jnp.arange(self.num_lanes, dtype=jnp.uint32)
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(lanes)
        state = dict(
            phase=jnp.zeros(self.num_lanes, jnp.int32),
            t=jnp.zeros(self.num_lanes, jnp.int32),
            key=keys,
        )
        return self.reset_lanes(state,
                                jnp.ones(self.num_lanes, bool))

    def reset_lanes(self, state: dict, mask: jax.Array) -> dict:
        """Redraw the phase and zero the step counter for masked lanes
        (the numpy env's ``reset``: ``phase = rng.integers(action_dim)``,
        ``t = 0``).  Unmasked lanes are untouched, including their RNG
        stream position."""
        def draw(k):
            k_next, sub = jax.random.split(k)
            phase = jax.random.randint(sub, (), 0, self.action_dim,
                                       dtype=jnp.int32)
            return k_next, phase

        new_key, new_phase = jax.vmap(draw)(state["key"])
        return dict(
            phase=jnp.where(mask, new_phase, state["phase"]),
            t=jnp.where(mask, 0, state["t"]),
            key=jnp.where(mask[:, None], new_key, state["key"]),
        )

    # ------------------------------------------------------------- dynamics
    def observe(self, state: dict) -> jax.Array:
        """(N, *obs_shape) uint8 — the numpy ``_obs`` band, vectorized:
        rows [band·rpb, (band+1)·rpb) are 255, everything else 0."""
        h = self.obs_shape[0]
        rpb = self._rows_per_band
        band = state["phase"] % self.action_dim            # (N,)
        r0 = band * rpb
        rows = jnp.arange(h, dtype=jnp.int32)              # (H,)
        mask = ((rows[None, :] >= r0[:, None])
                & (rows[None, :] < (r0 + rpb)[:, None]))   # (N, H)
        extra = (1,) * (len(self.obs_shape) - 1)
        mask = mask.reshape(mask.shape + extra)            # (N, H, 1, 1...)
        obs = jnp.where(mask, jnp.uint8(255), jnp.uint8(0))
        return jnp.broadcast_to(
            obs, (state["phase"].shape[0], *self.obs_shape))

    def step(self, state: dict, actions: jax.Array
             ) -> Tuple[dict, jax.Array, jax.Array]:
        """One lockstep env step for every lane.

        Mirrors ``FakeAtariEnv.step`` exactly: reward 1.0 on the phase-
        matching action, phase and t advance, truncation at
        ``episode_len`` adds the +2.0 bonus.  ``terminated`` is always
        False in the numpy env, so only ``truncated`` is returned.  Lanes
        are NOT auto-reset — call :meth:`reset_lanes` with the truncated
        mask after recording the post-step observation.
        """
        target = state["phase"] % self.action_dim
        reward = jnp.where(actions.astype(jnp.int32) == target,
                           jnp.float32(1.0), jnp.float32(0.0))
        phase = state["phase"] + 1
        t = state["t"] + 1
        truncated = t >= self.episode_len
        reward = reward + jnp.where(truncated, jnp.float32(2.0),
                                    jnp.float32(0.0))
        return (dict(phase=phase, t=t, key=state["key"]),
                reward, truncated)

    # ----------------------------------------------------- host-side mirror
    def host_phase_draw(self, key: np.ndarray) -> Tuple[np.ndarray, int]:
        """The host-numpy mirror of one lane's reset-phase draw — the
        parity tests use it to force the numpy oracle's phase to this
        env's stream (module docstring).  ``key`` is one lane's (2,)
        uint32 key; returns ``(next_key, phase)`` with identical values
        to the in-graph draw."""
        k = jnp.asarray(key, jnp.uint32)
        k_next, sub = jax.random.split(k)
        phase = int(jax.random.randint(sub, (), 0, self.action_dim,
                                       dtype=jnp.int32))
        return np.asarray(k_next), phase
