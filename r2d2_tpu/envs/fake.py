"""Deterministic fake Atari-shaped environment.

The reference has no test story at all (SURVEY.md §4); this env is the
framework's substitute for ALE in tests, smoke runs, and actor benchmarks.
It follows the gymnasium 5-tuple step API that the real wrappers produce
and emits uint8 observations of ``cfg.obs_shape``.

The dynamics are a tiny learnable POMDP so end-to-end training can
demonstrably reduce loss and improve return:

- A hidden phase counter advances each step; the rewarded action is
  ``phase % action_dim``.
- The observation encodes the phase as a bright horizontal band, so a
  Q-network (even an MLP torso) can learn the mapping obs → best action.
- Episodes truncate after ``episode_len`` steps; a small terminal bonus
  exercises the γ-zero terminal tail path in the replay format.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class _Box:
    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype


class _Discrete:
    def __init__(self, n: int, rng: np.random.Generator):
        self.n = n
        self._rng = rng

    def sample(self) -> int:
        return int(self._rng.integers(self.n))


class FakeAtariEnv:
    """Deterministic-by-seed fake env with the wrapped-ALE interface."""

    def __init__(self, obs_shape: Tuple[int, ...] = (84, 84, 1),
                 action_dim: int = 4, episode_len: int = 32, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self.observation_space = _Box(obs_shape, np.uint8)
        self.action_space = _Discrete(action_dim, self._rng)
        self.episode_len = episode_len
        self._phase = 0
        self._t = 0

    def _obs(self) -> np.ndarray:
        h = self.observation_space.shape[0]
        obs = np.zeros(self.observation_space.shape, np.uint8)
        band = self._phase % self.action_space.n
        rows_per_band = max(1, h // self.action_space.n)
        r0 = band * rows_per_band
        obs[r0:r0 + rows_per_band] = 255
        return obs

    def reset(self, *, seed: Optional[int] = None, **kwargs):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
            # the action space samples from the SAME generator: rebinding
            # only self._rng left action_space._rng on the old stream, so
            # exploration sampling was not reseeded (ISSUE 6 satellite)
            self.action_space._rng = self._rng
        self._phase = int(self._rng.integers(self.action_space.n))
        self._t = 0
        return self._obs(), {}

    def step(self, action: int):
        target = self._phase % self.action_space.n
        reward = 1.0 if int(action) == target else 0.0
        self._phase += 1
        self._t += 1
        terminated = False
        truncated = self._t >= self.episode_len
        if truncated:
            reward += 2.0  # exercises episode-end accounting distinctly
        return self._obs(), reward, terminated, truncated, {}

    def clone_state(self) -> dict:
        """ALE-style resumable emulator state (actor full-state snapshots
        — VectorActor.snapshot): RNG + phase + step counter is the whole
        dynamics, so restore continues the episode bit-exactly."""
        return dict(rng=self._rng.bit_generator.state, phase=self._phase,
                    t=self._t)

    def restore_state(self, state: dict) -> None:
        self._rng.bit_generator.state = state["rng"]
        self._phase = int(state["phase"])
        self._t = int(state["t"])

    def close(self):
        pass
