"""Environment layer.

``create_env`` is the single factory every other component uses
(reference: environment.py:66-74).  It returns ALE Atari when the
``ale_py`` plugin is installed, and otherwise (or when
``cfg.game_name == "Fake"``) a deterministic fake Atari-shaped env so the
framework is runnable and testable without the Atari ROMs.
"""
from r2d2_tpu.envs.fake import FakeAtariEnv
from r2d2_tpu.envs.grid import GridWorldEnv
from r2d2_tpu.envs.atari import (
    NoopResetEnv,
    WarpFrame,
    atari_available,
    create_env,
)

__all__ = [
    "FakeAtariEnv",
    "GridWorldEnv",
    "NoopResetEnv",
    "WarpFrame",
    "atari_available",
    "create_env",
]
