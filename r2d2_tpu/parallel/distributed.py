"""Multi-host distributed runtime (SURVEY.md §5.8).

The reference's "distributed backend" is single-host
``torch.multiprocessing`` queues + shared memory (train.py:23-26); it has no
multi-node story at all.  The TPU-native equivalent splits cleanly:

- **Within the learner step**: nothing here — gradient/metric collectives
  are GSPMD-inserted ``psum``s over the mesh (parallel/mesh.py) and ride
  ICI within a slice and DCN across slices automatically.
- **Process bring-up**: :func:`init_distributed` wraps
  ``jax.distributed.initialize`` so N host processes (one per TPU host)
  form a single JAX runtime whose ``jax.devices()`` is the global device
  set.  After it returns, ``make_mesh`` over ``jax.devices()`` is a global
  mesh and the table-driven ``parallel/sharding.pjit_train_step``
  compiles unchanged.
- **Host-side data plane**: replay stays host-local (each host's actor
  fleet feeds its own buffer — the analogue of the reference's per-actor
  queues staying on one box).  ``cfg.batch_size`` remains the **global**
  batch: each host samples only :func:`host_batch_size` rows (its share of
  the dp axis) and :func:`host_local_batch` assembles them into one
  globally sharded device batch via
  ``jax.make_array_from_process_local_data`` — no batch data ever crosses
  DCN.  The step's dp-sharded priority output comes back through
  :func:`local_rows`, which reads only this host's addressable shards, so
  each host's priority feedback aligns with the indexes it sampled.

Single-process (tests, the one-chip bench) is the degenerate case: every
helper reduces to the identity / a sharded ``device_put``, which is how the
whole path is unit tested on the 8-device CPU mesh — the single-process
code path IS the multi-host code path.

Topology assumption (asserted): each host's devices cover whole dp groups,
contiguously — true for standard pod slices where the mesh is built from
``jax.devices()`` in order (make_mesh).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from r2d2_tpu.config import Config
from r2d2_tpu.parallel.sharding import DEVICE_BATCH_KEYS, ShardingTable


def _distributed_initialized() -> bool:
    """Has ``jax.distributed.initialize`` already run in this process?

    ``jax.distributed.is_initialized`` only exists in newer JAX; older
    releases (e.g. 0.4.x) expose the same fact as
    ``jax.distributed.global_state.client`` being non-None.  Probing via
    ``getattr`` keeps the bring-up idempotent on both.
    """
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    state = getattr(jax.distributed, "global_state", None)
    if state is None:
        # 0.4.x keeps global_state in the private module only
        try:
            from jax._src import distributed as _distributed_src

            state = getattr(_distributed_src, "global_state", None)
        except ImportError:
            state = None
    return state is not None and getattr(state, "client", None) is not None


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto: bool = False) -> Dict[str, int]:
    """Join (or create) the multi-host JAX runtime.

    Must run before any other JAX call in the process (XLA backend
    initialisation pins the runtime) — the CLI's ``--distributed`` flag
    calls it first thing.  Arguments default to the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID``).  With ``auto=True`` (the CLI's behaviour) and no
    coordinator configured, ``jax.distributed.initialize()`` is called
    bare so TPU pods autodetect all three from the metadata server — an
    explicit distributed request never silently degrades to N independent
    single-host runs.  With ``auto=False`` (library default) and no
    coordinator, it is a no-op so single-process use needs no guards.

    Returns ``{"process_id": ..., "process_count": ...}``.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])

    # NOTE: nothing before initialize() may touch the backend
    # (jax.devices(), jax.process_count(), ...) or it would raise
    if not _distributed_initialized():
        if coordinator_address is not None:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        elif auto:
            try:
                jax.distributed.initialize()  # TPU-pod autodetection
            except Exception as e:
                raise RuntimeError(
                    "distributed bring-up requested but no coordinator is "
                    "configured and autodetection failed; set "
                    "JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / "
                    "JAX_PROCESS_ID") from e
    return dict(process_id=jax.process_index(),
                process_count=jax.process_count())


def owned_dp_groups(mesh: Mesh) -> slice:
    """The contiguous range of dp groups whose devices this process owns.

    Raises (real errors, not asserts — this alignment is load-bearing for
    priority/index pairing and must survive ``python -O``) when a dp group
    is split across processes or this process's groups are
    non-contiguous: the topology assumption from the module docstring.
    """
    axis = mesh.axis_names.index("dp")
    dp = mesh.shape["dp"]
    groups = np.moveaxis(mesh.devices, axis, 0).reshape(dp, -1)
    local_ids = {d.id for d in jax.local_devices()}
    owned = []
    for i in range(dp):
        n_local = sum(d.id in local_ids for d in groups[i])
        if n_local not in (0, groups.shape[1]):
            raise RuntimeError(
                f"dp group {i} is split across processes; re-order mesh "
                f"axes so dp groups are host-aligned")
        if n_local:
            owned.append(i)
    if not owned:
        return slice(0, 0)
    if owned != list(range(owned[0], owned[-1] + 1)):
        raise RuntimeError(
            f"process owns non-contiguous dp groups {owned}; re-order mesh "
            f"axes so each host's dp rows are contiguous")
    return slice(owned[0], owned[-1] + 1)


def dp_rows_for_process(mesh: Mesh, global_batch: int) -> slice:
    """The contiguous slice of the global batch this process's devices own.

    Rows are sharded over the ``dp`` axis wherever it sits in the mesh; a
    dp group's row-shard is replicated over the remaining axes.
    """
    owned = owned_dp_groups(mesh)
    per = global_batch // mesh.shape["dp"]
    return slice(owned.start * per, owned.stop * per)


def local_mesh(mesh: Mesh) -> Mesh:
    """This process's whole-dp-group submesh of ``mesh`` — the same axis
    names and order, the dp extent reduced to the groups this process
    owns.  Collectives/jits over it are process-local (no cross-host
    lockstep needed), which is what lets each host run its own device-side
    replay plane (gather/write) independently while the global train step
    stays SPMD over the full mesh."""
    owned = owned_dp_groups(mesh)
    axis = mesh.axis_names.index("dp")
    sub = np.moveaxis(np.moveaxis(mesh.devices, axis, 0)[owned], 0, axis)
    return Mesh(sub, mesh.axis_names)


def assemble_global(shardings: Dict[str, Any],
                    local_arrays: Dict[str, jax.Array],
                    global_leading: int) -> Dict[str, jax.Array]:
    """Stitch per-process device-resident shards into global jax Arrays.

    ``local_arrays[k]`` is this process's slab, laid out over
    :func:`local_mesh` such that each local device already holds exactly
    the rows the global sharding assigns it (same physical device, same
    bytes — only the leading-axis coordinates differ by the process
    offset).  ``jax.make_array_from_single_device_arrays`` then assembles
    the global view with **zero data movement**: every process contributes
    its addressable shards.  Single-process this is a relabeling no-op.
    """
    out = {}
    for k, la in local_arrays.items():
        gshape = (global_leading, *la.shape[1:])
        out[k] = jax.make_array_from_single_device_arrays(
            gshape, shardings[k], [s.data for s in la.addressable_shards])
    return out


def host_batch_size(cfg: Config, mesh: Mesh) -> int:
    """How many rows of the global ``cfg.batch_size`` this host samples
    from its local replay buffer.  Single-process: ``cfg.batch_size``."""
    rows = dp_rows_for_process(mesh, cfg.batch_size)
    return rows.stop - rows.start


def host_local_batch(mesh: Mesh, local_batch: Dict[str, np.ndarray],
                     shardings: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Build the globally dp-sharded device batch from per-process data.

    ``local_batch`` holds only this process's rows (``host_batch_size`` of
    them).  Single-process, the local rows are the whole batch and the
    result equals a sharded ``jax.device_put``.  Pass cached ``shardings``
    (``ShardingTable.batch_shardings()``) from hot paths to avoid
    rebuilding them per step.
    """
    if shardings is None:
        shardings = ShardingTable(mesh).batch_shardings()
    return {
        k: jax.make_array_from_process_local_data(shardings[k],
                                                  local_batch[k])
        for k in DEVICE_BATCH_KEYS
    }


def local_rows(arr: jax.Array, axis: int = 0) -> np.ndarray:
    """This process's rows of an ``axis``-sharded global array.

    Reads only addressable shards (a multi-host ``device_get`` of the full
    array would fail), ordered by global row index and deduplicated (a
    shard replicated over non-dp axes appears once per replica).
    Single-process this equals ``device_get`` of the whole array.
    """
    rows: Dict[int, np.ndarray] = {}
    for shard in arr.addressable_shards:
        start = shard.index[axis].start or 0
        if start not in rows:
            rows[start] = np.asarray(shard.data)
    return np.concatenate([rows[s] for s in sorted(rows)], axis=axis)


def global_from_local_rows(sharding: Any, local_data: np.ndarray,
                           global_shape: tuple, axis: int,
                           offset: int) -> jax.Array:
    """Host data → globally sharded device array, when this process's
    ``local_data`` covers global indices [offset, offset + local) of
    ``axis`` (replicated over every other mesh axis).

    The per-device H2D puts follow the sharding's own index map, so this
    works for any axis position (``make_array_from_process_local_data``
    only tiles the leading axis).  Used for the (k, B, 6) index bundles of
    the multi-host device-replay plane, which shard axis 1.
    """
    idx_map = sharding.addressable_devices_indices_map(global_shape)
    arrs = []
    for dev, idx in idx_map.items():
        sl = list(idx)
        s = sl[axis]
        start = (s.start or 0) - offset
        stop = (global_shape[axis] if s.stop is None else s.stop) - offset
        sl[axis] = slice(start, stop)
        arrs.append(jax.device_put(local_data[tuple(sl)], dev))
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, arrs)


def sync_counter(value: int, reduce: str = "max") -> int:
    """All-process reduction of a host counter (e.g. env_steps, buffer
    size) — a device-mediated allgather so hosts agree on progress without
    a side channel.  Single-process it is the identity."""
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils

    vals = np.asarray(multihost_utils.process_allgather(
        np.asarray(value, np.int64)))
    if reduce == "max":
        return int(vals.max())
    if reduce == "min":
        return int(vals.min())
    return int(vals.sum())


def sync_min_array(values: np.ndarray) -> np.ndarray:
    """Element-wise min of a small float array across processes (the
    cross-host IS-weight normalisation for the multi-host device replay
    plane).  Single-process identity."""
    values = np.asarray(values, np.float64)
    if jax.process_count() == 1:
        return values
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.process_allgather(values)).min(axis=0)
