"""Declarative per-parameter sharding for the unified pjit learner.

The r1-r8 learner carried an axis-variant surface: a ``_param_spec``
heuristic for the retired ``mp`` axis, shard_map-wrapped super-step
variants for dp-sharded rings, and mesh-vs-no-mesh branches through the
learner.  This module collapses all of it into the GSPMD-native shape the
Podracer/pjit lineage uses (SNIPPETS.md [2], [3]): ONE
``jax.jit(in_shardings=..., out_shardings=..., donate_argnums=...)``
train step per drivetrain, whose entire layout comes from a declarative
**sharding table** over a 3-axis mesh:

- ``dp``  — data parallelism: the batch's leading axis, the replay ring's
  slot axis, gradient psums inserted by XLA.
- ``fsdp`` — parameter/moment sharding for memory: kernels (and their
  optimizer moments, which inherit the param layout by construction —
  adam's ``mu``/``nu`` subtrees carry the same trailing key paths) shard
  a large dim, XLA inserting the allgather/reduce-scatter pairs.
- ``tp``  — Megatron-style tensor parallelism: the LSTM 4H gate kernels
  and dense output dims column-split; gate nonlinearities and dueling
  heads are elementwise/tiny in the split dim.

The table maps **param-path patterns** to per-dim axis assignments.
Integer layer indices are wildcarded (``lstm_0`` → ``lstm_*`` — the
SNIPPETS.md [3] ``sharding_map`` convention), patterns match the
*trailing* tokens of a leaf's path (so ``params``, ``target_params`` and
the optax moments all resolve through one entry), a per-dim divisibility
guard falls back to replication when a dim does not divide its mesh
axis, and an **unresolved leaf is an error** — a new model family must
extend the table (docs/SHARDING.md) rather than silently replicate at
pod scale.

Scalars (0-d leaves: the step counter, adam's ``count``) always
replicate; no table entry is needed or consulted.

``cfg.sharding_table`` overrides/extends the default table from the CLI
(``pattern=axis,axis;pattern2=...`` — empty slots replicate that dim).
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

# the override grammar lives in config.py (jax-free, so Config can
# validate it at construction); re-exported here as the table's home
from r2d2_tpu.config import normalize_token, parse_table  # noqa: F401
from r2d2_tpu.parallel.mesh import trivial_mesh
from r2d2_tpu.utils.trace import RETRACES

# device-batch fields (everything else in a replay batch is host-only
# bookkeeping: idxes, block_ptr, env_steps); replay/device_ring.py's
# gather emits exactly these keys
DEVICE_BATCH_KEYS = (
    "obs", "last_action", "last_reward", "hidden", "action",
    "n_step_reward", "n_step_gamma", "burn_in", "learning", "forward",
    "is_weights",
)

# device-ring data arrays (replay/device_ring.py imports these as its
# _DATA_KEYS — one definition, no drift); named here so the ring's
# sharding constructors resolve through the table
RING_DATA_KEYS = ("obs", "last_action", "last_reward", "action",
                  "n_step_reward", "n_step_gamma", "hidden")
PER_KEYS = ("prios", "seq_meta", "first")


class UnresolvedShardingError(ValueError):
    """A TrainState leaf matched no sharding-table pattern.

    Silent replication of an unmatched leaf would hide a missing table
    entry until a new model family OOMs at pod scale — new families must
    extend the table (docs/SHARDING.md's add-a-model-family workflow)."""


# pattern → per-dim axis names (None = replicated dim; missing trailing
# dims replicate).  Keys are dot-joined NORMALIZED path suffixes: integer
# layer indices already wildcarded, "*" matches any single token.
DEFAULT_TABLE: Dict[str, Tuple[Optional[str], ...]] = {
    # conv torsos (nature/impala): compute is batch-dominated and dp
    # shards it; fsdp takes the output-channel dim purely for memory
    "torso.Conv_*.kernel": (None, None, None, "fsdp"),
    "torso.Conv_*.bias": (),
    # torso FC (nature flatten->512 dominates param count): fsdp on the
    # huge input dim, tp on the output dim
    "torso.Dense_*.kernel": ("fsdp", "tp"),
    "torso.Dense_*.bias": ("tp",),
    # LSTM: the 4H gate kernels take the Megatron column split over tp
    # (gate math is elementwise in the 4H dim); fsdp shards the input dim
    "lstm_*.wi": ("fsdp", "tp"),
    "lstm_*.wh": ("fsdp", "tp"),
    "lstm_*.b": ("tp",),
    # dueling head: hidden kernels split like the torso FC; the tiny
    # output dims (action_dim, 1) fall back to replication via the
    # divisibility guard wherever tp does not divide them
    "head.*.kernel": ("fsdp", "tp"),
    "head.*.bias": ("tp",),
    # device-replay plane: ring slots and PER leaves shard over dp when
    # the ring layout asks for it (DeviceRing consumes these entries)
    "ring.*": ("dp",),
    "per.*": ("dp",),
    # anakin fused loop (learner/anakin.py): per-lane carry arrays — env
    # state, agent obs/LSTM carry, local stream buffers — shard their
    # lane axis over dp (the Podracer replicate-the-program axis);
    # anakin_state_shardings resolves every lane-batched leaf through
    # this one entry (scalars/fleet-wide RNG keys replicate, the ring
    # slot-axis accounting follows the ring.* entries)
    "anakin.lane.*": ("dp",),
}

def _path_token(entry: Any) -> str:
    """One pytree KeyPath entry → its string token (DictKey.key,
    GetAttrKey.name, SequenceKey.idx, FlattenedIndexKey.key)."""
    for attr in ("key", "name"):
        v = getattr(entry, attr, None)
        if v is not None:
            return str(v)
    v = getattr(entry, "idx", None)
    if v is not None:
        return str(v)
    return str(entry)


def normalize_path(tokens: Sequence[str]) -> Tuple[str, ...]:
    return tuple(normalize_token(t) for t in tokens)


class ShardingTable:
    """The resolved sharding rules over one mesh.

    One instance is built per trainer bring-up (``train._build``) and
    consumed by every sharding constructor: the unified train/super
    steps' in/out shardings, the Learner's batch staging, the DeviceRing
    slot/PER layouts, and checkpoint re-placement.
    """

    def __init__(self, mesh, cfg: Any = None,
                 rules: Optional[Dict[str, Tuple[Optional[str], ...]]]
                 = None):
        if isinstance(cfg, dict):
            # ShardingTable(mesh, {...}) would silently treat a rules
            # dict as cfg (getattr(dict, "sharding_table", "") == "")
            # and ignore it — the caller meant rules=
            raise TypeError(
                "ShardingTable's second positional arg is cfg; pass "
                "extra pattern rules via the rules= keyword")
        self.mesh = mesh
        self.rules = dict(DEFAULT_TABLE)
        if rules:
            self.rules.update(rules)
        if cfg is not None and getattr(cfg, "sharding_table", ""):
            self.rules.update(parse_table(cfg.sharding_table))
        # longest pattern wins, and at equal length the entry with fewer
        # "*" tokens wins (a fully-specified override must beat a wildcard
        # default — "*" sorts before letters, so raw lexicographic order
        # would silently shadow it); lexicographic tiebreak last keeps
        # resolution deterministic
        self._patterns = sorted(
            ((tuple(p.split(".")), spec) for p, spec in self.rules.items()),
            key=lambda kv: (-len(kv[0]),
                            sum(t == "*" for t in kv[0]), kv[0]))

    # ------------------------------------------------------------ resolve
    def lookup(self, tokens: Sequence[str]
               ) -> Optional[Tuple[Optional[str], ...]]:
        """The first (longest) pattern matching the normalized path's
        trailing tokens, or None."""
        norm = normalize_path(tokens)
        for pat, spec in self._patterns:
            n = len(pat)
            if n <= len(norm) and all(
                    p == "*" or p == t for p, t in zip(pat, norm[-n:])):
                return spec
        return None

    def spec(self, tokens: Sequence[str],
             shape: Optional[Tuple[int, ...]] = None) -> P:
        """PartitionSpec for one leaf: 0-d leaves replicate, otherwise the
        table entry with the per-dim divisibility guard applied.  Raises
        :class:`UnresolvedShardingError` when no pattern matches."""
        if shape is not None and len(shape) == 0:
            return P()
        entry = self.lookup(tokens)
        if entry is None:
            raise UnresolvedShardingError(
                f"no sharding-table entry matches param path "
                f"{'.'.join(tokens)!r} (normalized "
                f"{'.'.join(normalize_path(tokens))!r}). Extend the table "
                f"— cfg.sharding_table override or "
                f"parallel/sharding.DEFAULT_TABLE; see docs/SHARDING.md "
                f"for the add-a-model-family workflow.")
        if shape is None:
            return P(*entry)
        if len(entry) > len(shape):
            raise ValueError(
                f"sharding-table entry {entry} for "
                f"{'.'.join(tokens)!r} names more dims than the leaf's "
                f"shape {shape}")
        dims = []
        for i, size in enumerate(shape):
            axis = entry[i] if i < len(entry) else None
            # divisibility guard: an indivisible dim replicates — the
            # layout is a pure perf choice, semantics are identical
            if axis is not None and size % self.mesh.shape[axis] != 0:
                axis = None
            dims.append(axis)
        return P(*dims)

    # --------------------------------------------------------- shardings
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def state_shardings(self, state) -> Any:
        """A TrainState-shaped tree of NamedShardings under the table.

        Works for ``params``, ``target_params`` and the optimizer
        moments without special-casing optax internals: patterns match
        trailing path tokens, and adam's ``mu``/``nu`` subtrees carry
        the same trailing key paths as the params they mirror — moments
        MUST share their param's layout or every update would reshard.
        ``state`` may hold live arrays or ``jax.ShapeDtypeStruct`` avals.
        """
        def leaf(path, x):
            tokens = [_path_token(k) for k in path]
            return NamedSharding(self.mesh,
                                 self.spec(tokens, tuple(np.shape(x))))
        return jax.tree_util.tree_map_with_path(leaf, state)

    def batch_shardings(self) -> Dict[str, NamedSharding]:
        """Leading-axis ``dp`` sharding for every device-batch field."""
        dp = NamedSharding(self.mesh, P("dp"))
        return {k: dp for k in DEVICE_BATCH_KEYS}

    def ring_shardings(self, layout: str = "replicated") -> Dict[str, Any]:
        """Device-ring array shardings: ``"replicated"`` pins the full
        ring on every device; ``"dp"`` resolves the slot axis through the
        table's ``ring.*`` entries (capacity scales with the mesh)."""
        if layout not in ("replicated", "dp"):
            raise ValueError(f"unknown device-ring layout {layout!r} "
                             "(expected 'replicated' or 'dp')")
        if layout == "replicated":
            return {k: self.replicated() for k in RING_DATA_KEYS}
        return {k: NamedSharding(self.mesh, self.spec(("ring", k)))
                for k in RING_DATA_KEYS}

    def per_shardings(self, layout: str = "replicated") -> Dict[str, Any]:
        """In-graph PER state shardings (prios/seq_meta/first), aligned
        with the ring slabs under ``"dp"`` (leaf axis splits exactly at
        slab boundaries because seqs_per_block divides each shard)."""
        if layout == "replicated":
            return {k: self.replicated() for k in PER_KEYS}
        return {k: NamedSharding(self.mesh, self.spec(("per", k)))
                for k in PER_KEYS}

    def anakin_state_shardings(self, ast, layout: str = "replicated"
                               ) -> Dict[str, Any]:
        """NamedShardings for the anakin fused loop's carry dict
        (learner/anakin.py ``make_anakin_state``): per-lane arrays
        resolve through the table's ``anakin.lane.*`` entry (lane axis
        over dp, with the divisibility guard's replication fallback),
        the ring-slot-axis accounting (``block_learning_total``) follows
        the ``ring.*`` entries under a ``"dp"`` ring layout, and
        scalars / the fleet-wide exploration key replicate.  ``ast`` may
        hold live arrays or ShapeDtypeStructs."""
        out: Dict[str, Any] = {}
        for k, v in ast.items():
            shape = tuple(np.shape(v))
            if k == "block_learning_total":
                out[k] = (NamedSharding(self.mesh,
                                        self.spec(("ring", k), shape))
                          if layout == "dp" else self.replicated())
            elif k == "act_key" or len(shape) == 0:
                out[k] = self.replicated()
            else:
                out[k] = NamedSharding(
                    self.mesh, self.spec(("anakin", "lane", k), shape))
        return out

    def place_state(self, state):
        """Place a host/any-layout TrainState onto the mesh with the
        table layout (used at bring-up and after checkpoint restore —
        the resharding half of the save/restore roundtrip).

        Multi-host: every process holds the same host value (same-seed
        init or a restored checkpoint), and a plain ``device_put`` cannot
        target non-addressable devices — build each global leaf from its
        index map instead."""
        shardings = self.state_shardings(state)
        if jax.process_count() == 1:
            return jax.device_put(state, shardings)

        def leaf(x, sh):
            x = np.asarray(x)
            return jax.make_array_from_callback(
                x.shape, sh, lambda idx: x[idx])
        return jax.tree.map(leaf, state, shardings)


# ---------------------------------------------------------------------------
# the unified jitted drivetrain entry points
# ---------------------------------------------------------------------------

_donation_warning_silenced = False


def _silence_benign_donation_warning() -> None:
    """The drivetrains donate the whole replay batch/index bundles by
    design (the buffers are dead after the gather/forward — donation
    frees them at dispatch even when XLA cannot ALIAS them to an
    output).  The int/uint8 leaves (obs, actions) can never alias the
    f32/scalar outputs, so every compile of a batch-donating step would
    log a multi-line "donated buffers were not usable" UserWarning that
    drowns real signal; the donation itself is correct, so silence
    exactly that message.

    Installed (once) from the factories that compile the batch-donating
    steps, NOT at module import.  Python's warning filters are global,
    so once any factory runs the message IS suppressed process-wide —
    and every trainer builds one (even the anakin path constructs a
    Learner, whose __init__ compiles pjit_train_step), so in practice
    all training processes filter it.  What factory-scoped install buys
    is the absence of an import side effect: host tools that import this
    module just to parse tables or resolve layouts do not have their
    warning state mutated."""
    global _donation_warning_silenced
    if _donation_warning_silenced:
        return
    _donation_warning_silenced = True
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable",
        category=UserWarning)


def _check_batch(cfg, mesh) -> None:
    if cfg.batch_size % mesh.shape["dp"] != 0:
        raise ValueError(
            f"batch_size {cfg.batch_size} not divisible by "
            f"dp={mesh.shape['dp']}")


def pjit_train_step(cfg, net, table: Optional[ShardingTable] = None,
                    state_template=None, donate_batch: bool = True):
    """THE train-step entry point — the only place a train step is jitted.

    One ``jax.jit`` whose layout comes entirely from the table: the
    TrainState shards per :meth:`ShardingTable.state_shardings`, the
    replay batch keeps its leading-axis ``dp`` sharding, and BOTH are
    donated — the state because the update consumes it, the batch
    because its buffers are dead after the gather/forward and XLA can
    reuse them for outputs (the (B,) priorities can alias is_weights).
    On a 1-device (trivial) mesh this IS the single-device step; there
    is no separate variant.

    ``donate_batch=False`` keeps the batch alive across calls — ONLY for
    diagnostics that deliberately re-step one device-resident batch
    (bench.py / measure_tpu timing loops); the training drivetrains
    always donate.

    ``state_template`` (a live TrainState or its avals) derives the
    per-leaf shardings; retrace-guarded as ``learner.train_step``.

    ``cfg.learnhealth_interval > 0`` appends the replicated in-graph
    diagnostic vector to the outputs (telemetry/learnhealth.py) — the
    drivetrains fold it into their existing result fetch; with the
    default 0 the compiled program is unchanged.
    """
    from r2d2_tpu.learner.step import make_train_step

    if table is None:
        table = ShardingTable(trivial_mesh(), cfg)
    if state_template is None:
        raise ValueError("pjit_train_step needs a state_template (a "
                         "TrainState or its ShapeDtypeStruct avals) to "
                         "resolve per-leaf shardings from the table")
    _silence_benign_donation_warning()
    _check_batch(cfg, table.mesh)
    lh = cfg.learnhealth_interval > 0
    st_sh = table.state_shardings(state_template)
    dp_rows = NamedSharding(table.mesh, P("dp"))
    out_sh = (st_sh, table.replicated(), dp_rows)
    if lh:
        out_sh = out_sh + (table.replicated(),)
    return jax.jit(
        RETRACES.wrap("learner.train_step",
                      make_train_step(cfg, net, learnhealth=lh)),
        in_shardings=(st_sh, table.batch_shardings()),
        out_shardings=out_sh,
        donate_argnums=(0, 1) if donate_batch else (0,),
    )


def pjit_super_step(cfg, net, table: ShardingTable, k: int,
                    state_template=None, layout: str = "replicated"):
    """The device-replay super-step (k fused optimizer steps, batches
    gathered in-graph from the HBM ring), jitted once with table-driven
    shardings: the ring follows ``layout`` (``ring.*`` table entries
    under ``"dp"`` — XLA partitions the gather, no hand-written
    shard_map), the (k, B, 6) index bundles and IS weights shard their
    batch axis over dp and are donated with the state.
    """
    from r2d2_tpu.learner.step import make_super_step_fn

    if state_template is None:
        raise ValueError("pjit_super_step needs a state_template (a "
                         "TrainState or its ShapeDtypeStruct avals) to "
                         "resolve per-leaf shardings from the table — "
                         "compiling without one would silently bypass "
                         "the table layout")
    _silence_benign_donation_warning()
    _check_batch(cfg, table.mesh)
    lh = cfg.learnhealth_interval > 0
    st_sh = table.state_shardings(state_template)
    dp_b = NamedSharding(table.mesh, P(None, "dp"))
    out_sh = (st_sh, table.replicated(), dp_b)
    if lh:
        # the (k, DIAG_SIZE) learnhealth diagnostic rows, replicated
        out_sh = out_sh + (table.replicated(),)
    return jax.jit(
        RETRACES.wrap("learner.super_step",
                      make_super_step_fn(cfg, net, k, learnhealth=lh)),
        in_shardings=(st_sh, table.ring_shardings(layout), dp_b, dp_b),
        out_shardings=out_sh,
        donate_argnums=(0, 2, 3),
    )


def pjit_in_graph_per_super_step(cfg, net, table: ShardingTable, k: int,
                                 state_template=None,
                                 layout: str = "replicated"):
    """The device-PER super-step (sample → gather → step → priority
    scatter inside one dispatch), jitted once with table-driven
    shardings.  Sampling is the global stratified draw regardless of
    layout — under a dp-sharded ring the PER leaves shard with the slabs
    and XLA inserts the cumsum/gather collectives, so over the same
    global ring content a dp-sharded run draws IDENTICAL strata to a
    single-device one (layout is a pure layout choice;
    test_in_graph_per_dp_layout_matches_single_device pins it —
    note block→slab ROUTING does depend on the dp size, so rings filled
    under different dp hold the same blocks in permuted global slots).
    The sampled bundle's batch rows are pinned to dp so
    the forward/backward shards exactly as the host-sampled path's.
    The priorities array is a donated carry, as before.
    """
    from r2d2_tpu.learner.step import make_in_graph_per_super_step_fn

    if state_template is None:
        raise ValueError("pjit_in_graph_per_super_step needs a "
                         "state_template (a TrainState or its "
                         "ShapeDtypeStruct avals) to resolve per-leaf "
                         "shardings from the table — compiling without "
                         "one would silently bypass the table layout")
    _silence_benign_donation_warning()
    _check_batch(cfg, table.mesh)
    st_sh = table.state_shardings(state_template)
    dp_rows = NamedSharding(table.mesh, P("dp"))

    def constrain(ints_t, w_t):
        return (jax.lax.with_sharding_constraint(ints_t, dp_rows),
                jax.lax.with_sharding_constraint(w_t, dp_rows))

    rep = table.replicated()

    def replicate_for_draw(p):
        return jax.lax.with_sharding_constraint(p, rep)

    per = table.per_shardings(layout)
    lh = cfg.learnhealth_interval > 0
    out_sh = (st_sh, per["prios"], table.replicated())
    if lh:
        # the (k, DIAG_SIZE) learnhealth diagnostic rows, replicated
        out_sh = out_sh + (table.replicated(),)
    return jax.jit(
        RETRACES.wrap(
            "learner.in_graph_per_super_step",
            make_in_graph_per_super_step_fn(
                cfg, net, k, constrain=constrain,
                replicate_for_draw=replicate_for_draw, learnhealth=lh)),
        in_shardings=(st_sh, table.ring_shardings(layout), per["prios"],
                      per["seq_meta"], per["first"], table.replicated()),
        out_shardings=out_sh,
        donate_argnums=(0, 2),
    )


def shard_batch(table: ShardingTable,
                batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Host batch → device batch: strip host-only fields, place dp shards
    (the H2D analogue of worker.py:330-342, minus the fields the step
    never needs)."""
    shardings = table.batch_shardings()
    return {k: jax.device_put(batch[k], shardings[k])
            for k in DEVICE_BATCH_KEYS}
